"""TPU batched SPF solver backend.

Drop-in replacement for the CPU oracle: inherits the entire route-assembly
pipeline from SpfSolver and overrides the SPF access seam so that distances
and ECMP nexthop sets come from one batched min-plus solve on device
(openr_tpu.ops.spf) instead of per-source Dijkstra runs.

Per (area, topology-version, node) the solver compiles the LinkState to
padded arrays and solves for sources = {me} ∪ neighbors(me) in a single
device call — exactly the rows the route pipeline consumes:
  - reachability/metric from me (best-announcer selection, min-cost nodes)
  - dist(neighbor, t) for the triangle-condition ECMP nexthops and for the
    RFC 5286 LFA inequality
Nexthop sets are materialized lazily per queried destination via the triangle
condition w(me,n) + D[n,t] == D[me,t], which reproduces Dijkstra's
nexthop-union semantics (LinkState.cpp:855-871) without tracing paths.

KSP (k-edge-disjoint shortest paths) is fused on device as well: the
reference's per-destination penalized Dijkstra re-runs
(LinkState::getKthPaths link-ignore re-solve, LinkState.cpp:760-789) become
extra batch rows of one per-row-weights solve (ignored links ≙ INF weights),
so one device call covers every destination's k-th solve; only the cheap
greedy edge-disjoint back-trace (traceOnePath, LinkState.cpp:398-419) runs
host-side, reconstructed from the distance rows with exactly Dijkstra's
path-link ordering (settle order = (metric, name); links in per-node sorted
order).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from openr_tpu.lsdb.link_state import Link, LinkState, Path
from openr_tpu.ops.graph import (
    INF,
    CompiledGraph,
    _next_bucket,
    compile_graph,
    refresh_graph,
)
from openr_tpu.ops.spf import (
    batched_spf,
    batched_spf_vw,
    compile_cache_memory,
    compile_cache_stats,
    sell_fixpoint_masked,
)
from openr_tpu.monitor.memledger import get_ledger
from openr_tpu.solver.cpu import Metric, SpfSolver
from openr_tpu.solver.flight_recorder import NULL_CLOCK, SolveTrace
from openr_tpu.testing.faults import fault_point


class DeviceCapacityError(RuntimeError):
    """Predicted RESOURCE_EXHAUSTED: the memory ledger's capacity model
    says the chosen layout cannot fit current headroom. Raised BEFORE the
    device dispatch so the supervisor classifies it as `device_oom` and
    walks the degrade ladder (smaller mesh -> CPU oracle) instead of the
    allocator dying mid-solve."""


# fixed per-bucket patch width for the fused patch+solve executable; events
# changing more slots per bucket fall back to standalone scatters
_PATCH_SLOTS = 64

# DeltaPath extraction cutoff: when more than this fraction of the
# destination columns changed, the full [S, n_pad] mirror is the cheaper
# copy-back and the event is served as a full rebuild instead
_DELTA_MAX_FRAC = 0.5


class _NodeView:
    """NodeSpfResult-compatible view over the device distance matrix."""

    __slots__ = ("metric", "_result", "_dest")

    def __init__(self, metric: Metric, result: "_TpuSpfResult", dest: str):
        self.metric = metric
        self._result = result
        self._dest = dest

    @property
    def next_hops(self) -> Set[str]:
        return self._result.next_hops_of(self._dest)


class _TpuSpfResult:
    """SpfResult-compatible mapping dest -> _NodeView, backed by D rows."""

    def __init__(self, area: "_AreaSolve", source: str):
        self._area = area
        self._source = source
        self._src_row = area.row_map[source]
        self._nh_cache: Dict[str, Set[str]] = {}

    def __contains__(self, dest: str) -> bool:
        col = self._area.graph.node_index.get(dest)
        if col is None:
            return False
        return self._area.d[self._src_row, col] < INF

    def get(self, dest: str) -> Optional[_NodeView]:
        col = self._area.graph.node_index.get(dest)
        if col is None:
            return None
        metric = int(self._area.d[self._src_row, col])
        if metric >= INF:
            return None
        return _NodeView(metric, self, dest)

    def __getitem__(self, dest: str) -> _NodeView:
        view = self.get(dest)
        if view is None:
            raise KeyError(dest)
        return view

    def next_hops_of(self, dest: str) -> Set[str]:
        """ECMP nexthop node set for source -> dest via triangle condition.

        The batch only solves nexthop sets from the primary node's
        perspective (neighbor rows for other sources are not in it). For
        any other source the resident all-pairs matrix answers instead —
        the same triangle against APSP rows (docs/Apsp.md); without one,
        fail fast rather than serve a silent partial answer (the route
        pipeline only reads nexthop sets from my_node_name's perspective).
        """
        if self._source != self._area.sources[0]:
            cached = self._nh_cache.get(dest)
            if cached is not None:
                return cached
            if self._area.ensure_apsp():
                nhs = _ApspSpfResult(
                    self._area, self._source
                ).next_hops_of(dest)
                self._nh_cache[dest] = nhs
                return nhs
            raise RuntimeError(
                f"nexthop sets are only solved for {self._area.sources[0]}, "
                f"requested for {self._source}"
            )
        cached = self._nh_cache.get(dest)
        if cached is not None:
            return cached
        area = self._area
        nhs: Set[str] = set()
        if dest != self._source:
            col = area.graph.node_index.get(dest)
            if col is not None:
                names, mask = area.nh_mask()
                nhs = {n for n, hit in zip(names, mask[:, col]) if hit}
        self._nh_cache[dest] = nhs
        return nhs


class _ApspSpfResult:
    """SpfResult-compatible view for a source OUTSIDE the solved batch,
    backed by the area's resident all-pairs matrix (docs/Apsp.md).

    Metrics read the source's APSP row; nexthop sets fall out of the same
    triangle condition the batch path uses — w(s, n) + D[n, t] == D[s, t]
    over s's ordered up-links, with overloaded neighbors valid only as
    final destinations — but against ALT-NEIGHBOR ROWS of the one resident
    matrix instead of a per-source Dijkstra column solve (the CPU-oracle
    fallback this replaces)."""

    def __init__(self, area: "_AreaSolve", source: str):
        self._area = area
        self._source = source
        self._src_row = area.graph.node_index[source]
        self._nh_cache: Dict[str, Set[str]] = {}

    def __contains__(self, dest: str) -> bool:
        col = self._area.graph.node_index.get(dest)
        if col is None:
            return False
        return self._area.apsp.d[self._src_row, col] < INF

    def get(self, dest: str) -> Optional[_NodeView]:
        col = self._area.graph.node_index.get(dest)
        if col is None:
            return None
        metric = int(self._area.apsp.d[self._src_row, col])
        if metric >= INF:
            return None
        return _NodeView(metric, self, dest)

    def __getitem__(self, dest: str) -> _NodeView:
        view = self.get(dest)
        if view is None:
            raise KeyError(dest)
        return view

    def next_hops_of(self, dest: str) -> Set[str]:
        cached = self._nh_cache.get(dest)
        if cached is not None:
            return cached
        nhs: Set[str] = set()
        area = self._area
        idx = area.graph.node_index
        col = idx.get(dest)
        d = area.apsp.d
        if (
            dest != self._source
            and col is not None
            and d[self._src_row, col] < INF
        ):
            ls = area.link_state
            for link in ls.ordered_links_from_node(self._source):
                if not link.is_up():
                    continue
                n = link.other_node_name(self._source)
                ni = idx.get(n)
                if ni is None:
                    continue
                # an overloaded neighbor relays nothing: valid only when
                # it is itself the destination (nh_mask semantics)
                if ls.is_node_overloaded(n) and n != dest:
                    continue
                w = link.metric_from_node(self._source)
                if w + int(d[ni, col]) == int(d[self._src_row, col]):
                    nhs.add(n)
        self._nh_cache[dest] = nhs
        return nhs


class _AreaSolve:
    """One batched device solve: sources = [me] + up-neighbors(me).

    Incremental event path: on topology change, `refresh()` patches the
    compiled arrays via the LinkState changelog (weight-only changes keep
    shapes and jit executables) and re-runs the device solve. The source
    batch is bucket-padded so a changed neighbor count stays in the same
    executable too.

    The distance matrix stays DEVICE-RESIDENT between events: host readers
    go through the lazy `d` mirror, and a weight-patch event feeds the
    previous fixpoint back in as the warm initial state (decrease-only
    events directly; events with weight increases first invalidate the
    entries whose old shortest path witnesses a changed edge — see
    ops.spf._sell_solver_warm). A cold solve is forced by a structural
    rebuild, a source-batch change, an overload-mask change, or a
    _PATCH_SLOTS overflow."""

    def __init__(
        self,
        link_state: LinkState,
        me: str,
        mesh=None,
        warm_start: bool = True,
        apsp_max_nodes: int = 0,
        apsp_audit_interval: int = 0,
        apsp_dispatch=None,
        recorder=None,
        on_capacity_refusal=None,
    ) -> None:
        self.link_state = link_state
        self.me = me
        # device-memory ledger (monitor/memledger.py): every persistent
        # buffer this solve uploads registers under this area tag and is
        # released by close() — the exact-accounting observatory surface
        self._ledger = get_ledger()
        self._mem_area = f"{link_state.area}/{me}"
        self._mem: Dict[str, int] = {}
        self._on_capacity_refusal = on_capacity_refusal
        # flight recorder (solver/flight_recorder.py): every solve emits a
        # SolveTrace into the bounded per-area ring; every Nth solve gets
        # a live PhaseClock whose seams barrier at phase boundaries. The
        # unsampled path sees only NULL_CLOCK attribute checks.
        self._recorder = recorder
        self._pclock = NULL_CLOCK
        self._last_trace: Optional[SolveTrace] = None
        # jax.sharding.Mesh or None: when set, the source batch is sharded
        # over the mesh 'batch' axis and the persistent layout buffers are
        # replicated across devices — same executables, multi-chip spread
        self.mesh = mesh
        self.warm_start = warm_start
        self.graph: CompiledGraph = compile_graph(link_state)
        # resident all-pairs matrix (docs/Apsp.md): lazily closed on first
        # consumer read, warm-re-closed per weight event, poisoned with the
        # batch warm state; None when the apsp knob is off
        self.apsp = None
        if apsp_max_nodes > 0:
            from openr_tpu.apsp import ApspState

            self.apsp = ApspState(
                apsp_max_nodes,
                dispatch=apsp_dispatch,
                audit_interval=apsp_audit_interval,
                warm=warm_start,
                area=self._mem_area,
                on_refusal=self._note_capacity_refusal,
            )
        self.device_solves = 0
        self.ksp_device_batches = 0
        self.ksp_warm_batches = 0  # penalized batches seeded from the base
        # convergence observability (decision.spf.* counters)
        self.incremental_solves = 0  # warm-started weight-patch solves
        self.full_solves = 0  # cold solves (from D0 = INF)
        self.rounds_last: Optional[int] = None  # relax rounds of last solve
        # boolean invalidation-mark fixpoint rounds of the last WARM solve
        # (None until one runs; 0 for decrease-only events)
        self.invalidation_rounds_last: Optional[int] = None
        # profiling (decision.spf.* histograms/gauges): wall time of the
        # last solve dispatch + whether it rode the warm path, and the
        # host<->device traffic this solve has generated — the warm event
        # path's whole point is shrinking both, so they are measured in the
        # serving path, not offline
        self.solve_ms_last: Optional[float] = None
        self.last_solve_warm = False
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        # halo-exchange accounting (the 2-D tiled layout's cross-chip
        # traffic): ring-rotation count of the last solve and cumulative
        # frontier bytes moved between chips — the destination-sharded
        # analog of the d2h/h2d counters (docs/Monitoring.md)
        self.halo_bytes = 0
        self.halo_exchanges_last: Optional[int] = None
        self._halo_synced = 0
        # DeltaPath (device-side route-delta extraction) accounting: the
        # changed-destination columns and copy-back bytes of extraction
        # dispatches — d2h_bytes grows by delta_bytes on the delta path and
        # by the full [S, n_pad] mirror on the cold/audit path, which is
        # how the two are told apart in tests and dashboards
        self.delta_extracts = 0
        self.delta_columns = 0
        self.delta_bytes = 0
        self.delta_extract_ms_last: Optional[float] = None
        # changed destination columns accumulated for the route-delta
        # consumer (take_route_delta); None = poisoned: some solve since
        # the last take had no device delta, the consumer must full-rebuild
        self._delta_pending: Optional[set] = set()
        self._last_solve_delta: Optional[np.ndarray] = None
        # _sync_spf_counters bookmarks (bytes already folded into counters)
        self._h2d_synced = 0
        self._d2h_synced = 0
        self._delta_cols_synced = 0
        self._delta_bytes_synced = 0
        self._delta_extracts_synced = 0
        self._ksp_warm_synced = 0
        # persistent device buffers (SURVEY.md §7: the <100ms convergence
        # budget leaves no room to re-upload the LSDB per event): sell
        # nbr/wg/overloaded live on device across events; weight patches
        # upload only the changed slots
        self._dev: Optional[dict] = None
        # device-resident distance matrix [s_pad, n_pad] + lazy host mirror
        self._d_dev = None
        self._d_host: Optional[np.ndarray] = None
        self._solve()

    @property
    def d(self) -> np.ndarray:
        """Host mirror of the device-resident distance matrix, fetched on
        first access after each solve — chained events that are never read
        host-side (or only read late) skip the [S, n_pad] copy-back.

        An OWNED copy, not np.asarray: on the CPU backend asarray can be a
        zero-copy view of the device buffer, and the warm solver donates
        that buffer to the next event — a view would alias reused memory."""
        if self._d_host is None:
            t0 = time.perf_counter()
            self._d_host = np.array(self._d_dev)
            self.d2h_bytes += self._d_host.nbytes
            trace = self._last_trace
            if trace is not None and trace.sampled:
                # the lazy mirror fetch is this solve's d2h phase; it
                # lands after the trace was recorded, so attribute it
                # post-hoc (the ring holds the live object) and queue the
                # histogram sample for the next counter sync
                ms = (time.perf_counter() - t0) * 1e3
                trace.phases["d2h"] = trace.phases.get("d2h", 0.0) + ms
                trace.d2h_bytes += self._d_host.nbytes
                if self._recorder is not None:
                    self._recorder.observe_phase("d2h", ms)
            self._mem_register(
                "mirror", "host", arrays=(self._d_host,)
            )
        return self._d_host

    # -- device-memory ledger seams (monitor/memledger.py) -------------

    def _mem_register(
        self, structure: str, layout: str, arrays=(), nbytes=None
    ) -> None:
        """Ledger register seam: (re-)register one named resident
        structure under this area, releasing the previous generation
        first — a structural rebuild frees the old buffers when the new
        upload replaces them, so live_bytes tracks what is actually
        reachable on device."""
        self._ledger.release(self._mem.pop(structure, None))
        self._mem[structure] = self._ledger.register(
            self._mem_area,
            structure,
            layout=layout,
            arrays=arrays,
            nbytes=nbytes,
        )

    def _mem_release(self, structure: str) -> None:
        """Ledger release seam for one named structure."""
        self._ledger.release(self._mem.pop(structure, None))

    def close(self) -> None:
        """Area teardown: release every ledger-registered structure (the
        resident distance matrix, layout buffers, patch slots, mirrors)
        and the APSP state. Called when the owning TpuSpfSolver drops or
        replaces this solve (invalidation, mesh degradation, LinkState
        replacement)."""
        if self.apsp is not None:
            self.apsp.close()
        for structure in list(self._mem):
            self._mem_release(structure)

    def _note_capacity_refusal(self, verdict: Dict) -> None:
        """Record + propagate a headroom-gated admission refusal up to
        the owning solver (surfaced as SOLVER_CAPACITY_REFUSED)."""
        if self._on_capacity_refusal is not None:
            self._on_capacity_refusal(verdict)

    def _admit_layout(self, layout: str) -> None:
        """Predictive capacity admission: before the first dispatch of a
        layout, ask the ledger's forward model whether it fits current
        headroom. No capacity source (the CPU tier-1 backend) -> no
        verdict -> admit; a definite no-fit raises DeviceCapacityError so
        the supervisor degrades (device_oom ladder) BEFORE the allocator
        raises RESOURCE_EXHAUSTED mid-solve."""
        verdict = self._ledger.predict_fit(
            self.graph.n,
            layout,
            n_sources=len(getattr(self, "sources", ())) or 1,
            graph=self.graph,
            mesh_shape=(
                (self.mesh.shape["batch"], self.mesh.shape["graph"])
                if self.mesh is not None
                else None
            ),
        )
        if verdict["fits"] is False:
            self._ledger.record_refusal(verdict)
            self._note_capacity_refusal(verdict)
            raise DeviceCapacityError(
                f"predicted RESOURCE_EXHAUSTED: layout {layout} for area "
                f"{self._mem_area} needs {verdict['predicted_bytes']} bytes, "
                f"headroom {verdict['headroom_bytes']} "
                f"(capacity {verdict['capacity_bytes']}, "
                f"source {verdict['source']})"
            )

    def _batch_pad(self, n: int, minimum: int = 8) -> int:
        """Source-batch pad: power-of-two bucket, rounded up to a multiple
        of the mesh batch-axis size so GSPMD splits rows evenly."""
        s_pad = _next_bucket(n, minimum=minimum)
        if self.mesh is not None:
            b = self.mesh.shape["batch"]
            s_pad += (-s_pad) % b
        return s_pad

    def _replicated(self, x):
        """Device placement for a persistent layout buffer: plain asarray
        single-device, explicitly replicated under a mesh (committed, so
        every sharded solve reuses it without per-call resharding)."""
        import jax
        import jax.numpy as jnp

        if self.mesh is None:
            return jnp.asarray(x)
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(jnp.asarray(x), NamedSharding(self.mesh, P()))

    def _solve(self) -> None:
        # named fault seam: the supervisor's error-classification/breaker
        # tests inject compile/runtime/device-loss faults here, exactly
        # where a real XLA dispatch would raise
        fault_point("solver.tpu.solve", self)
        me = self.me
        neighbors = sorted(
            {
                link.other_node_name(me)
                for link in self.link_state.links_from_node(me)
                if link.is_up()
            }
        )
        self.sources: List[str] = [me] + neighbors
        self.row_map: Dict[str, int] = {
            name: i for i, name in enumerate(self.sources)
        }
        rows = np.array(
            [self.graph.node_index[s] for s in self.sources], dtype=np.int32
        )
        s_pad = self._batch_pad(len(rows), minimum=8)
        rows = np.concatenate(
            [rows, np.full(s_pad - len(rows), rows[0], dtype=np.int32)]
        )
        # one device call for the whole batch; results stay device-resident
        # (the host mirror is fetched lazily through the `d` property).
        # Timing covers patch build + dispatch; on the sliced-ELL paths the
        # scalar `rounds` output forces completion of the same computation,
        # so the measured wall time includes device execution there.
        inc_before = self.incremental_solves
        self._last_solve_delta = None  # set by a qualifying resident solve
        rec = self._recorder
        pc = self._pclock = rec.begin() if rec is not None else NULL_CLOCK
        h2d0, d2h0, halo0 = self.h2d_bytes, self.d2h_bytes, self.halo_bytes
        misses0 = compile_cache_stats()["misses"] if rec is not None else 0
        t0 = time.perf_counter()
        self.h2d_bytes += rows.nbytes
        pc.seam("prepare")
        if self._use_tiled():
            self._admit_layout("tile2d")
            self._d_dev, self.rounds_last = self._tile_solve_resident(rows)
        elif self.graph.sell is not None:
            self._admit_layout("sell")
            self._d_dev, self.rounds_last = self._sell_solve_resident(rows)
        elif self.mesh is not None:
            from openr_tpu.parallel import sharded_batched_spf

            self._admit_layout("replicated")
            self._d_dev = sharded_batched_spf(self.graph, rows, self.mesh)
            self.rounds_last = None  # edge-list form: rounds untracked
            self.full_solves += 1
            pc.seam("relax", self._d_dev)
        else:
            self._admit_layout("bf")
            self._d_dev, self.rounds_last = self._bf_solve_resident(rows)
        self._mem_register(
            "dist",
            (self._dev or {}).get("kind", "none"),
            arrays=(self._d_dev,),
        )
        self.solve_ms_last = (time.perf_counter() - t0) * 1e3
        self.last_solve_warm = self.incremental_solves > inc_before
        self.device_solves += 1
        if self._last_solve_delta is None:
            # cold or non-qualifying event: the host mirrors are stale and
            # the accumulated delta cannot describe the event — poison it
            # until the consumer takes it (and full-rebuilds)
            self._d_host = None
            self._mem_release("mirror")
            self._nh_links = None
            self._nh_mask = None
            self._delta_pending = None
        elif self._delta_pending is not None:
            # qualifying event: mirrors were patched in place during
            # extraction, the changed columns accumulate for the consumer
            self._delta_pending.update(int(c) for c in self._last_solve_delta)
        # KSP: (dest, k) -> traced edge-disjoint path set for src == me;
        # reset with the snapshot, so topology changes invalidate it for free
        self._ksp: Dict[Tuple[str, int], List[Path]] = {}
        # APSP staleness guard (docs/Apsp.md): any event that poisons the
        # batch warm solve — cold start, patch overflow, structural
        # rebuild, overload change — also invalidates the resident
        # all-pairs matrix, so a consumer can never read distances the
        # event classes above moved out from under it. Warm events leave
        # it resident; its own ensure() re-closes the touched blocks.
        if self.apsp is not None and not self.last_solve_warm:
            self.apsp.invalidate("batch_warm_poisoned")
        if rec is not None:
            kind = (self._dev or {}).get("kind") or (
                "replicated" if self.mesh is not None else "none"
            )
            self._last_trace = SolveTrace(
                seq=rec.next_seq(),
                ts=time.time(),
                area=self.link_state.area,
                node=self.me,
                event="solve",
                layout=kind,
                warm=self.last_solve_warm,
                solve_ms=self.solve_ms_last,
                rounds=self.rounds_last,
                invalidation_rounds=(
                    self.invalidation_rounds_last
                    if self.last_solve_warm
                    else None
                ),
                halo_exchanges=(
                    self.halo_exchanges_last if kind == "tile2d" else None
                ),
                h2d_bytes=self.h2d_bytes - h2d0,
                d2h_bytes=self.d2h_bytes - d2h0,
                halo_bytes=self.halo_bytes - halo0,
                delta_columns=(
                    len(self._last_solve_delta)
                    if self._last_solve_delta is not None
                    else None
                ),
                compile_cache_misses=(
                    compile_cache_stats()["misses"] - misses0
                ),
                breaker_state=rec.breaker_state,
                sampled=pc.sampled,
                phases=dict(pc.phases),
            )
            rec.record(self._last_trace, pc)
        # corruption seam (ctx = this solve): the warm-state audit tests
        # perturb the resident D here to prove divergence detection works
        fault_point("solver.tpu.warm_d", self)

    def ensure_apsp(self) -> bool:
        """Bring the resident all-pairs matrix current with this solve's
        graph snapshot; False when APSP is off or the area exceeds the
        node cap (consumers fall back to their column-solve paths)."""
        if self.apsp is None:
            return False
        return self.apsp.ensure(self.graph)

    def _use_tiled(self) -> bool:
        """The destination-tiled P('batch', 'graph') layout serves whenever
        the mesh has a real graph axis and it divides the padded node
        count (both are powers of two in practice). A graph axis of one
        has nothing to tile — the row-sharded replica layouts keep it."""
        return (
            self.mesh is not None
            and self.mesh.shape["graph"] > 1
            and self.graph.n_pad % self.mesh.shape["graph"] == 0
        )

    def _graph_sharded(self, x):
        """Device placement for a per-partition tiled buffer: leading dim
        split over the mesh 'graph' axis, replicated over 'batch'."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(
            jnp.asarray(x), NamedSharding(self.mesh, P("graph", None))
        )

    def _account_halo(self, exchanges: int) -> None:
        """Fold one tiled solve's ring traffic into the halo counters:
        `exchanges` ppermute rotations ran, and per rotation every device
        forwarded its compact frontier (ctr [S_l, h] int32) plus the
        slot->column map ([h] int32)."""
        tiling = self._dev["tiling"]
        b = self.mesh.shape["batch"]
        g = self.mesh.shape["graph"]
        s_l = max(len(self._dev["rows"]) // max(b, 1), 1)
        payload = (s_l * tiling.h + tiling.h) * 4
        self.halo_exchanges_last = exchanges
        self.halo_bytes += exchanges * b * g * payload

    def _tile_solve_resident(self, rows: np.ndarray):
        """Destination-tiled solve against persistent device buffers;
        returns (device distance matrix [s_pad, n_pad] sharded
        P('batch', 'graph'), relaxation rounds).

        Persistent state per device is a [s_pad/batch, n_pad/graph] tile
        plus its partition's slice of the tiled edge arrays — no chip holds
        the full destination axis. The warm event path uploads the whole
        [g, e_tile] tiled weight array (the layout's native patch unit,
        like the edge-list form) and lets the device classify increases
        against the resident copy; overload toggles ride the same warm
        invalidation (newly-overloaded out-edges become seed edges, the
        repair relax uses the new transit mask), so only structural
        rebuilds and source-batch changes force a cold solve."""
        import jax.numpy as jnp

        from openr_tpu.ops.spf import _tile_solver, _tile_solver_warm
        from openr_tpu.parallel import tile_graph

        g = self.graph
        g_ax = self.mesh.shape["graph"]
        st = self._dev
        if (
            st is None
            or st.get("kind") != "tile2d"
            or st["src_ref"] is not g.src
        ):
            tiling = tile_graph(g, g_ax)
            st = self._dev = {
                "kind": "tile2d",
                "src_ref": g.src,
                "tiling": tiling,
                "src_l": self._graph_sharded(tiling.src_l),
                "hseg": self._graph_sharded(tiling.hseg),
                "w2": self._graph_sharded(tiling.tile_weights(g.w)),
                "hcols": self._graph_sharded(tiling.hcols),
                "ov": self._replicated(g.overloaded),
                "w_host": g.w.copy(),
                "w_ver": g.version,
                "ov_host": g.overloaded.copy(),
                "rows": np.array(rows),
            }
            self.h2d_bytes += (
                tiling.src_l.nbytes
                + tiling.hseg.nbytes
                + tiling.w.nbytes
                + tiling.hcols.nbytes
                + g.overloaded.nbytes
            )
            self._mem_register(
                "tile",
                "tile2d",
                arrays=(st["src_l"], st["hseg"], st["w2"], st["ov"]),
            )
            self._mem_register("halo", "tile2d", arrays=(st["hcols"],))
        else:
            tiling = st["tiling"]
            ov_changed = not np.array_equal(st["ov_host"], g.overloaded)
            rows_same = np.array_equal(st["rows"], rows)
            st["rows"] = np.array(rows)
            if (
                g.changed_edges is not None
                and g.parent_version == st.get("w_ver")
            ):
                cand = g.changed_edges
                changed = cand[st["w_host"][cand] != g.w[cand]]
            else:
                changed = np.nonzero(st["w_host"][: g.e] != g.w[: g.e])[0]
            st["w_ver"] = g.version
            if (
                self.warm_start
                and rows_same
                and (len(changed) or ov_changed)
                and self._d_dev is not None
            ):
                w2_new = self._graph_sharded(tiling.tile_weights(g.w))
                self.h2d_bytes += tiling.w.nbytes
                ov_new = st["ov"]
                if ov_changed:
                    ov_new = self._replicated(g.overloaded)
                    self.h2d_bytes += g.overloaded.nbytes
                # DeltaPath qualification: same contract as the other
                # layouts — my own out-link metrics and the transit mask
                # feed the route build outside D, so events touching
                # either cannot be described by changed columns alone
                delta_ok = not ov_changed and not np.any(
                    g.src[changed] == rows[0]
                )
                fn = _tile_solver_warm(
                    tiling.shape_key() + (g.n_pad,), self.mesh
                )
                self._pclock.seam("h2d", w2_new, ov_new)
                d, rounds, inv_rounds, col_changed, num_changed = fn(
                    jnp.asarray(rows, dtype=jnp.int32),
                    st["src_l"],
                    st["hseg"],
                    w2_new,
                    st["w2"],
                    st["hcols"],
                    ov_new,
                    st["ov"],
                    self._d_dev,
                )
                st["w2"] = w2_new
                st["w_host"] = g.w.copy()
                st["ov"] = ov_new
                st["ov_host"] = g.overloaded.copy()
                self.incremental_solves += 1
                self.invalidation_rounds_last = int(inv_rounds)
                rounds = int(rounds)
                self._pclock.seam("relax", d)
                # seed exchange + one ring per invalidation and relax round
                self._account_halo(
                    (g_ax - 1) * (1 + int(inv_rounds) + rounds)
                )
                self._finish_delta(col_changed, num_changed, d, delta_ok)
                return d, rounds
            if len(changed):
                st["w2"] = self._graph_sharded(tiling.tile_weights(g.w))
                st["w_host"] = g.w.copy()
                self.h2d_bytes += tiling.w.nbytes
            if ov_changed:
                st["ov"] = self._replicated(g.overloaded)
                st["ov_host"] = g.overloaded.copy()
                self.h2d_bytes += g.overloaded.nbytes

        fn = _tile_solver(st["tiling"].shape_key() + (g.n_pad,), self.mesh)
        self._pclock.seam("h2d", st["w2"], st["ov"])
        d, rounds = fn(
            jnp.asarray(rows, dtype=jnp.int32),
            st["src_l"],
            st["hseg"],
            st["w2"],
            st["hcols"],
            st["ov"],
        )
        self.full_solves += 1
        rounds = int(rounds)
        self._pclock.seam("relax", d)
        self._account_halo((g_ax - 1) * rounds)
        return d, rounds

    def _sell_solve_resident(self, rows: np.ndarray):
        """Sliced-ELL solve against persistent device buffers; returns
        (device distance matrix [s_pad, n_pad], relaxation rounds).

        The first call (or any structural rebuild, detected by src array
        identity) uploads the full layout; subsequent events diff the host
        weight/overload arrays against the device snapshot and upload only
        the changed slots (`.at[].set` with tiny index arrays) — a link
        flap moves a handful of ints over the host-device link instead of
        the whole LSDB. When the event is a pure weight patch (same source
        batch, same overload mask, fits _PATCH_SLOTS), the previous
        device-resident distances warm-start the fixpoint instead of
        re-relaxing from INF."""
        import jax.numpy as jnp

        from openr_tpu.ops.spf import (
            _sell_solver_counted,
            _sell_solver_patched,
            _sell_solver_warm,
        )

        g = self.graph
        sell = g.sell
        st = self._dev
        if st is None or st.get("kind") != "sell" or st["src_ref"] is not g.src:
            st = self._dev = {
                "kind": "sell",
                "src_ref": g.src,
                "nbrs": tuple(self._replicated(a) for a in sell.nbr),
                "wgs": tuple(self._replicated(a) for a in sell.wg),
                "ov": self._replicated(g.overloaded),
                "w_host": g.w.copy(),
                "w_ver": g.version,
                "ov_host": g.overloaded.copy(),
                "rows": np.array(rows),
            }
            self.h2d_bytes += (
                sum(a.nbytes for a in sell.nbr)
                + sum(a.nbytes for a in sell.wg)
                + g.overloaded.nbytes
            )
            self._mem_register(
                "sell",
                "sell",
                arrays=(*st["nbrs"], *st["wgs"], st["ov"]),
            )
            # fixed-capacity weight-patch slots (rowcol [nb,64,2] + vals
            # [nb,64], int32): allocated fresh per patched event but the
            # capacity is layout-constant, so the ledger carries it as one
            # resident-equivalent entry per sell generation
            self._mem_register(
                "patch",
                "sell",
                nbytes=len(sell.nbr) * _PATCH_SLOTS * 3 * 4,
            )
        else:
            ov_changed = not np.array_equal(st["ov_host"], g.overloaded)
            ov_seed_edges = np.empty(0, dtype=np.int64)
            if ov_changed:
                # an overload toggle is a transit-mask change, but it is
                # expressible as weight increases on the node's incident
                # edges: for every other source, a newly-overloaded node's
                # out-edges just rose to INF, so exactly the entries whose
                # old shortest path witnesses one of those edges must be
                # invalidated — the same seed shape as a metric increase.
                # Un-overloading only ADDS paths (the old D stays an upper
                # bound) and warm-starts as-is. Either way the toggle rides
                # the existing warm invalidation path instead of forcing a
                # cold solve (ROADMAP open item).
                newly_on = np.nonzero(g.overloaded & ~st["ov_host"])[0]
                if len(newly_on):
                    ov_seed_edges = np.nonzero(
                        np.isin(g.src[: g.e], newly_on)
                    )[0]
                    # down edges (old weight INF) are never on the old DAG
                    ov_seed_edges = ov_seed_edges[
                        st["w_host"][ov_seed_edges] < INF
                    ]
                st["ov"] = self._replicated(g.overloaded)
                st["ov_host"] = g.overloaded.copy()
                self.h2d_bytes += g.overloaded.nbytes
            # warm start needs the previous fixpoint to describe the same
            # problem modulo edge weights: identical source batch (a flap
            # adjacent to me changes the rows); transit-mask changes are
            # folded into the invalidation seeds above
            rows_same = np.array_equal(st["rows"], rows)
            st["rows"] = np.array(rows)
            if (
                g.changed_edges is not None
                and g.parent_version == st.get("w_ver")
            ):
                # refresh provenance matches our snapshot: diff only the
                # positions the changelog touched instead of all of w
                cand = g.changed_edges
                changed = cand[st["w_host"][cand] != g.w[cand]]
            else:
                changed = np.nonzero(st["w_host"][: g.e] != g.w[: g.e])[0]
            st["w_ver"] = g.version  # snapshot is current even if no diff
            if len(changed) or ov_changed:
                # classify vs the weights that produced the resident D —
                # increases invalidate, decreases warm-start as-is
                increased = changed[g.w[changed] > st["w_host"][changed]]
                st["w_host"][changed] = g.w[changed]
                # invalidation seed set: weight increases plus the
                # out-edges of newly-overloaded nodes (duplicates are
                # harmless — seeding is an idempotent boolean max)
                inc_edges = (
                    np.concatenate([increased, ov_seed_edges])
                    if len(ov_seed_edges)
                    else increased
                )
                # fused patch+solve: one dispatch carries the changed slots
                # and returns the distances plus the patched buffers, which
                # stay device-resident for the next event. The patch shape
                # is FIXED (_PATCH_SLOTS per bucket) so every event shares
                # one executable — a varying pad would recompile the whole
                # fixpoint per new event size. Oversized events (SRLG-style
                # bulk changes) fall back to standalone scatters + plain
                # solve, whose small ops are cheap to compile per shape.
                nb = len(sell.nbr)
                per_bucket = [
                    changed[sell.edge_bucket[changed] == k]
                    for k in range(nb)
                ]
                fits_inc = all(
                    np.count_nonzero(sell.edge_bucket[inc_edges] == k)
                    <= _PATCH_SLOTS
                    for k in range(nb)
                )
                if all(len(s_) <= _PATCH_SLOTS for s_ in per_bucket):
                    idx = np.full(
                        (nb, _PATCH_SLOTS, 2), 1 << 30, dtype=np.int32
                    )
                    vals = np.zeros((nb, _PATCH_SLOTS), dtype=np.int32)
                    for k, sel in enumerate(per_bucket):
                        if len(sel):
                            idx[k, : len(sel), 0] = sell.edge_row[sel]
                            idx[k, : len(sel), 1] = sell.edge_slot[sel]
                            vals[k, : len(sel)] = g.w[sel]
                    args = (
                        jnp.asarray(rows, dtype=jnp.int32),
                        st["nbrs"],
                        st["wgs"],
                        st["ov"],
                        jnp.asarray(idx),
                        jnp.asarray(vals),
                    )
                    self.h2d_bytes += idx.nbytes + vals.nbytes
                    if (
                        self.warm_start
                        and rows_same
                        and fits_inc
                        and self._d_dev is not None
                    ):
                        inc_idx = np.full(
                            (nb, _PATCH_SLOTS, 2), 1 << 30, dtype=np.int32
                        )
                        for k in range(nb):
                            sel = inc_edges[sell.edge_bucket[inc_edges] == k]
                            if len(sel):
                                inc_idx[k, : len(sel), 0] = sell.edge_row[sel]
                                inc_idx[k, : len(sel), 1] = sell.edge_slot[sel]
                        fn = _sell_solver_warm(sell.shape_key(), self.mesh)
                        self.h2d_bytes += inc_idx.nbytes
                        # DeltaPath qualification: the host-visible route
                        # inputs besides D are my own out-link metrics (the
                        # nh_mask triangle w-column) and the transit mask —
                        # an event touching either cannot be described by
                        # changed D columns alone
                        delta_ok = not ov_changed and not np.any(
                            g.src[changed] == rows[0]
                        )
                        self._pclock.seam("h2d", args[4], args[5])
                        (
                            d,
                            new_wgs,
                            rounds,
                            inv_rounds,
                            col_changed,
                            num_changed,
                        ) = fn(*args, jnp.asarray(inc_idx), self._d_dev)
                        st["wgs"] = new_wgs
                        self.incremental_solves += 1
                        self.invalidation_rounds_last = int(inv_rounds)
                        self._pclock.seam("relax", d)
                        self._finish_delta(
                            col_changed, num_changed, d, delta_ok
                        )
                        return d, int(rounds)
                    if len(changed):
                        fn = _sell_solver_patched(sell.shape_key(), self.mesh)
                        self._pclock.seam("h2d", args[4], args[5])
                        d, new_wgs, rounds = fn(*args)
                        st["wgs"] = new_wgs
                        self.full_solves += 1
                        self._pclock.seam("relax", d)
                        return d, int(rounds)
                    # overload-only event with warm start unavailable:
                    # nothing to patch — plain cold solve below
                elif len(changed):
                    wgs = list(st["wgs"])
                    for k, sel in enumerate(per_bucket):
                        if len(sel):
                            wgs[k] = (
                                wgs[k]
                                .at[sell.edge_row[sel], sell.edge_slot[sel]]
                                .set(jnp.asarray(g.w[sel]))
                            )
                            # standalone scatters: row/slot + value uploads
                            self.h2d_bytes += 3 * 4 * len(sel)
                    st["wgs"] = tuple(wgs)

        fn = _sell_solver_counted(sell.shape_key(), self.mesh)
        self._pclock.seam("h2d", st["ov"], *st["wgs"])
        d, rounds = fn(
            jnp.asarray(rows, dtype=jnp.int32),
            st["nbrs"],
            st["wgs"],
            st["ov"],
        )
        self.full_solves += 1
        self._pclock.seam("relax", d)
        return d, int(rounds)

    def _bf_solve_resident(self, rows: np.ndarray):
        """Edge-list (non sliced-ELL) solve against persistent device
        buffers; returns (device distance matrix [s_pad, n_pad], rounds or
        None). The warm event path mirrors the sliced-ELL recipe with the
        layout's native patch unit — the whole [e_pad] weight vector —
        and derives the increased-edge set on device (ops.spf._bf_warm_core),
        so degree profiles that disqualify sliced-ELL no longer force a
        cold solve per event (and no longer silently mask the delta path)."""
        import jax.numpy as jnp

        from openr_tpu.ops.spf import _bf_fixpoint, _bf_solver_warm

        g = self.graph
        st = self._dev
        structural = (
            st is None or st.get("kind") != "bf" or st["src_ref"] is not g.src
        )
        if structural:
            st = self._dev = {
                "kind": "bf",
                "src_ref": g.src,
                "src": self._replicated(g.src),
                "dst": self._replicated(g.dst),
                "w": self._replicated(g.w),
                "ov": self._replicated(g.overloaded),
                "w_host": g.w.copy(),
                "w_ver": g.version,
                "ov_host": g.overloaded.copy(),
                "rows": np.array(rows),
            }
            self.h2d_bytes += (
                g.src.nbytes + g.dst.nbytes + g.w.nbytes + g.overloaded.nbytes
            )
            self._mem_register(
                "bf",
                "bf",
                arrays=(st["src"], st["dst"], st["w"], st["ov"]),
            )
        else:
            ov_changed = not np.array_equal(st["ov_host"], g.overloaded)
            rows_same = np.array_equal(st["rows"], rows)
            st["rows"] = np.array(rows)
            if (
                g.changed_edges is not None
                and g.parent_version == st.get("w_ver")
            ):
                cand = g.changed_edges
                changed = cand[st["w_host"][cand] != g.w[cand]]
            else:
                changed = np.nonzero(st["w_host"][: g.e] != g.w[: g.e])[0]
            st["w_ver"] = g.version
            if ov_changed:
                st["ov"] = self._replicated(g.overloaded)
                st["ov_host"] = g.overloaded.copy()
                self.h2d_bytes += g.overloaded.nbytes
            if (
                self.warm_start
                and rows_same
                and not ov_changed
                and len(changed)
                and self._d_dev is not None
            ):
                # weight-only event: upload the new weight vector and let
                # the device classify increases against the resident copy
                w_new = jnp.asarray(g.w)
                self.h2d_bytes += g.w.nbytes
                delta_ok = not np.any(g.src[changed] == rows[0])
                self._pclock.seam("h2d", w_new)
                d, rounds, inv_rounds, col_changed, num_changed = (
                    _bf_solver_warm(
                        jnp.asarray(rows, dtype=jnp.int32),
                        st["src"],
                        st["dst"],
                        w_new,
                        st["w"],
                        st["ov"],
                        self._d_dev,
                    )
                )
                st["w"] = w_new
                st["w_host"] = g.w.copy()
                self.incremental_solves += 1
                self.invalidation_rounds_last = int(inv_rounds)
                self._pclock.seam("relax", d)
                self._finish_delta(col_changed, num_changed, d, delta_ok)
                return d, int(rounds)
            if len(changed):
                st["w"] = self._replicated(g.w)
                st["w_host"] = g.w.copy()
                self.h2d_bytes += g.w.nbytes

        self._pclock.seam("h2d", st["w"], st["ov"])
        d = _bf_fixpoint(
            jnp.asarray(rows, dtype=jnp.int32),
            st["src"],
            st["dst"],
            st["w"],
            st["ov"],
        )
        self.full_solves += 1
        self._pclock.seam("relax", d)
        return d, None

    def _nh_link_arrays(self):
        """(names, batch rows [L], metrics [L], overloaded flags [L]) of
        my ordered up-links — the nh_mask triangle inputs, shared by the
        host mask build and the device delta extraction."""
        ls = self.link_state
        names: List[str] = []
        rows: List[int] = []
        ws: List[int] = []
        ov: List[bool] = []
        for link in ls.ordered_links_from_node(self.me):
            if not link.is_up():
                continue
            n = link.other_node_name(self.me)
            r = self.row_map.get(n)
            if r is None:
                continue
            names.append(n)
            rows.append(r)
            ws.append(link.metric_from_node(self.me))
            ov.append(ls.is_node_overloaded(n))
        return names, rows, ws, ov

    def _finish_delta(self, col_changed, num_changed, d_dev, delta_ok) -> None:
        """Complete a qualifying warm solve's DeltaPath extraction: read the
        changed-column count (4 bytes), size a compacted `_delta_extract`
        dispatch, and patch the persistent host mirrors (distance matrix +
        nexthop mask) in place. Sets self._last_solve_delta to the changed
        destination columns; leaving it None makes _solve treat the event
        as full (mirrors reset, accumulated delta poisoned)."""
        if not delta_ok:
            return
        num = int(num_changed)
        if num == 0:
            self._last_solve_delta = np.empty(0, dtype=np.int64)
            return
        g = self.graph
        if num > max(_PATCH_SLOTS, int(g.n_pad * _DELTA_MAX_FRAC)):
            return  # full mirror is the cheaper copy-back for bulk events
        import jax.numpy as jnp

        from openr_tpu.ops.spf import _delta_extract

        names, rows_l, ws_l, ov_l = self._nh_link_arrays()
        l_pad = _next_bucket(max(len(rows_l), 1), minimum=8)
        nh_rows = np.zeros(l_pad, dtype=np.int32)
        nh_ws = np.full(l_pad, INF, dtype=np.int32)  # padding never matches
        nh_rows[: len(rows_l)] = rows_l
        nh_ws[: len(ws_l)] = ws_l
        cap = _next_bucket(num, minimum=8)
        t0 = time.perf_counter()
        self.h2d_bytes += nh_rows.nbytes + nh_ws.nbytes
        cols_d, dcols_d, nh_d = _delta_extract(
            col_changed, d_dev, jnp.asarray(nh_rows), jnp.asarray(nh_ws),
            cap=cap,
        )
        cols = np.asarray(cols_d)
        dcols = np.array(dcols_d)
        nh = np.array(nh_d)
        self.delta_extract_ms_last = (time.perf_counter() - t0) * 1e3
        self._pclock.seam("delta_extract")  # host copies above are synced
        xfer = cols.nbytes + dcols.nbytes + nh.nbytes + 4  # + count scalar
        self.d2h_bytes += xfer
        self.delta_bytes += xfer
        self.delta_columns += num
        self.delta_extracts += 1
        valid = cols < g.n_pad
        cols_real = cols[valid].astype(np.int64)
        if self._d_host is not None:
            self._d_host[:, cols_real] = dcols[:, valid]
        if self._nh_mask is not None and self._nh_links == names:
            mask_cols = nh[: len(names)][:, valid]
            for i, (nm, is_ov) in enumerate(zip(names, ov_l)):
                if is_ov:
                    # an overloaded neighbor relays nothing: valid only
                    # when it is itself the destination (nh_mask semantics)
                    mask_cols[i] &= cols_real == g.node_index[nm]
            self._nh_mask[:, cols_real] = mask_cols
        elif self._nh_mask is not None:
            self._nh_mask = None  # up-link set moved: rebuild lazily
            self._nh_links = None
        self._last_solve_delta = cols_real

    def take_route_delta(self) -> Optional[set]:
        """One-shot consumer handshake for the DeltaPath route build: the
        changed destination columns accumulated since the last take (an
        empty set means solves ran but no destination moved, or no solve
        ran), or None when any intervening solve could not produce a
        device delta — the caller must rebuild the full route db, which
        re-arms accumulation."""
        out = self._delta_pending
        self._delta_pending = set()
        return out

    def nh_mask(self) -> Tuple[List[str], np.ndarray]:
        """(neighbor names, [L, n_pad] bool): entry [i, t] is True iff the
        i-th up-link from me is an ECMP first hop toward node t.

        One vectorized triangle-condition broadcast over the solved rows
        (w(me,v) + D[v, t] == D[me, t], LinkState.cpp:855-871 semantics,
        with overloaded neighbors valid only as final destinations) replaces
        the per-destination link loop."""
        if self._nh_mask is None:
            names, rows, ws, ov = self._nh_link_arrays()
            if not names:
                self._nh_links = []
                self._nh_mask = np.zeros(
                    (0, self.graph.n_pad), dtype=bool
                )
                return self._nh_links, self._nh_mask
            w_col = np.asarray(ws, dtype=np.int32)[:, None]
            mask = (w_col + self.d[rows]) == self.d[0][None, :]
            # an overloaded neighbor relays nothing: valid only when it is
            # itself the destination
            for i, (n, is_ov) in enumerate(zip(names, ov)):
                if is_ov:
                    only = np.zeros(self.graph.n_pad, dtype=bool)
                    only[self.graph.node_index[n]] = True
                    mask[i] &= only
            self._nh_links = names
            self._nh_mask = mask
        return self._nh_links, self._nh_mask

    def refresh(self) -> None:
        """Re-solve against the current LinkState snapshot if it moved."""
        if self.graph.version == self.link_state.version:
            return
        self.graph = refresh_graph(self.graph, self.link_state)
        self._solve()

    def cold_reference_d(self) -> np.ndarray:
        """Shadow cold solve from the HOST-side graph truth (the compiled
        arrays kept current by refresh_graph), independent of both the
        persistent device buffers and the resident distance state.

        This is the warm-state audit comparator: a diverged device-resident
        D (bit flip, donation bug, missed patch) differs from this
        recomputation, while an honest warm fixpoint is bit-identical to
        it. Runs off the hot path — no buffers are touched or reused."""
        rows = np.array(
            [self.graph.node_index[s] for s in self.sources], dtype=np.int32
        )
        s_pad = self._batch_pad(len(rows), minimum=8)
        rows = np.concatenate(
            [rows, np.full(s_pad - len(rows), rows[0], dtype=np.int32)]
        )
        cold = np.array(batched_spf(self.graph, rows))
        self.d2h_bytes += cold.nbytes  # audit copy-back, accounted too
        return cold

    # -- KSP (k-edge-disjoint shortest paths), device-batched ------------

    def kth_paths(self, dest: str, k: int) -> List[Path]:
        cached = self._ksp.get((dest, k))
        if cached is None:
            self.prefetch_ksp([dest], k)
            cached = self._ksp[(dest, k)]
        return cached

    def prefetch_ksp(self, dests: List[str], k: int) -> None:
        """Solve + trace the k-th path set for every dest in one device call.

        The reference runs one full penalized Dijkstra per destination
        (LinkState.cpp:777-780); here every destination's penalized solve is
        one batch row of a single per-row-weights fixpoint.
        """
        assert k >= 1
        idx = self.graph.node_index
        todo = [
            d
            for d in dests
            if (d, k) not in self._ksp and d != self.me and d in idx
        ]
        for d in dests:
            if (d, k) not in self._ksp and (d == self.me or d not in idx):
                self._ksp[(d, k)] = []
        if not todo:
            return
        if k == 1:
            # base solve row 0 is me with the unpenalized weights
            for dest in todo:
                self._ksp[(dest, 1)] = _trace_paths(
                    self.link_state, self.graph, self.d[0], self.me, dest, set()
                )
            return
        self.prefetch_ksp(todo, k - 1)

        # per-dest ignore set = links used by path sets 1..k-1
        ignores: List[Set[Link]] = []
        for dest in todo:
            ig: Set[Link] = set()
            for i in range(1, k):
                for path in self._ksp[(dest, i)]:
                    ig.update(path)
            ignores.append(ig)

        # pad the batch axis to a power-of-two bucket so every anycast group
        # size in a bucket shares one jitted executable (same convention as
        # n_pad/e_pad in compile_graph); filler rows re-solve unpenalized
        s_pad = self._batch_pad(len(todo), minimum=1)
        me_row = idx[self.me]
        sources = np.full(s_pad, me_row, dtype=np.int32)
        # warm layer seeding (docs/Apsp.md): the penalized layer-k problem
        # is the base problem plus weight INCREASES (ignored links -> INF),
        # so every batch row warm-starts from the resident base row of me —
        # the same row the all-pairs matrix serves — via the standard
        # increase-invalidation instead of cold-starting from INF. The
        # tiled 2-D layout keeps a different buffer set and the mesh vw
        # solvers shard d0 differently, so both keep the cold path.
        warm_prev = None
        if (
            self.warm_start
            and self.mesh is None
            and self._d_dev is not None
            and self._dev is not None
            and self._dev.get("kind") in ("sell", "bf")
        ):
            import jax.numpy as jnp

            base_row = self._d_dev[0]  # rows[0] is me's unpenalized row
            warm_prev = jnp.broadcast_to(
                base_row[None, :], (s_pad, base_row.shape[0])
            )
        if self.graph.sell is not None:
            # sliced layout: per-row ignores become device-side INF masks —
            # no [S, E] host tile, no bulk upload
            mask_positions: List[List[int]] = []
            for ig in ignores:
                pos: List[int] = []
                for link in ig:
                    fwd, rev = self.graph.link_edges[link]
                    pos.extend((fwd, rev))
                mask_positions.append(pos)
            mask_positions.extend([[] for _ in range(s_pad - len(todo))])
            # persistent buffers, synced by _solve() — only the sliced-ELL
            # resident state carries them (the tiled 2-D layout keeps a
            # different buffer set; KSP re-uploads the sell layout there)
            dev = self._dev
            if dev is not None and dev.get("kind") != "sell":
                dev = None
            d_rows = np.asarray(
                sell_fixpoint_masked(
                    self.graph.sell,
                    sources,
                    self.graph.overloaded,
                    mask_positions,
                    device_arrays=(
                        (dev["nbrs"], dev["wgs"], dev["ov"])
                        if dev is not None and dev.get("kind") == "sell"
                        else None
                    ),
                    mesh=self.mesh,
                    d_prev=(
                        warm_prev
                        if dev is not None and dev.get("kind") == "sell"
                        else None
                    ),
                )
            )
            if (
                warm_prev is not None
                and dev is not None
                and dev.get("kind") == "sell"
            ):
                self.ksp_warm_batches += 1
        elif warm_prev is not None and self._dev.get("kind") == "bf":
            from openr_tpu.ops.spf import _bf_solver_warm_vw

            import jax.numpy as jnp

            w_rows = np.tile(self.graph.w, (s_pad, 1))
            for row, ig in enumerate(ignores):
                for link in ig:
                    fwd, rev = self.graph.link_edges[link]
                    w_rows[row, fwd] = INF
                    w_rows[row, rev] = INF
            st = self._dev
            self._mem_register("ksp", "vw", arrays=(w_rows,))
            fault_point("ops.spf.batched_spf_vw", self.graph)
            d_dev, _rounds, _inv = _bf_solver_warm_vw(
                jnp.asarray(sources, dtype=jnp.int32),
                st["src"],
                st["dst"],
                jnp.asarray(w_rows, dtype=jnp.int32),
                st["w"],
                st["ov"],
                warm_prev,
            )
            d_rows = np.asarray(d_dev)
            self.h2d_bytes += w_rows.nbytes
            self.ksp_warm_batches += 1
        else:
            w_rows = np.tile(self.graph.w, (s_pad, 1))
            for row, ig in enumerate(ignores):
                for link in ig:
                    fwd, rev = self.graph.link_edges[link]
                    w_rows[row, fwd] = INF
                    w_rows[row, rev] = INF
            self._mem_register("ksp", "vw", arrays=(w_rows,))
            d_rows = np.asarray(
                batched_spf_vw(self.graph, sources, w_rows, mesh=self.mesh)
            )
            self.h2d_bytes += w_rows.nbytes
        # the penalized distance rows are consumed host-side by the greedy
        # back-trace — a real copy-back, so it rides the transfer counters
        # like the mirror fetch does; the per-row-weights layer upload is
        # transient, so its ledger entry releases with the batch
        self.d2h_bytes += d_rows.nbytes
        self._mem_release("ksp")
        self.ksp_device_batches += 1

        for row, (dest, ig) in enumerate(zip(todo, ignores)):
            self._ksp[(dest, k)] = _trace_paths(
                self.link_state, self.graph, d_rows[row], self.me, dest, ig
            )


def _trace_paths(
    link_state: LinkState,
    graph: CompiledGraph,
    d_row: np.ndarray,
    src: str,
    dest: str,
    ignore: Set[Link],
) -> List[Path]:
    """Greedy edge-disjoint path enumeration from a single-source distance
    row, byte-for-byte equivalent to tracing the Dijkstra SPF DAG
    (LinkState.cpp:398-419): path links into v are the up, non-ignored links
    from nodes u with d(u) + w(u→v) == d(v) that offer transit, ordered by
    u's settle order (= (d(u), u), valid since metrics ≥ 1) then by u's
    sorted link order."""
    idx = graph.node_index
    dd = d_row.tolist()
    dcol = idx.get(dest)
    if dcol is None or dd[dcol] >= INF:
        return []

    path_links: Dict[str, List[Tuple[Link, str]]] = {}

    def pl(v: str) -> List[Tuple[Link, str]]:
        cached = path_links.get(v)
        if cached is not None:
            return cached
        vi = idx[v]
        out: List[Tuple[Link, str]] = []
        for link in link_state.ordered_links_from_node(v):
            if not link.is_up() or link in ignore:
                continue
            u = link.other_node_name(v)
            ui = idx.get(u)
            if ui is None or dd[ui] >= INF:
                continue
            if u != src and link_state.is_node_overloaded(u):
                continue
            if dd[ui] + link.metric_from_node(u) == dd[vi]:
                out.append((link, u))
        out.sort(key=lambda t: (dd[idx[t[1]]], t[1], t[0]))
        path_links[v] = out
        return out

    visited: Set[Link] = set()

    def trace_one(node: str) -> Optional[Path]:
        if node == src:
            return []
        for link, prev in pl(node):
            if link not in visited:
                visited.add(link)
                sub = trace_one(prev)
                if sub is not None:
                    sub.append(link)
                    return sub
        return None

    paths: List[Path] = []
    path = trace_one(dest)
    while path:
        paths.append(path)
        path = trace_one(dest)
    return paths


class TpuSpfSolver(SpfSolver):
    """SpfSolver with the batched TPU distance backend.

    mesh: None (single device), a jax.sharding.Mesh, or a (batch, graph)
    shape tuple resolved against jax.devices() on first use — the
    DecisionConfig.solver_mesh production knob. Sharding rides entirely
    inside _AreaSolve (sources row-sharded, layout replicated), so the
    meshed solver passes the same parity suite as the single-device one.
    """

    def __init__(
        self,
        *args,
        mesh=None,
        warm_start: bool = True,
        apsp_max_nodes: int = 0,
        apsp_audit_interval: int = 0,
        **kwargs,
    ) -> None:
        super().__init__(*args, **kwargs)
        # (area name, node) -> (LinkState identity, solve); keyed by the
        # stable area name so a replaced LinkState object for the same area
        # overwrites its predecessor instead of leaking it; topology-version
        # tracking lives in _AreaSolve.refresh()
        self._solves: Dict[Tuple[str, str], Tuple[int, _AreaSolve]] = {}
        self.device_solves = 0  # counter: batched device calls
        # device-memory observatory: the process-global ledger plus the
        # compile caches as an informational external source; headroom-
        # gated admission refusals queue here until the supervisor drains
        # them into SOLVER_CAPACITY_REFUSED samples
        self._ledger = get_ledger()
        self._ledger.attach_external("compile_cache", compile_cache_memory)
        self._capacity_refusals: List[Dict] = []
        self.warm_start = warm_start
        # resident APSP matrix knobs (docs/Apsp.md): areas up to this many
        # real nodes keep a blocked-FW all-pairs matrix on device; 0 = off
        self.apsp_max_nodes = apsp_max_nodes
        self.apsp_audit_interval = apsp_audit_interval
        # set by SolverSupervisor.attach_supervisor: APSP closes dispatch
        # through its fault domain (classified errors feed the shared
        # breaker, numpy FW serves as the degraded path)
        self._supervisor = None
        # flight recorder (solver/flight_recorder.py), attached by the
        # supervisor before the first solve; every _AreaSolve records its
        # SolveTraces into it and the phase histograms drain through
        # _sync_spf_counters
        self._recorder = None
        # last-solve timing gauges surfaced by getSolverHealth next to
        # solve_ms_last (docs/Robustness.md observability surface)
        self.solve_ms_last: Optional[float] = None
        self.delta_extract_ms_last: Optional[float] = None
        self.apsp_close_ms_last: Optional[float] = None
        # resolved EAGERLY: a solver_mesh that doesn't fit the device set
        # must fail at daemon startup with a clear error, not inside the
        # first debounced rebuild callback mid-convergence
        if mesh is not None:
            from openr_tpu.parallel import resolve_mesh

            mesh = resolve_mesh(mesh)
        self.mesh = mesh

    def attach_supervisor(self, supervisor) -> None:
        """Wire the solver fault domain into non-solve device workloads
        owned by this backend (the APSP closes). Called by
        SolverSupervisor.__init__."""
        self._supervisor = supervisor

    def attach_recorder(self, recorder) -> None:
        """Wire the solver flight recorder (solver/flight_recorder.py)
        into every area solve. Called by SolverSupervisor.__init__ before
        the first solve; cached solves created earlier (none in the
        supervised construction order) keep recording disabled."""
        self._recorder = recorder

    def _apsp_dispatch(self, op: str, primary_fn, fallback_fn):
        """ApspState dispatch hook: supervised when a supervisor is
        attached (classified faults feed the shared breaker), bare
        try/except with the numpy fallback otherwise."""
        if self._supervisor is not None:
            return self._supervisor.supervised_call(op, primary_fn, fallback_fn)
        try:
            return primary_fn(), False
        except Exception:
            return fallback_fn(), True

    def _area_solve(
        self, link_state: LinkState, node: str
    ) -> Optional[_AreaSolve]:
        """The cached device solve for this area, or None when the node is
        not present in this area's graph (multi-area: fall back to CPU)."""
        if not link_state.has_node(node) and not link_state.links_from_node(
            node
        ):
            return None
        key = (link_state.area, node)
        cached = self._solves.get(key)
        if cached is not None and cached[0] == id(link_state):
            solve = cached[1]
            before = solve.device_solves
            inc0, full0 = solve.incremental_solves, solve.full_solves
            solve.refresh()  # incremental: patch arrays + one device call
            self.device_solves += solve.device_solves - before
            self._sync_spf_counters(solve, inc0, full0)
            return solve
        if cached is not None:
            # a replaced LinkState for the same area: release the stale
            # solve's device buffers from the ledger before the rebuild
            cached[1].close()
        solve = _AreaSolve(
            link_state,
            node,
            mesh=self.mesh,
            warm_start=self.warm_start,
            apsp_max_nodes=self.apsp_max_nodes,
            apsp_audit_interval=self.apsp_audit_interval,
            apsp_dispatch=self._apsp_dispatch,
            recorder=self._recorder,
            on_capacity_refusal=self._note_capacity_refusal,
        )
        self.device_solves += solve.device_solves
        self._sync_spf_counters(solve, 0, 0)
        self._solves[key] = (id(link_state), solve)
        return solve

    def _note_capacity_refusal(self, verdict: Dict) -> None:
        """Queue a headroom-gated admission refusal for the supervisor to
        drain into a SOLVER_CAPACITY_REFUSED LogSample; also kept as the
        last_capacity_refusal gauge row in getSolverHealth."""
        self._capacity_refusals.append(dict(verdict))

    def take_capacity_refusals(self) -> List[Dict]:
        """Drain queued capacity refusals (supervisor sample emission)."""
        out, self._capacity_refusals = self._capacity_refusals, []
        return out

    def _sync_spf_counters(
        self, solve: _AreaSolve, inc0: int, full0: int
    ) -> None:
        """Fold an _AreaSolve's convergence + profiling stats into the
        decision.spf.* counters/histograms (merged into Decision's dicts
        for the monitor/ctrl API): incremental vs full solves and transfer
        bytes are monotonic, rounds/invalidation-rounds are gauges of the
        most recent solve, solve wall time lands in the warm/cold-split
        latency histograms."""
        d_inc = solve.incremental_solves - inc0
        d_full = solve.full_solves - full0
        counters = self._ensure_counters()
        if d_inc:
            self._bump("decision.spf.incremental_solves", d_inc)
        if d_full:
            self._bump("decision.spf.full_solves", d_full)
        if solve.rounds_last is not None:
            counters["decision.spf.rounds_last"] = solve.rounds_last
        if solve.invalidation_rounds_last is not None:
            counters["decision.spf.invalidation_rounds_last"] = (
                solve.invalidation_rounds_last
            )
        if (d_inc or d_full) and solve.solve_ms_last is not None:
            self.solve_ms_last = solve.solve_ms_last
            self._observe("decision.spf.solve_ms", solve.solve_ms_last)
            self._observe(
                "decision.spf.solve_warm_ms"
                if solve.last_solve_warm
                else "decision.spf.solve_cold_ms",
                solve.solve_ms_last,
            )
        # transfer-byte deltas since the last sync (the lazy d mirror fetch
        # lands on the NEXT sync — the fetch happens after this call, when
        # the route pipeline first reads solve.d)
        d_h2d = solve.h2d_bytes - solve._h2d_synced
        if d_h2d:
            solve._h2d_synced = solve.h2d_bytes
            self._bump("decision.spf.host_to_device_bytes", d_h2d)
        d_d2h = solve.d2h_bytes - solve._d2h_synced
        if d_d2h:
            solve._d2h_synced = solve.d2h_bytes
            self._bump("decision.spf.device_to_host_bytes", d_d2h)
        # DeltaPath extraction stats (docs/Monitoring.md): changed columns
        # and O(changes) copy-back bytes per warm event
        d_cols = solve.delta_columns - solve._delta_cols_synced
        if d_cols:
            solve._delta_cols_synced = solve.delta_columns
            self._bump("decision.spf.delta_columns", d_cols)
        d_bytes = solve.delta_bytes - solve._delta_bytes_synced
        if d_bytes:
            solve._delta_bytes_synced = solve.delta_bytes
            self._bump("decision.spf.delta_bytes", d_bytes)
        # halo-exchange traffic of the destination-tiled layout: ring
        # rotations of the last solve (gauge) + cumulative frontier bytes
        d_halo = solve.halo_bytes - solve._halo_synced
        if d_halo:
            solve._halo_synced = solve.halo_bytes
            self._bump("decision.spf.halo_bytes", d_halo)
        if solve.halo_exchanges_last is not None:
            counters["decision.spf.halo_exchanges_last"] = (
                solve.halo_exchanges_last
            )
        if (
            solve.delta_extracts > solve._delta_extracts_synced
            and solve.delta_extract_ms_last is not None
        ):
            solve._delta_extracts_synced = solve.delta_extracts
            self.delta_extract_ms_last = solve.delta_extract_ms_last
            self._observe(
                "decision.spf.delta_extract_ms", solve.delta_extract_ms_last
            )
        # flight-recorder drain: sampled phase observations land in the
        # decision.spf.phase.*_ms histograms (the names are literals in
        # flight_recorder.PHASE_HISTOGRAMS, pinned to the docs table by
        # registry-drift), and the ring/eviction accounting rides the
        # counter registry as absolute totals
        rec = self._recorder
        if rec is not None:
            for hist_name, value in rec.drain_observations():
                self._observe(hist_name, value)
            counters["decision.spf.traces_recorded"] = rec.recorded
            counters["decision.spf.traces_evicted"] = rec.evicted
            counters["decision.spf.traces_sampled"] = rec.sampled_solves
        self._sync_apsp_counters(solve)
        from openr_tpu.apsp import apsp_compile_cache_stats
        from openr_tpu.ops.spf import compile_cache_stats

        stats = compile_cache_stats()
        fw_stats = apsp_compile_cache_stats()
        counters["decision.spf.compile_cache_hits"] = (
            stats["hits"] + fw_stats["hits"]
        )
        counters["decision.spf.compile_cache_misses"] = (
            stats["misses"] + fw_stats["misses"]
        )
        # device-memory observatory: fold the ledger's counters + gauges
        # (decision.mem.*) in on the same sync cadence as the transfer
        # bytes they complement
        self._ledger.fold_counters(counters)

    def _sync_apsp_counters(self, solve: _AreaSolve) -> None:
        """Fold the solve's APSP + KSP-warm stats into the decision.spf.*
        registry (docs/Apsp.md counter rows): close counts split
        warm/cold/fallback, staleness invalidations, shadow-audit runs,
        the re-close round gauge, transfer bytes, and the close-latency
        histogram — same monotonic-delta discipline as the batch stats."""
        counters = self._ensure_counters()
        d_ksp = solve.ksp_warm_batches - solve._ksp_warm_synced
        if d_ksp:
            solve._ksp_warm_synced = solve.ksp_warm_batches
            self._bump("decision.spf.ksp_warm_batches", d_ksp)
        apsp = solve.apsp
        if apsp is None:
            return
        if apsp.close_ms_last is not None:
            self.apsp_close_ms_last = apsp.close_ms_last
        d_closes = apsp.closes - apsp._closes_synced
        if d_closes:
            apsp._closes_synced = apsp.closes
            self._bump("decision.spf.apsp_closes", d_closes)
            if apsp.close_ms_last is not None:
                self._observe(
                    "decision.spf.apsp_close_ms", apsp.close_ms_last
                )
        for attr, name in (
            ("warm_closes", "decision.spf.apsp_warm_closes"),
            ("cold_closes", "decision.spf.apsp_cold_closes"),
            ("fallback_closes", "decision.spf.apsp_fallback_closes"),
            ("invalidations", "decision.spf.apsp_invalidations"),
            ("audit_runs", "decision.spf.apsp_audit_runs"),
            ("audit_mismatches", "decision.spf.apsp_audit_mismatches"),
            ("h2d_bytes", "decision.spf.apsp_h2d_bytes"),
            ("d2h_bytes", "decision.spf.apsp_d2h_bytes"),
        ):
            value = getattr(apsp, attr)
            synced = apsp._sync_marks.get(attr, 0)
            if value > synced:
                apsp._sync_marks[attr] = value
                self._bump(name, value - synced)
        if apsp.reclose_rounds_last is not None:
            counters["decision.spf.apsp_reclose_rounds_last"] = (
                apsp.reclose_rounds_last
            )

    # -- DeltaPath (device-side route-delta extraction) ------------------

    def poll_device_delta(
        self, area_link_states: Dict[str, LinkState]
    ) -> Optional[Set[str]]:
        """Refresh every area's device solve against the current LSDB and
        return the union of changed destination NODE NAMES — iff every
        area event since the last poll rode the device delta-extraction
        path. None means some event had no device delta (cold solve,
        overload change, flap incident to me, bulk event): the caller must
        rebuild the full route db, which re-arms delta accumulation.

        Areas where this node is absent contribute no routes (the pipeline
        sees an empty SPF there) and are skipped.

        Under `compute_lfa_paths` one extra column is load-bearing: the
        RFC 5286 inequality dist(neighbor, dst) < shortest + dist(neighbor,
        me) reads the ME column from every alt-neighbor row, so a delta
        whose changed set contains me would leave every OTHER prefix's LFA
        threshold stale — that event class is answered with None (full
        rebuild). Every other LFA input is a changed-announcer column the
        delta already names (docs/Apsp.md "DeltaPath under LFA")."""
        me = self.my_node_name
        changed: Set[str] = set()
        ok = True
        for link_state in area_link_states.values():
            solve = self._area_solve(link_state, me)
            if solve is None:
                continue
            cols = solve.take_route_delta()
            if cols is None:
                ok = False  # keep draining the other areas' pending state
                continue
            names = solve.graph.names
            changed.update(names[c] for c in cols if c < len(names))
        if ok and self.compute_lfa_paths and me in changed:
            return None
        return changed if ok else None

    def lfa_delta_ready(self) -> bool:
        """DeltaPath-under-LFA capability gate (solver/delta.py): True when
        every resident area solve carries an APSP-capable state — the
        LFA-era delta build leans on the me-column poison test in
        poll_device_delta plus alt-neighbor rows served from the resident
        matrices; areas past the node cap fall back to the pre-APSP
        force-full behavior."""
        if self.apsp_max_nodes <= 0 or not self._solves:
            return False
        return all(
            solve.apsp is not None and solve.apsp.enabled_for(solve.graph)
            for _, solve in self._solves.values()
        )

    def borrow_apsp(self, area: str, version: int) -> Optional[np.ndarray]:
        """TE hard-scoring borrow (te/service.py): the exact [n, n]
        distance matrix for this area's CURRENT weights, or None when no
        fresh matrix can serve — wrong snapshot version, APSP off or the
        area over the node cap, or drained nodes present (TE excludes
        drained transit by pinning out-edges, which diverges from the
        per-source transit masks a drained topology closes under)."""
        cached = self._solves.get((area, self.my_node_name))
        if cached is None:
            return None
        solve = cached[1]
        g = solve.graph
        if g.version != version or np.any(g.overloaded[: g.n]):
            return None
        if not solve.ensure_apsp():
            return None
        return solve.apsp.d[: g.n, : g.n]

    # -- fault domain (SolverSupervisor seams) ---------------------------

    def degrade_mesh(self) -> bool:
        """Partial-mesh degradation: re-resolve the solver mesh over the
        surviving chips — the largest strictly-smaller (batch, graph)
        factorization that still answers probes — instead of tripping all
        the way to the CPU oracle on a single-chip loss. Returns whether a
        smaller mesh was installed; False means no viable mesh remains
        (single-device mesh, or no mesh at all) and the caller should trip.

        Warm state cannot be re-tiled across mesh shapes (tile ownership
        and frontier slots are functions of the factorization), so every
        cached solve is dropped and the next event cold-starts on the new
        mesh — re-tiled-or-cold, never silently wrong (docs/Decision.md)."""
        if self.mesh is None:
            return False
        from openr_tpu.parallel import plan_degraded_mesh

        new_mesh = plan_degraded_mesh(self.mesh)
        if new_mesh is None:
            return False
        self.mesh = new_mesh
        self._close_solves()
        counters = self._ensure_counters()
        self._bump("decision.spf.mesh_degradations")
        counters["decision.spf.mesh_devices"] = int(new_mesh.devices.size)
        return True

    def invalidate_warm_state(self) -> None:
        """Drop every cached device solve: the next build_route_db
        recompiles the graph and solves cold. The supervisor calls this on
        breaker trips and audit mismatches — after a device fault or a
        detected divergence the resident buffers are not to be trusted."""
        self._close_solves()
        self._bump("decision.spf.warm_state_invalidations")

    def close(self) -> None:
        """Solver teardown (daemon stop): release every device-resident
        structure this solver registered with the memory ledger. Entries
        pinned by `solver.mem.retain` survive by design — that is the
        leak the observatory exists to show."""
        self._close_solves()

    def _close_solves(self) -> None:
        """Drop every cached device solve, releasing each one's ledger-
        registered buffers first — teardown must return the ledger to its
        pre-area baseline (the leak-regression contract)."""
        for _, solve in self._solves.values():
            solve.close()
        self._solves.clear()
        self._ledger.fold_counters(self._ensure_counters())

    def audit_warm_state(self) -> List[dict]:
        """Shadow cold-audit of every resident warm solve: recompute each
        area's distance matrix from host-side truth and compare entrywise
        against the warm device-resident D. Returns one record per
        diverged area (empty list = all clean)."""
        mismatches: List[dict] = []
        for (area, node), (_, solve) in self._solves.items():
            cold = solve.cold_reference_d()
            warm = solve.d
            if warm.shape == cold.shape and np.array_equal(warm, cold):
                continue
            if warm.shape != cold.shape:
                entries = -1
                max_abs = -1
            else:
                diff = warm != cold
                entries = int(diff.sum())
                max_abs = int(
                    np.abs(
                        warm.astype(np.int64) - cold.astype(np.int64)
                    ).max()
                )
            mismatches.append(
                {
                    "area": area,
                    "node": node,
                    "entries": entries,
                    "max_abs_delta": max_abs,
                }
            )
        return mismatches

    # -- SPF access seam -------------------------------------------------

    def _spf(self, link_state: LinkState, node: str):
        solve = self._area_solve(link_state, self.my_node_name)
        if solve is not None and node in solve.row_map:
            return _TpuSpfResult(solve, node)
        # source outside the solved batch (not me / my neighbor): the
        # resident all-pairs matrix serves its whole row — LFA-style
        # qualification from an arbitrary perspective reads alt-neighbor
        # rows from ApspState instead of a per-source Dijkstra column
        # solve (docs/Apsp.md)
        if (
            solve is not None
            and node in solve.graph.node_index
            and solve.ensure_apsp()
        ):
            return _ApspSpfResult(solve, node)
        # area this node does not participate in: CPU oracle fallback
        return link_state.get_spf_result(node)

    def _dist(self, link_state: LinkState, a: str, b: str) -> Optional[Metric]:
        if a == b:
            return 0
        solve = self._area_solve(link_state, self.my_node_name)
        if solve is not None:
            row = solve.row_map.get(a)
            col = solve.graph.node_index.get(b)
            if row is not None and col is not None:
                metric = int(solve.d[row, col])
                return metric if metric < INF else None
            if (
                col is not None
                and a in solve.graph.node_index
                and solve.ensure_apsp()
            ):
                metric = int(
                    solve.apsp.d[solve.graph.node_index[a], col]
                )
                return metric if metric < INF else None
        return link_state.get_metric_from_a_to_b(a, b)

    def _kth_paths(
        self, link_state: LinkState, src: str, dest: str, k: int
    ) -> List[Path]:
        solve = self._area_solve(link_state, self.my_node_name)
        if solve is None or src != self.my_node_name:
            return link_state.get_kth_paths(src, dest, k)
        return solve.kth_paths(dest, k)

    def _prefetch_kth_paths(
        self, link_state: LinkState, src: str, dests: List[str], k: int
    ) -> None:
        solve = self._area_solve(link_state, self.my_node_name)
        if solve is not None and src == self.my_node_name:
            solve.prefetch_ksp(dests, k)
