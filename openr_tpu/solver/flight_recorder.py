"""Solver flight recorder: per-solve traces, sampled phase timing, and
fault-forensics dumps.

Every solver path this repo has shipped — cold, warm-invalidation,
edge-list, tiled-halo, blocked-FW APSP — reported exactly one wall-clock
number per solve (`decision.spf.solve_ms`), so nothing could attribute an
event's latency to h2d upload, the relax fixpoint, delta extraction or
the lazy d2h mirror fetch; and when the fault domain fired, the
supervisor threw away exactly the context (the recent solve history)
needed to diagnose it. This module is the missing observability layer:

  - **SolveTrace** — one structured record per supervised solve: event
    class, layout kind (sell / bf / tile2d / cpu), warm/cold disposition,
    wall time, fixpoint rounds, transfer bytes, compile-cache deltas,
    breaker state, and (on sampled solves) a per-phase millisecond
    breakdown.
  - **PhaseClock** — the sampled phase timer. Every `sample_every`-th
    solve gets a live clock whose `seam(...)` calls take
    `block_until_ready` barriers at the phase boundaries, so the
    recorded per-phase times are real device time; the other solves get
    the shared `NULL_CLOCK`, whose `seam` is a single attribute check —
    the unsampled hot path never touches a device buffer it would not
    have touched anyway (the probe-effect contract,
    tests/test_flight_recorder.py).
  - **FlightRecorder** — a bounded per-area ring of traces with exact
    eviction accounting (`recorded == retained + evicted`), plus the
    forensics side: `dump(reason)` snapshots the rings, the solver
    config, a mesh/device digest and a counter snapshot into one JSON
    artifact, referenced by id from the breaker/audit LogSamples
    (`SOLVER_FORENSICS_DUMPED`, docs/Monitoring.md).

The recorder owns no registry: phase samples queue in a pending list the
owning backend drains into its `decision.spf.phase.*_ms` histograms on
the existing counter-sync path (solver/tpu.py:_sync_spf_counters), so
monitor/ctrl/exporter all see them through the normal substrate.
"""

from __future__ import annotations

import collections
import json
import os
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Deque, Dict, List, Optional, Tuple

# phase vocabulary, in dispatch order. The fused warm kernels run the
# invalidation-mark fixpoint and (on the tiled layout) the halo exchange
# inside the same dispatch as the relax rounds, so those phases are
# attributed inside `relax` with the per-trace round/exchange gauges
# splitting them (docs/Monitoring.md "Flight recorder & profiling").
PHASES = ("prepare", "h2d", "relax", "delta_extract", "d2h")

# phase -> registry histogram (docs/Monitoring.md histogram table); the
# full names live here as literals so the doc rows stay pinned to code
# by the registry-drift analyzer's string universe
PHASE_HISTOGRAMS: Dict[str, str] = {
    "prepare": "decision.spf.phase.prepare_ms",
    "h2d": "decision.spf.phase.h2d_ms",
    "relax": "decision.spf.phase.relax_ms",
    "delta_extract": "decision.spf.phase.delta_extract_ms",
    "d2h": "decision.spf.phase.d2h_ms",
}


class PhaseClock:
    """Per-solve phase timer; a live one exists only on sampled solves.

    `seam(phase, *values)` closes the current phase: it blocks on every
    value that exposes `block_until_ready` (so device execution up to the
    seam is inside the measured window, not smeared into the next phase
    by async dispatch) and credits the elapsed milliseconds to `phase`.
    The shared NULL_CLOCK instance short-circuits on `self.sampled`."""

    __slots__ = ("sampled", "phases", "barriers", "_last")

    def __init__(self, sampled: bool) -> None:
        self.sampled = sampled
        self.phases: Dict[str, float] = {}
        self.barriers = 0  # block_until_ready calls taken (probe-effect)
        self._last = time.perf_counter() if sampled else 0.0

    def seam(self, phase: str, *values: Any) -> None:
        if not self.sampled:
            return
        for value in values:
            block = getattr(value, "block_until_ready", None)
            if block is not None:
                block()
                self.barriers += 1
        now = time.perf_counter()
        self.phases[phase] = (
            self.phases.get(phase, 0.0) + (now - self._last) * 1e3
        )
        self._last = now


NULL_CLOCK = PhaseClock(False)


@dataclass
class SolveTrace:
    """One supervised solve, structured (docs/Monitoring.md field table)."""

    seq: int
    ts: float  # wall clock (forensics correlation across nodes)
    area: str
    node: str
    event: str  # solve | fallback_solve | fault
    layout: str  # sell | bf | tile2d | replicated | cpu | none
    warm: bool
    solve_ms: Optional[float]
    rounds: Optional[int]
    invalidation_rounds: Optional[int]
    halo_exchanges: Optional[int]
    h2d_bytes: int
    d2h_bytes: int
    halo_bytes: int
    delta_columns: Optional[int]
    compile_cache_misses: int  # executables compiled BY this solve
    breaker_state: str
    sampled: bool
    phases: Dict[str, float] = field(default_factory=dict)
    fault_kind: Optional[str] = None
    detail: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return asdict(self)


class FlightRecorder:
    """Bounded per-area SolveTrace rings + forensics dump snapshots."""

    def __init__(
        self,
        ring_size: int = 64,
        sample_every: int = 16,
        forensics_dir: Optional[str] = None,
        forensics_last_n: int = 16,
        max_dumps: int = 8,
        node: str = "",
    ) -> None:
        self.ring_size = max(int(ring_size), 1)
        self.sample_every = max(int(sample_every), 0)  # 0 = never sample
        self.forensics_dir = forensics_dir
        self.forensics_last_n = max(int(forensics_last_n), 1)
        self.max_dumps = max(int(max_dumps), 1)
        self.node = node
        # stamped by the supervisor on breaker transitions so traces and
        # dumps carry the serving state they were recorded under
        self.breaker_state = "closed"
        self._rings: Dict[str, Deque[SolveTrace]] = {}
        self._seq = 0
        self.solves_seen = 0
        self.recorded = 0
        self.evicted = 0
        self.sampled_solves = 0
        self.barrier_calls = 0  # total sampled-seam barriers ever taken
        self._pending_obs: List[Tuple[str, float]] = []
        self.dumps: List[Dict[str, Any]] = []
        self.dumps_written = 0
        self.last_dump_id: Optional[str] = None
        self.last_dump_reason: Optional[str] = None

    # -- recording -------------------------------------------------------

    def begin(self) -> PhaseClock:
        """Per-solve sampling decision: every `sample_every`-th solve gets
        a live PhaseClock (barriers at phase seams), the rest share the
        no-op NULL_CLOCK."""
        self.solves_seen += 1
        if self.sample_every > 0 and (
            self.solves_seen % self.sample_every == 1
            or self.sample_every == 1
        ):
            self.sampled_solves += 1
            return PhaseClock(True)
        return NULL_CLOCK

    def next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def record(self, trace: SolveTrace, clock: Optional[PhaseClock] = None):
        """Append one trace to its area ring (evicting with accounting)
        and queue the sampled phase observations for the histogram
        drain."""
        ring = self._rings.get(trace.area)
        if ring is None:
            ring = self._rings[trace.area] = collections.deque()
        while len(ring) >= self.ring_size:
            ring.popleft()
            self.evicted += 1
        ring.append(trace)
        self.recorded += 1
        if clock is not None and clock.sampled:
            self.barrier_calls += clock.barriers
            for phase, ms in clock.phases.items():
                self.observe_phase(phase, ms)

    def observe_phase(self, phase: str, ms: float) -> None:
        """Queue one phase sample for the owning backend's histogram
        drain (also used post-hoc: the lazy d2h mirror fetch lands after
        the trace was recorded)."""
        name = PHASE_HISTOGRAMS.get(phase)
        if name is not None:
            self._pending_obs.append((name, ms))

    def drain_observations(self) -> List[Tuple[str, float]]:
        out, self._pending_obs = self._pending_obs, []
        return out

    # -- read surfaces ---------------------------------------------------

    def retained(self) -> int:
        return sum(len(r) for r in self._rings.values())

    def snapshot(
        self, area: Optional[str] = None, last_n: Optional[int] = None
    ) -> List[Dict[str, Any]]:
        """Trace dicts, oldest first, optionally filtered/limited."""
        traces: List[SolveTrace] = []
        for ring_area, ring in sorted(self._rings.items()):
            if area is not None and ring_area != area:
                continue
            traces.extend(ring)
        traces.sort(key=lambda t: t.seq)
        if last_n is not None and last_n >= 0:
            traces = traces[-last_n:]
        return [t.to_dict() for t in traces]

    def stats(self) -> Dict[str, Any]:
        return {
            "ring_size": self.ring_size,
            "sample_every": self.sample_every,
            "areas": sorted(self._rings),
            "recorded": self.recorded,
            "retained": self.retained(),
            "evicted": self.evicted,
            "sampled_solves": self.sampled_solves,
            "barrier_calls": self.barrier_calls,
        }

    # -- forensics -------------------------------------------------------

    def dump(
        self,
        reason: str,
        *,
        solver_config: Optional[Dict[str, Any]] = None,
        counters: Optional[Dict[str, int]] = None,
        mesh_digest: Optional[Dict[str, Any]] = None,
        extra: Optional[Dict[str, Any]] = None,
        device_memory: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Snapshot the rings + context into one JSON-serializable
        forensics artifact; kept in a bounded in-memory list and, when
        `forensics_dir` is configured, written to
        `<dir>/<id>.json` (best-effort: an unwritable dir must never
        turn a breaker trip into a crash)."""
        self.dumps_written += 1
        dump_id = (
            f"forensics-{self.node or 'node'}-"
            f"{self.dumps_written:04d}-{int(time.time())}"
        )
        dump: Dict[str, Any] = {
            "id": dump_id,
            "reason": reason,
            "ts": time.time(),
            "node": self.node,
            "breaker_state": self.breaker_state,
            "trace_stats": self.stats(),
            "traces": {
                area: [t.to_dict() for t in list(ring)][
                    -self.forensics_last_n:
                ]
                for area, ring in sorted(self._rings.items())
            },
            "solver_config": solver_config or {},
            "mesh_digest": mesh_digest or device_digest(None),
            "counters": dict(counters or {}),
        }
        if extra:
            dump["extra"] = extra
        if device_memory is not None:
            # memory-ledger snapshot (monitor/memledger.py): resident
            # structures + capacity picture at dump time — the device_oom
            # post-mortem's primary evidence
            dump["device_memory"] = device_memory
        self.dumps.append(dump)
        while len(self.dumps) > self.max_dumps:
            self.dumps.pop(0)
        self.last_dump_id = dump_id
        self.last_dump_reason = reason
        dump["path"] = None
        if self.forensics_dir:
            try:
                os.makedirs(self.forensics_dir, exist_ok=True)
                path = os.path.join(self.forensics_dir, f"{dump_id}.json")
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as fh:
                    json.dump(dump, fh, sort_keys=True)
                os.replace(tmp, path)
                dump["path"] = path
            except OSError:
                pass
        return dump

    def dump_summaries(self) -> List[Dict[str, Any]]:
        """Compact dump index (getSolverHealth / getSolveTraces): id,
        reason, timestamp, trace count, artifact path."""
        return [
            {
                "id": d["id"],
                "reason": d["reason"],
                "ts": d["ts"],
                "breaker_state": d["breaker_state"],
                "traces": sum(len(ts) for ts in d["traces"].values()),
                "path": d.get("path"),
            }
            for d in self.dumps
        ]

    def forensics_stats(self) -> Dict[str, Any]:
        return {
            "dumps": self.dumps_written,
            "retained_dumps": len(self.dumps),
            "last_id": self.last_dump_id,
            "last_reason": self.last_dump_reason,
            "dir": self.forensics_dir,
        }


def device_digest(mesh) -> Dict[str, Any]:
    """Mesh/device context for forensics dumps, degrade-safe: a dead or
    absent backend yields an error string, never an exception (the dump
    runs exactly when the device is suspect)."""
    digest: Dict[str, Any] = {
        "mesh_shape": dict(mesh.shape) if mesh is not None else None,
    }
    try:
        import jax

        devices = jax.devices()
        digest["devices"] = len(devices)
        digest["platform"] = devices[0].platform if devices else None
        digest["device_kind"] = (
            getattr(devices[0], "device_kind", "") if devices else None
        )
    except Exception as exc:  # device loss is exactly when dumps happen
        digest["error"] = f"{type(exc).__name__}: {exc}"
    return digest
