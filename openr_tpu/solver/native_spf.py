"""ctypes binding for the native C++ SPF oracle (native/spf/onl_spf.cpp).

This is the rebuild's equivalent of keeping the reference's C++ SpfSolver
around (openr/decision/LinkState.cpp:806-880) as the small-graph fallback
and the honest CPU baseline the TPU batched solver is measured against —
a Python Dijkstra would flatter the TPU numbers.

Operates directly on the CompiledGraph edge arrays (openr_tpu/ops/graph.py),
so link flaps/metric changes are `set_weight` patches, mirroring the device
path's weight-patch incrementality.

Auto-builds openr_tpu/_native/libopenr_spf.so via `make` on first use;
`native_spf_available()` gates callers, who fall back to the Python
LinkState oracle when the toolchain is missing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Set

import numpy as np

from openr_tpu.ops.graph import INF, CompiledGraph

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "_native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libopenr_spf.so")
_MAKE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(__file__))), "native"
)

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    try:
        if not os.path.exists(_SO_PATH):
            # build only the SPF library: a failure in an unrelated native
            # component (e.g. netlink, needing linux headers) must not
            # disable the SPF baseline
            subprocess.run(
                ["make", "-C", _MAKE_DIR, "../openr_tpu/_native/libopenr_spf.so"],
                check=True,
                capture_output=True,
                timeout=120,
            )
        lib = ctypes.CDLL(_SO_PATH)
    except Exception:
        return None
    i32p = ctypes.POINTER(ctypes.c_int32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    lib.onl_spf_create.restype = ctypes.c_void_p
    lib.onl_spf_create.argtypes = [
        ctypes.c_int32,
        ctypes.c_int64,
        i32p,
        i32p,
        i32p,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.onl_spf_destroy.argtypes = [ctypes.c_void_p]
    lib.onl_spf_set_weight.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int32,
    ]
    lib.onl_spf_set_overloaded.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_uint8,
    ]
    lib.onl_spf_out_degree.restype = ctypes.c_int32
    lib.onl_spf_out_degree.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.onl_spf_out_neighbors.restype = ctypes.c_int32
    lib.onl_spf_out_neighbors.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        i32p,
        ctypes.c_int32,
    ]
    lib.onl_spf_run.restype = ctypes.c_int64
    lib.onl_spf_run.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        i32p,
        u64p,
        ctypes.c_int32,
    ]
    lib.onl_spf_run_many.restype = ctypes.c_int64
    lib.onl_spf_run_many.argtypes = [ctypes.c_void_p, i32p, ctypes.c_int32]
    _lib = lib
    return _lib


def native_spf_available() -> bool:
    return _load() is not None


def _as_i32_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


class NativeSpfSolver:
    """Dijkstra over a CompiledGraph's real edges, run by the C++ engine.

    Only the `graph.e` real edge slots are passed down (array padding never
    relaxes anyway); edge positions used by `set_weight` are therefore the
    same positions `CompiledGraph.link_edges` records.
    """

    def __init__(self, graph: CompiledGraph):
        lib = _load()
        if lib is None:
            raise RuntimeError("native SPF library unavailable")
        self._lib = lib
        self.graph = graph
        self.n = graph.n
        src = np.ascontiguousarray(graph.src[: graph.e], dtype=np.int32)
        dst = np.ascontiguousarray(graph.dst[: graph.e], dtype=np.int32)
        w = np.ascontiguousarray(graph.w[: graph.e], dtype=np.int32)
        ov = np.ascontiguousarray(
            graph.overloaded[: graph.n], dtype=np.uint8
        )
        self._h = lib.onl_spf_create(
            graph.n,
            graph.e,
            _as_i32_ptr(src),
            _as_i32_ptr(dst),
            _as_i32_ptr(w),
            ov.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        )
        if not self._h:
            raise RuntimeError("onl_spf_create failed")

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.onl_spf_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    def set_weight(self, edge_pos: int, w: int) -> None:
        self._lib.onl_spf_set_weight(self._h, edge_pos, int(w))

    def set_overloaded(self, node: int, overloaded: bool) -> None:
        self._lib.onl_spf_set_overloaded(self._h, node, 1 if overloaded else 0)

    def out_neighbors(self, source: int) -> np.ndarray:
        deg = self._lib.onl_spf_out_degree(self._h, source)
        out = np.zeros(max(deg, 1), dtype=np.int32)
        self._lib.onl_spf_out_neighbors(self._h, source, _as_i32_ptr(out), deg)
        return out[:deg]

    def run(self, source: int) -> np.ndarray:
        """Distances int32 [n] from `source` (INF = unreachable)."""
        dist = np.empty(self.n, dtype=np.int32)
        r = self._lib.onl_spf_run(self._h, source, _as_i32_ptr(dist), None, 0)
        if r < 0:
            raise ValueError(f"bad source {source}")
        return dist

    def run_with_nexthops(self, source: int):
        """(distances [n], first-hop neighbor-id sets per node)."""
        deg = self._lib.onl_spf_out_degree(self._h, source)
        words = max(1, (deg + 63) // 64)
        dist = np.empty(self.n, dtype=np.int32)
        nh = np.zeros((self.n, words), dtype=np.uint64)
        r = self._lib.onl_spf_run(
            self._h,
            source,
            _as_i32_ptr(dist),
            nh.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            words,
        )
        if r < 0:
            raise ValueError(f"bad source {source}")
        nbrs = self.out_neighbors(source)
        sets: List[Set[int]] = []
        for v in range(self.n):
            s: Set[int] = set()
            row = nh[v]
            for word_i in range(words):
                bits = int(row[word_i])
                while bits:
                    b = bits & -bits
                    slot = word_i * 64 + b.bit_length() - 1
                    if slot < len(nbrs):
                        s.add(int(nbrs[slot]))
                    bits ^= b
            sets.append(s)
        return dist, sets

    def run_many(self, sources: np.ndarray) -> int:
        """Benchmark path: Dijkstra from each source, results discarded."""
        src = np.ascontiguousarray(sources, dtype=np.int32)
        r = self._lib.onl_spf_run_many(self._h, _as_i32_ptr(src), len(src))
        if r < 0:
            raise ValueError("bad source in batch")
        return int(r)
