"""RibPolicy: match/action transform applied to computed routes.

Behavioral port of openr/decision/RibPolicy.{h,cpp}: statements match routes
by exact prefix; the set-weight action assigns per-area weights (weight 0
drops the nexthop); the policy expires after ttl seconds and Decision
re-applies routes when it does.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Set

from openr_tpu.solver.routes import RibUnicastEntry
from openr_tpu.types import IpPrefix, NextHop, replace


@dataclass
class SetWeightAction:
    """thrift::RibRouteActionWeight equivalent."""

    default_weight: int = 0
    area_to_weight: Dict[str, int] = field(default_factory=dict)


@dataclass
class RibPolicyStatement:
    name: str
    prefixes: Set[IpPrefix]
    action: SetWeightAction

    def __post_init__(self) -> None:
        if not self.prefixes:
            raise ValueError("policy statement requires match prefixes")

    def match(self, route: RibUnicastEntry) -> bool:
        return route.prefix in self.prefixes

    def apply_action(self, route: RibUnicastEntry) -> bool:
        if not self.match(route):
            return False
        new_nexthops: Set[NextHop] = set()
        for nh in route.nexthops:
            weight = self.action.default_weight
            if nh.area is not None:
                weight = self.action.area_to_weight.get(
                    nh.area, self.action.default_weight
                )
            if weight > 0:
                new_nexthops.add(replace(nh, weight=weight))
            # weight 0 drops the nexthop
        route.nexthops = new_nexthops
        return True


class RibPolicy:
    def __init__(
        self, statements: List[RibPolicyStatement], ttl_secs: float
    ) -> None:
        if not statements:
            raise ValueError("policy requires statements")
        self.statements = statements
        self._valid_until = time.monotonic() + ttl_secs

    def get_ttl_duration(self) -> float:
        return self._valid_until - time.monotonic()

    def is_active(self) -> bool:
        return self.get_ttl_duration() > 0

    def match(self, route: RibUnicastEntry) -> bool:
        return any(s.match(route) for s in self.statements)

    def apply_action(self, route: RibUnicastEntry) -> bool:
        for s in self.statements:
            if s.apply_action(route):
                return True
        return False

    # -- ctrl-plane (de)serialization (OpenrCtrl.thrift RibPolicy:84-123) --

    def to_dict(self) -> dict:
        return {
            "ttl_secs": max(0.0, self.get_ttl_duration()),
            "statements": [
                {
                    "name": s.name,
                    "prefixes": sorted(str(p) for p in s.prefixes),
                    "default_weight": s.action.default_weight,
                    "area_to_weight": dict(s.action.area_to_weight),
                }
                for s in self.statements
            ],
        }

    @staticmethod
    def from_dict(data: dict) -> "RibPolicy":
        statements = [
            RibPolicyStatement(
                name=s["name"],
                prefixes={IpPrefix(p) for p in s["prefixes"]},
                action=SetWeightAction(
                    default_weight=s.get("default_weight", 0),
                    area_to_weight=dict(s.get("area_to_weight", {})),
                ),
            )
            for s in data["statements"]
        ]
        return RibPolicy(statements, float(data["ttl_secs"]))
