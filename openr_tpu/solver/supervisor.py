"""Supervised solver layer: the solve path's explicit fault domain.

Sits between Decision and the solver backends so that a failing device
solve (XLA compile error, runtime fault, device loss, deadline overrun)
degrades to the CPU oracle instead of unwinding into Decision's event loop
— degraded hardware means slower convergence, never wrong routes or a dead
Decision module (FatPaths correctness-under-failure posture, PAPERS.md).

Three cooperating mechanisms:

  1. **Supervised solves** — every `build_route_db` on the primary (TPU)
     backend is wrapped with error classification
     (compile / runtime / device_loss / deadline), bounded in-call retry,
     and per-solve deadline accounting stamped into the Watchdog's
     heartbeat map (`monitor/watchdog.py`) so a wedged solve is attributed
     to the solver, not generically to Decision.

  2. **Circuit breaker with CPU fallback** — `failure_threshold`
     consecutive primary failures trip the breaker OPEN: the primary's
     device-resident warm state is invalidated (it is untrustworthy after
     a device fault) and every solve is served by the CPU oracle
     (`decision.spf.fallback_active` = 1). Recovery is probe-driven with
     hysteresis: background health-probe solves re-run the primary on the
     live LSDB off the hot path, and only `probe_successes_to_close`
     consecutive successes close the breaker; any probe failure re-arms an
     `ExponentialBackoff` gate so a flapping device cannot oscillate the
     serving path.

  3. **Warm-state self-audit** — every `audit_interval`-th successful
     primary solve triggers a shadow cold solve (recomputed from the
     host-side graph truth) compared entrywise against the warm
     device-resident distance matrix. Divergence increments
     `decision.spf.audit_mismatches`, emits a `WARM_STATE_AUDIT` LogSample
     (CONVERGENCE_TRACE-style, through the monitor queue), forces a cold
     re-solve and re-serves the corrected routes — self-healing, not
     crash: a silently-diverged warm `D` would otherwise program wrong
     routes forever.

All counters live in the `decision.spf.*` namespace so they flow through
Decision's existing counter sync into Monitor/ctrl/breeze.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from openr_tpu.solver.routes import get_route_delta
from openr_tpu.utils.backoff import ExponentialBackoff
from openr_tpu.utils.counters import CountersMixin, HistogramsMixin

log = logging.getLogger(__name__)

# breaker states
CLOSED = "closed"  # primary serving
OPEN = "open"  # fallback serving, probes running
HALF_OPEN = "half_open"  # fallback serving, probe streak in progress

# fault kinds (classification buckets)
FAULT_COMPILE = "compile"
FAULT_RUNTIME = "runtime"
FAULT_DEVICE_LOSS = "device_loss"
FAULT_DEADLINE = "deadline"
FAULT_DEVICE_OOM = "device_oom"


class SolveDeadlineExceeded(RuntimeError):
    """A solve finished but blew its per-solve deadline budget."""


def classify_solver_error(exc: BaseException) -> str:
    """Map a raised solve exception onto a fault-kind bucket.

    Classification is by exception type name + message substrings rather
    than concrete jax types: the supervisor must not import device
    runtimes it is there to survive, and jax's exception taxonomy moves
    between releases. Unknown errors classify as runtime (the safe bucket:
    retry-then-fallback)."""
    if isinstance(exc, SolveDeadlineExceeded):
        return FAULT_DEADLINE
    names = {type(e).__name__ for e in _exc_chain(exc)}
    text = " ".join(
        f"{type(e).__name__}: {e}" for e in _exc_chain(exc)
    ).lower()
    # allocator exhaustion FIRST: XLA's RESOURCE_EXHAUSTED wording and the
    # capacity model's predicted refusal both land here — the forensics
    # dump for this kind embeds the full memory-ledger snapshot so the
    # post-mortem names the structure that ate the chip
    if any(
        hint in text
        for hint in (
            "resource_exhausted",
            "resource exhausted",
            "out of memory",
            "out-of-memory",
            "memory allocation failure",
            "allocation failure",
        )
    ) or "DeviceCapacityError" in names:
        return FAULT_DEVICE_OOM
    if any(
        hint in text
        for hint in (
            "device_lost",
            "device lost",
            "device is lost",
            "failed to connect",
            "halted",
            "data transfer",
            "device unavailable",
        )
    ):
        return FAULT_DEVICE_LOSS
    if (
        "XlaCompileError" in names
        or "compile" in text
        or "lowering" in text
        or isinstance(exc, (TypeError, NotImplementedError))
    ):
        return FAULT_COMPILE
    return FAULT_RUNTIME


def _exc_chain(exc: BaseException) -> List[BaseException]:
    out: List[BaseException] = []
    seen = set()
    cur: Optional[BaseException] = exc
    while cur is not None and id(cur) not in seen:
        out.append(cur)
        seen.add(id(cur))
        cur = cur.__cause__ or cur.__context__
    return out


@dataclass
class SupervisorConfig:
    """Knobs for the solver fault domain (docs/Robustness.md)."""

    # consecutive primary failures that trip the breaker OPEN
    failure_threshold: int = 3
    # in-call retry budget per build_route_db (1 = no retry)
    max_attempts: int = 2
    # per-solve wall-clock deadline; overruns classify as FAULT_DEADLINE
    # and count toward the breaker (the result, if any, is still served —
    # slow-but-correct beats no-route)
    solve_deadline_s: float = 30.0
    # health-probe cadence while the breaker is OPEN/HALF_OPEN; failures
    # back off exponentially from this base
    probe_interval_s: float = 5.0
    probe_backoff_max_s: float = 60.0
    # hysteresis: consecutive probe successes required to close the breaker
    probe_successes_to_close: int = 2
    # shadow cold-audit every Nth successful primary solve; 0 disables
    audit_interval: int = 0
    # partial-mesh degradation: when a device-loss streak reaches the
    # failure threshold on a multi-chip solver_mesh, re-resolve the mesh
    # over the surviving chips (smaller batch x graph factorization)
    # instead of tripping straight to the CPU oracle; the breaker only
    # opens when no viable mesh remains (docs/Robustness.md ladder)
    mesh_degrade: bool = True
    # watchdog heartbeat name stamped around solves
    watchdog_module: str = "decision"
    # flight recorder (solver/flight_recorder.py, docs/Monitoring.md
    # "Flight recorder & profiling"): per-area SolveTrace ring bound and
    # the phase-timing sampling cadence — every trace_sample_every-th
    # solve takes block_until_ready barriers at phase seams; 0 disables
    # sampling entirely (traces still record, without phase splits)
    trace_ring_size: int = 64
    trace_sample_every: int = 16
    # forensics dumps: traces per area snapshotted into each dump, and an
    # optional directory the JSON artifacts are also written to (None =
    # in-memory only, read via ctrl getSolveTraces)
    forensics_last_n: int = 16
    forensics_dir: Optional[str] = None


class SolverSupervisor(CountersMixin, HistogramsMixin):
    """Drop-in SpfSolver facade: primary backend under supervision, CPU
    oracle as the degraded path. Decision talks only to this object."""

    def __init__(
        self,
        primary,
        fallback,
        config: Optional[SupervisorConfig] = None,
        *,
        watchdog=None,
        log_sample_fn=None,
        clock=time.monotonic,
    ) -> None:
        self.primary = primary
        self.fallback = fallback
        self.config = config or SupervisorConfig()
        self.watchdog = watchdog
        self._log_sample_fn = log_sample_fn
        self._clock = clock
        self.my_node_name = primary.my_node_name

        self.state = CLOSED
        self.consecutive_failures = 0
        self.probe_streak = 0
        self.last_fault_kind: Optional[str] = None
        self._solves_since_audit = 0
        self._delta_builds_since_audit = 0
        self._probe_backoff = ExponentialBackoff(
            max(self.config.probe_interval_s, 1e-3),
            max(
                self.config.probe_backoff_max_s,
                self.config.probe_interval_s,
                1e-3,
            ),
            clock=clock,
        )
        self._next_probe_at = 0.0
        self._probe_task = None
        # last solve inputs, kept for probes/audits off the hot path
        self._last_inputs = None

        self.counters: Dict[str, int] = {}
        self.histograms: Dict = {}
        self.counters["decision.spf.fallback_active"] = 0

        # flight recorder: every supervised solve leaves a SolveTrace in
        # the bounded per-area ring, and the fault paths below snapshot
        # the ring into forensics dumps (docs/Monitoring.md)
        from openr_tpu.solver.flight_recorder import FlightRecorder

        self.recorder = FlightRecorder(
            ring_size=self.config.trace_ring_size,
            sample_every=self.config.trace_sample_every,
            forensics_dir=self.config.forensics_dir,
            forensics_last_n=self.config.forensics_last_n,
            node=self.my_node_name,
        )
        attach_rec = getattr(primary, "attach_recorder", None)
        if attach_rec is not None:
            attach_rec(self.recorder)

        # non-solve device workloads owned by the primary (the APSP
        # closes) dispatch through this fault domain too: classified
        # faults feed the shared breaker, numpy FW is their degraded path
        attach = getattr(primary, "attach_supervisor", None)
        if attach is not None:
            attach(self)

    # ------------------------------------------------------------------
    # lifecycle (background probe loop; optional — probes also run
    # opportunistically from the solve path when no loop is attached)
    # ------------------------------------------------------------------

    def start(self, loop=None) -> None:
        import asyncio

        if self._probe_task is not None:
            return
        try:
            loop = loop or asyncio.get_event_loop()
        except RuntimeError:
            return
        self._probe_task = loop.create_task(self._probe_loop())

    def stop(self) -> None:
        if self._probe_task is not None:
            self._probe_task.cancel()
            self._probe_task = None

    def close(self) -> None:
        """Teardown passthrough: release the primary backend's ledger-
        registered device structures (the fallback oracle holds none)."""
        close = getattr(self.primary, "close", None)
        if close is not None:
            close()

    async def _probe_loop(self) -> None:
        import asyncio

        interval = max(self.config.probe_interval_s / 4.0, 0.01)
        try:
            while True:
                await asyncio.sleep(interval)
                if self.state != CLOSED:
                    self.maybe_probe()
        except asyncio.CancelledError:
            pass

    # ------------------------------------------------------------------
    # SpfSolver facade
    # ------------------------------------------------------------------

    def build_route_db(self, my_node_name, area_link_states, prefix_state):
        self._last_inputs = (my_node_name, area_link_states, prefix_state)
        if self.state != CLOSED:
            # opportunistic probe for loop-less embeddings: the breaker
            # must be able to recover even when nobody started the
            # background task (probe_due gates the cadence)
            if self._probe_task is None:
                self.maybe_probe()
        if self.state != CLOSED:
            return self._fallback_solve(
                my_node_name, area_link_states, prefix_state
            )

        attempts = 0
        while True:
            attempts += 1
            self._touch_watchdog()
            t0 = self._clock()
            try:
                db = self.primary.build_route_db(
                    my_node_name, area_link_states, prefix_state
                )
            except Exception as exc:
                self._record_failure(classify_solver_error(exc), exc)
                if self.state != CLOSED:
                    break
                if attempts >= max(self.config.max_attempts, 1):
                    # retry budget exhausted without tripping the breaker:
                    # serve this event degraded, keep the breaker counting
                    break
                self._bump("decision.spf.solver_retries")
                continue
            finally:
                self._touch_watchdog()
            elapsed = self._clock() - t0
            if elapsed > self.config.solve_deadline_s:
                # the solve completed but blew its budget: a deadline
                # fault feeds the breaker (repeated overruns mean the
                # device is degrading), yet the computed routes are valid
                # — serve them rather than discard correct work
                self._record_failure(
                    FAULT_DEADLINE,
                    SolveDeadlineExceeded(
                        f"solve took {elapsed:.3f}s "
                        f"(deadline {self.config.solve_deadline_s}s)"
                    ),
                    elapsed_s=elapsed,
                )
            else:
                self._record_success()
            self._sync_backend_stats(self.primary)
            db = self._maybe_audit(
                db, my_node_name, area_link_states, prefix_state
            )
            return db

        return self._fallback_solve(
            my_node_name, area_link_states, prefix_state
        )

    # ------------------------------------------------------------------
    # generic supervised device workloads (TE optimization etc.)
    # ------------------------------------------------------------------

    def supervised_call(
        self, op: str, primary_fn, fallback_fn=None, deadline_s=None
    ):
        """Run a non-SPF device workload inside this fault domain.

        Same contract as a supervised solve: raised errors are classified
        and feed the breaker (the workloads share the device — a TE
        dispatch fault is device evidence like any other), retries are
        bounded by `max_attempts`, and a completed-but-late call records a
        deadline fault while its result is still served. While the
        breaker is non-CLOSED, or when the retry budget is exhausted, the
        fallback serves. Returns (result, degraded); with no fallback the
        last primary error propagates."""
        deadline = (
            deadline_s if deadline_s is not None
            else self.config.solve_deadline_s
        )
        if self.state != CLOSED and self._probe_task is None:
            self.maybe_probe()  # loop-less embeddings still recover
        if self.state != CLOSED:
            if fallback_fn is None:
                raise RuntimeError(
                    f"supervised call {op}: breaker {self.state}, "
                    f"no fallback"
                )
            return fallback_fn(), True

        attempts = 0
        last_exc: Optional[BaseException] = None
        while True:
            attempts += 1
            self._touch_watchdog()
            t0 = self._clock()
            try:
                result = primary_fn()
            except Exception as exc:
                last_exc = exc
                self._record_failure(classify_solver_error(exc), exc)
                if self.state != CLOSED:
                    break
                if attempts >= max(self.config.max_attempts, 1):
                    break
                self._bump("decision.spf.solver_retries")
                continue
            finally:
                self._touch_watchdog()
            elapsed = self._clock() - t0
            if elapsed > deadline:
                self._record_failure(
                    FAULT_DEADLINE,
                    SolveDeadlineExceeded(
                        f"{op} took {elapsed:.3f}s (deadline {deadline}s)"
                    ),
                    elapsed_s=elapsed,
                )
            else:
                self._record_success()
            # the non-SPF device workloads leave ring evidence too: an
            # APSP close or TE dispatch sits in the same solve history a
            # forensics dump reconstructs
            self._record_event_trace(
                "device_call",
                layout="apsp" if "apsp" in op else "device",
                solve_ms=elapsed * 1e3,
                detail=op,
            )
            return result, False

        if fallback_fn is None:
            raise last_exc
        return fallback_fn(), True

    # ------------------------------------------------------------------
    # DeltaPath (device-side route-delta) fault domain
    # ------------------------------------------------------------------

    def poll_device_delta(self, area_link_states):
        """Supervised DeltaPath poll: while the breaker is non-CLOSED the
        primary's device state is not serving (and was invalidated on the
        trip), so the answer is always None — the route build takes the
        full path through the fallback. A solve fault inside the poll is
        classified and fed to the breaker exactly like a supervised solve
        failure, then reported as 'no delta' so the event is served by the
        (retrying, degradable) full build."""
        if self.state != CLOSED:
            return None
        poll = getattr(self.primary, "poll_device_delta", None)
        if poll is None:
            return None
        try:
            delta = poll(area_link_states)
        except Exception as exc:
            self._record_failure(classify_solver_error(exc), exc)
            return None
        self._sync_backend_stats(self.primary)
        return delta

    def verify_route_delta(
        self, delta_db, my_node_name, area_link_states, prefix_state
    ):
        """Shadow audit of a delta-built route db: every `audit_interval`-th
        delta build, recompute the full db from the primary (plus the
        existing warm-state cold-mirror audit underneath it, via
        _maybe_audit) and compare. A mismatch means the partial rebuild
        dropped or fabricated a route: self-heal by invalidating the warm
        state and serving the full rebuild — returns the corrected db, or
        None when the delta-built db checks out (or no audit was due)."""
        if self.config.audit_interval <= 0:
            return None
        self._delta_builds_since_audit += 1
        if self._delta_builds_since_audit < self.config.audit_interval:
            return None
        self._delta_builds_since_audit = 0
        self._bump("decision.spf.delta_audit_runs")
        full_db = self.build_route_db(
            my_node_name, area_link_states, prefix_state
        )
        if full_db is None:
            return None
        diff = get_route_delta(full_db, delta_db)
        reverse = get_route_delta(delta_db, full_db)
        if diff.empty() and reverse.empty():
            return None
        self._bump("decision.spf.delta_audit_mismatches")
        log.error(
            "route-delta audit mismatch: %d updates / %d deletes missing "
            "from the delta-built db; forcing the full path",
            len(diff.unicast_routes_to_update) + len(diff.mpls_routes_to_update),
            len(diff.unicast_routes_to_delete) + len(diff.mpls_routes_to_delete),
        )
        forensics_id = self._forensics_dump("delta_audit_mismatch")
        self._emit_sample(
            "ROUTE_DELTA_AUDIT_MISMATCH",
            {"forensics_id": forensics_id or ""},
            {
                "unicast_diverged": len(diff.unicast_routes_to_update)
                + len(diff.unicast_routes_to_delete),
                "mpls_diverged": len(diff.mpls_routes_to_update)
                + len(diff.mpls_routes_to_delete),
            },
        )
        # the partial rebuild derives from the resident warm state: after a
        # route-level divergence it is not to be trusted either
        self._invalidate_primary_warm_state()
        return full_db

    # static-route pass-through: both backends ingest every push so the
    # fallback's static MPLS state is identical the moment it must serve
    def push_static_routes_delta(self, mpls_to_update, mpls_to_delete):
        self.primary.push_static_routes_delta(mpls_to_update, mpls_to_delete)
        self.fallback.push_static_routes_delta(mpls_to_update, mpls_to_delete)

    def static_routes_updated(self) -> bool:
        return self.primary.static_routes_updated()

    def process_static_route_updates(self):
        delta = self.primary.process_static_route_updates()
        self.fallback.process_static_route_updates()  # keep state in lockstep
        return delta

    @property
    def static_mpls_routes(self):
        return self.primary.static_mpls_routes

    def __getattr__(self, name: str):
        # drop-in facade: introspection attributes the supervisor does not
        # shadow (device_solves, mesh, warm_start, ...) read through to the
        # primary backend. Only called for attributes missing on self.
        if name.startswith("_") or name == "primary":
            raise AttributeError(name)
        return getattr(self.primary, name)

    # ------------------------------------------------------------------
    # breaker mechanics
    # ------------------------------------------------------------------

    def _fallback_solve(self, my_node_name, area_link_states, prefix_state):
        self._bump("decision.spf.fallback_solves")
        t0 = self._clock()
        db = self.fallback.build_route_db(
            my_node_name, area_link_states, prefix_state
        )
        self._record_event_trace(
            "fallback_solve",
            layout="cpu",
            solve_ms=(self._clock() - t0) * 1e3,
        )
        self._sync_backend_stats(self.fallback)
        return db

    def _record_event_trace(
        self,
        event: str,
        *,
        layout: str = "none",
        solve_ms: Optional[float] = None,
        fault_kind: Optional[str] = None,
        detail: Optional[str] = None,
    ) -> None:
        """Supervisor-level SolveTrace (fallback solves, classified
        faults): no per-phase detail — the device never ran — but the
        event lands in the same ring as the device traces, so a forensics
        dump shows the degraded serving next to the solves that led to
        it."""
        from openr_tpu.solver.flight_recorder import SolveTrace

        rec = self.recorder
        rec.record(
            SolveTrace(
                seq=rec.next_seq(),
                ts=time.time(),
                area="*",
                node=self.my_node_name,
                event=event,
                layout=layout,
                warm=False,
                solve_ms=solve_ms,
                rounds=None,
                invalidation_rounds=None,
                halo_exchanges=None,
                h2d_bytes=0,
                d2h_bytes=0,
                halo_bytes=0,
                delta_columns=None,
                compile_cache_misses=0,
                breaker_state=self.state,
                sampled=False,
                fault_kind=fault_kind,
                detail=detail,
            )
        )

    def _forensics_dump(self, reason: str) -> Optional[str]:
        """Snapshot the flight-recorder rings + solver context into one
        forensics artifact; returns the dump id referenced from the
        breaker/audit LogSamples. Every fault-domain transition calls
        this BEFORE invalidating warm state, so the dump still holds the
        solve history that led to the fault."""
        import dataclasses

        from openr_tpu.solver.flight_recorder import device_digest

        from openr_tpu.monitor.memledger import get_ledger

        dump = self.recorder.dump(
            reason,
            solver_config=dataclasses.asdict(self.config),
            counters={
                k: v
                for k, v in self.counters.items()
                if k.startswith(("decision.spf.", "decision.mem."))
            },
            mesh_digest=device_digest(getattr(self.primary, "mesh", None)),
            # the full memory-ledger snapshot rides EVERY forensics dump:
            # an OOM post-mortem must name the structures that were
            # resident when the fault domain transitioned
            device_memory=get_ledger().snapshot(),
        )
        self._bump("decision.spf.forensics_dumps")
        self._emit_sample(
            "SOLVER_FORENSICS_DUMPED",
            {"forensics_id": dump["id"], "reason": reason},
            {"traces": sum(len(t) for t in dump["traces"].values())},
        )
        return dump["id"]

    def _record_failure(
        self, kind: str, exc: BaseException, elapsed_s: Optional[float] = None
    ) -> None:
        self.last_fault_kind = kind
        self.consecutive_failures += 1
        self._bump("decision.spf.solver_failures")
        self._bump(f"decision.spf.solver_failures.{kind}")
        self._record_event_trace(
            "fault",
            fault_kind=kind,
            detail=f"{type(exc).__name__}: {exc}"[:200],
        )
        log.warning(
            "supervised solve failure #%d (%s): %s",
            self.consecutive_failures,
            kind,
            exc,
        )
        if kind == FAULT_DEADLINE:
            # a deadline overrun serves its (valid) result but is device
            # evidence worth keeping: snapshot the solve history now,
            # while the slow solve's trace is still in the ring
            self._forensics_dump("deadline")
        if kind == FAULT_DEVICE_OOM:
            # allocator exhaustion: dump IMMEDIATELY, while the ledger
            # still shows the resident set that overflowed the chip —
            # retries and degradations below will start releasing it
            self._forensics_dump("device_oom")
        if elapsed_s is not None and self.watchdog is not None:
            note = getattr(self.watchdog, "note_slow", None)
            if note is not None:
                note(
                    self.config.watchdog_module,
                    elapsed_s,
                    self.config.solve_deadline_s,
                )
        if (
            self.state == CLOSED
            and self.consecutive_failures >= self.config.failure_threshold
        ):
            self._trip()

    def _record_success(self) -> None:
        self.consecutive_failures = 0

    def _trip(self) -> None:
        if self._try_mesh_degrade():
            return  # still CLOSED, serving from the smaller mesh
        log.error(
            "solver circuit breaker TRIPPED after %d consecutive failures "
            "(last fault: %s); serving from CPU oracle",
            self.consecutive_failures,
            self.last_fault_kind,
        )
        self.state = OPEN
        self.recorder.breaker_state = OPEN
        self._bump("decision.spf.breaker_trips")
        self.counters["decision.spf.fallback_active"] = 1
        self.probe_streak = 0
        self._probe_backoff.report_success()  # fresh probe schedule
        self._next_probe_at = self._clock() + self.config.probe_interval_s
        # forensics BEFORE the warm-state drop: the dump must hold the
        # solve history that led here, referenced by id from the sample
        forensics_id = self._forensics_dump("breaker_trip")
        # the device-resident warm state is untrustworthy after a fault:
        # dropping it forces the recovery path to rebuild from cold
        self._invalidate_primary_warm_state()
        self._emit_sample(
            "SOLVER_BREAKER_TRIPPED",
            {
                "fault_kind": self.last_fault_kind or "",
                "forensics_id": forensics_id or "",
            },
            {"consecutive_failures": self.consecutive_failures},
        )

    def _try_mesh_degrade(self) -> bool:
        """One rung of the partial-mesh degradation ladder: on a
        device-loss streak that would trip the breaker, ask the primary to
        re-resolve its mesh over the surviving chips first. A successful
        degradation resets the failure streak and keeps the breaker CLOSED
        — hardware loss costs capacity, not the device path; the CPU
        oracle is the LAST rung, reached only when no viable mesh remains
        (or the fault is not device loss, where a smaller mesh would not
        help)."""
        if not self.config.mesh_degrade:
            return False
        if self.last_fault_kind not in (FAULT_DEVICE_LOSS, FAULT_DEVICE_OOM):
            # a smaller mesh only helps faults that are about the devices
            # themselves: lost chips, or allocator exhaustion (fewer chips
            # = smaller replicated working set per remaining headroom —
            # the replicated->tiled->CPU degrade ladder's middle rungs)
            return False
        degrade = getattr(self.primary, "degrade_mesh", None)
        if degrade is None or not degrade():
            return False
        mesh = getattr(self.primary, "mesh", None)
        shape = dict(mesh.shape) if mesh is not None else None
        log.error(
            "solver mesh degraded after %d consecutive device-loss "
            "failures; re-resolved over surviving chips as %s",
            self.consecutive_failures,
            shape,
        )
        failures = self.consecutive_failures
        self.consecutive_failures = 0
        self._sync_backend_stats(self.primary)
        forensics_id = self._forensics_dump("mesh_degraded")
        self._emit_sample(
            "SOLVER_MESH_DEGRADED",
            {
                "mesh_shape": str(shape or {}),
                "forensics_id": forensics_id or "",
            },
            {
                "consecutive_failures": failures,
                "mesh_devices": int(mesh.devices.size) if mesh else 0,
            },
        )
        return True

    def _close(self) -> None:
        log.warning(
            "solver circuit breaker CLOSED after %d consecutive probe "
            "successes; primary backend restored",
            self.probe_streak,
        )
        self.state = CLOSED
        self.recorder.breaker_state = CLOSED
        self.counters["decision.spf.fallback_active"] = 0
        self.consecutive_failures = 0
        self.probe_streak = 0
        self._emit_sample("SOLVER_BREAKER_CLOSED", {}, {})

    # -- probes ---------------------------------------------------------

    def probe_due(self) -> bool:
        if self.state == CLOSED:
            return False
        if not self._probe_backoff.can_try_now():
            return False
        return self._clock() >= self._next_probe_at

    def maybe_probe(self) -> bool:
        """Run one health probe if the schedule says so; returns whether a
        probe ran. Exposed for tests and loop-less embeddings."""
        if not self.probe_due():
            return False
        self.probe_now()
        return True

    def probe_now(self) -> None:
        """One TPU health-probe solve against the live LSDB (off the hot
        path: results are discarded, only success/failure matters).
        Hysteresis: `probe_successes_to_close` consecutive successes close
        the breaker; one failure resets the streak and backs off."""
        if self._last_inputs is None or self.state == CLOSED:
            return
        self._bump("decision.spf.probe_attempts")
        my_node_name, area_link_states, prefix_state = self._last_inputs
        # a probe must prove the DEVICE works, not the cache: drop any
        # resident solve so this dispatch compiles + solves cold
        self._invalidate_primary_warm_state()
        self._touch_watchdog()
        try:
            self.primary.build_route_db(
                my_node_name, area_link_states, prefix_state
            )
        except Exception as exc:
            self._bump("decision.spf.probe_failures")
            self.last_fault_kind = classify_solver_error(exc)
            self.probe_streak = 0
            self.state = OPEN
            self.recorder.breaker_state = OPEN
            self._probe_backoff.report_error()
            self._next_probe_at = (
                self._clock()
                + self._probe_backoff.get_time_remaining_until_retry()
            )
            log.warning("solver health probe failed (%s): %s",
                        self.last_fault_kind, exc)
            # a failed probe may have left partial device state around
            self._invalidate_primary_warm_state()
            return
        finally:
            self._touch_watchdog()
        self._bump("decision.spf.probe_successes")
        self._sync_backend_stats(self.primary)  # probe solve stats, live
        self.probe_streak += 1
        self._probe_backoff.report_success()
        self._next_probe_at = self._clock() + self.config.probe_interval_s
        if self.probe_streak >= self.config.probe_successes_to_close:
            self._close()
        else:
            self.state = HALF_OPEN
            self.recorder.breaker_state = HALF_OPEN

    # -- warm-state audit ------------------------------------------------

    def _maybe_audit(
        self, db, my_node_name, area_link_states, prefix_state
    ):
        if self.config.audit_interval <= 0:
            return db
        audit = getattr(self.primary, "audit_warm_state", None)
        if audit is None:
            return db
        self._solves_since_audit += 1
        if self._solves_since_audit < self.config.audit_interval:
            return db
        self._solves_since_audit = 0
        self._bump("decision.spf.audit_runs")
        mismatches = audit()
        if not mismatches:
            return db
        self._bump("decision.spf.audit_mismatches", len(mismatches))
        for m in mismatches:
            log.error(
                "warm-state audit mismatch in area %s (node %s): "
                "%d diverged entries, max |delta|=%d",
                m["area"], m["node"], m["entries"], m["max_abs_delta"],
            )
        forensics_id = self._forensics_dump("audit_mismatch")
        self._emit_sample(
            "WARM_STATE_AUDIT_MISMATCH",
            {
                "areas": ",".join(m["area"] for m in mismatches),
                "forensics_id": forensics_id or "",
            },
            {
                "mismatched_areas": len(mismatches),
                "mismatched_entries": sum(
                    m["entries"] for m in mismatches
                ),
            },
        )
        # self-heal: drop the diverged warm state and re-solve cold —
        # the corrected routes replace the suspect ones this same event
        self._invalidate_primary_warm_state()
        self._bump("decision.spf.audit_forced_cold_solves")
        db = self.primary.build_route_db(
            my_node_name, area_link_states, prefix_state
        )
        self._sync_backend_stats(self.primary)
        return db

    # ------------------------------------------------------------------
    # plumbing
    # ------------------------------------------------------------------

    def invalidate_warm_state(self) -> None:
        """Public warm-state drop, forwarded to the primary. Decision's
        start path calls this on every boot so a whole-node restart
        cold-starts its solves exactly like a resharding event would."""
        self._invalidate_primary_warm_state()

    def _invalidate_primary_warm_state(self) -> None:
        invalidate = getattr(self.primary, "invalidate_warm_state", None)
        if invalidate is not None:
            invalidate()
            # invalidations happen on background paths (trips, probes) —
            # sync immediately so monitor surfaces read them live
            self._sync_backend_stats(self.primary)

    def _touch_watchdog(self) -> None:
        if self.watchdog is not None:
            self.watchdog.touch(self.config.watchdog_module)

    def _sync_backend_stats(self, backend) -> None:
        """Fold the serving backend's decision.spf.* counters/histograms
        into this facade's dicts (Decision's sync loop reads only these)."""
        counters = getattr(backend, "counters", None)
        if isinstance(counters, dict):
            for key, value in counters.items():
                if key.startswith(("decision.spf.", "decision.mem.")):
                    self.counters[key] = value
        ensure = getattr(backend, "_ensure_histograms", None)
        if ensure is not None:
            for key, hist in ensure().items():
                if key.startswith("decision.spf."):
                    self._ensure_histograms()[key] = hist
        self._drain_capacity_refusals(backend)

    def _drain_capacity_refusals(self, backend) -> None:
        """Emit one SOLVER_CAPACITY_REFUSED LogSample per headroom-gated
        admission refusal the backend queued since the last sync: the
        capacity model said a layout would not fit and the solver refused
        or degraded residency instead of letting the allocator raise —
        an explicit, typed event instead of silent non-residency."""
        take = getattr(backend, "take_capacity_refusals", None)
        if take is None:
            return
        for refusal in take():
            self._emit_sample(
                "SOLVER_CAPACITY_REFUSED",
                {
                    "layout": str(refusal.get("layout", "")),
                    "capacity_source": str(refusal.get("source", "")),
                },
                {
                    "n_nodes": int(refusal.get("n_nodes") or 0),
                    "predicted_bytes": int(
                        refusal.get("predicted_bytes") or 0
                    ),
                    "headroom_bytes": int(
                        refusal.get("headroom_bytes") or 0
                    ),
                },
            )

    def _emit_sample(self, event: str, strings: Dict, ints: Dict) -> None:
        if self._log_sample_fn is None:
            return
        from openr_tpu.monitor.monitor import LogSample

        sample = LogSample()
        sample.add_string("event", event)
        sample.add_string("breaker_state", self.state)
        for k, v in strings.items():
            sample.add_string(k, v)
        for k, v in ints.items():
            sample.add_int(k, v)
        try:
            self._log_sample_fn(sample)
        except Exception:  # a full/closed monitor queue must not hurt solves
            log.exception("failed to emit solver supervisor log sample")

    def health(self) -> Dict:
        """Degraded-flag surface served by ctrl getSolverHealth and
        `breeze decision solver-health`."""
        mesh = getattr(self.primary, "mesh", None)
        return {
            "degraded": self.state != CLOSED,
            "breaker_state": self.state,
            "solver_mesh": dict(mesh.shape) if mesh is not None else None,
            "mesh_degradations": self.counters.get(
                "decision.spf.mesh_degradations", 0
            ),
            "fallback_active": int(self.state != CLOSED),
            "consecutive_failures": self.consecutive_failures,
            "probe_streak": self.probe_streak,
            "last_fault_kind": self.last_fault_kind,
            "probe_attempts": self.counters.get(
                "decision.spf.probe_attempts", 0
            ),
            "probe_successes": self.counters.get(
                "decision.spf.probe_successes", 0
            ),
            "probe_failures": self.counters.get(
                "decision.spf.probe_failures", 0
            ),
            "audit_runs": self.counters.get("decision.spf.audit_runs", 0),
            "audit_mismatches": self.counters.get(
                "decision.spf.audit_mismatches", 0
            ),
            "delta_audit_runs": self.counters.get(
                "decision.spf.delta_audit_runs", 0
            ),
            "delta_audit_mismatches": self.counters.get(
                "decision.spf.delta_audit_mismatches", 0
            ),
            "apsp_closes": self.counters.get("decision.spf.apsp_closes", 0),
            "apsp_audit_mismatches": self.counters.get(
                "decision.spf.apsp_audit_mismatches", 0
            ),
            # last-solve timing picture (docs/Monitoring.md): the gauges
            # next to solve_ms_last so `breeze decision solver-health`
            # shows the full per-event latency split without waiting for
            # the phase histograms to fill
            "solve_ms_last": getattr(self.primary, "solve_ms_last", None),
            "delta_extract_ms_last": getattr(
                self.primary, "delta_extract_ms_last", None
            ),
            "apsp_close_ms_last": getattr(
                self.primary, "apsp_close_ms_last", None
            ),
            # flight-recorder ring + forensics state
            "traces": self.recorder.stats(),
            "forensics": self.recorder.forensics_stats(),
            # device-memory observatory rows (monitor/memledger.py):
            # resident totals, the exact-accounting verdict, and the last
            # headroom-gated capacity refusal
            "device_memory": self._device_memory_health(),
        }

    def _device_memory_health(self) -> Dict:
        from openr_tpu.monitor.memledger import get_ledger

        ledger = get_ledger()
        return {
            "live_bytes": ledger.live_bytes,
            "peak_bytes": ledger.peak_bytes,
            "registered_bytes": ledger.registered_bytes,
            "freed_bytes": ledger.freed_bytes,
            "exact": ledger.check(),
            "structures": ledger.structure_bytes(),
            "capacity": ledger.capacity(),
            "capacity_refusals": ledger.capacity_refusals,
            "last_refusal": ledger.last_refusal,
        }
