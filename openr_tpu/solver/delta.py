"""DeltaPath route build: O(changes) per event instead of O(table).

The warm solver already repairs only the distance entries an LSDB event
touched (ops/spf.py:_sell_solver_warm) and, since the device-side delta
extraction landed, reports exactly WHICH destination columns moved
(`_AreaSolve.take_route_delta`). This module closes the remaining host-side
gap: instead of rebuilding the whole RouteDatabase and diffing it against
the previous one (`get_route_delta`, O(prefixes) per event even for a
single link flap), `DeltaRouteBuilder` recomputes only the prefixes and
node-label routes the device delta names and emits the
`DecisionRouteUpdate` directly — the DeltaPath end-to-end difference
propagation (PAPERS.md, arxiv 1808.06893) on the host side.

Soundness: a route entry from `my_node_name`'s perspective is a function of
(a) the distance columns of its announcers / label targets, (b) my own
out-link attributes (the nexthop triangle's weight column, link up/down,
addresses), (c) the transit/overload mask, (d) node labels, and (e) the
prefix advertisements themselves. The device delta covers (a) exactly; the
solver refuses to produce a delta for events touching (b) or (c)
(`_AreaSolve._finish_delta` qualification), Decision forces the full path
for (d) and batches that structurally change the LSDB, and Decision feeds
(e) in as explicit dirty prefixes. SR_MPLS-forwarding prefixes (KSP2 path
traces can move on edges no distance column reflects) are always dirty via
`PrefixState.mpls_forwarding_prefixes`. RFC 5286 LFA adds exactly one
input beyond the announcer columns — the ME column, read by every
alt-neighbor row's inequality threshold — so with an APSP-capable solver
(`lfa_delta_ready`, docs/Apsp.md) the delta path stays enabled under
`compute_lfa_paths`: the solver's poll answers None whenever the changed
set contains me (poisoning exactly the events whose LFA thresholds moved),
and every other LFA input is a changed-announcer column the dirty set
already covers. Solvers without a resident APSP state keep the historical
force-full behavior. Everything else is provably unchanged and is neither
recomputed nor diffed.

The correctness backstop is the SolverSupervisor's route-delta shadow audit
(`verify_route_delta`): every Nth delta-built db is compared against a full
rebuild, and mismatches self-heal exactly like warm-state audit hits.
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, Optional, Set, Tuple

from openr_tpu.solver.routes import (
    DecisionRouteDb,
    DecisionRouteUpdate,
    apply_route_delta,
    get_route_delta,
)
from openr_tpu.types import is_mpls_label_valid

log = logging.getLogger(__name__)


class DeltaRouteBuilder:
    """Builds (new route db, update) per rebuild, taking the O(changes)
    partial path whenever the solver offers a device delta and the event
    class qualifies, else the classic full build + diff. Owned by Decision;
    drivable synchronously by tests without an event loop."""

    def __init__(self, solver) -> None:
        self.solver = solver
        # label -> set of nodes advertising it (collision detection for the
        # partial node-label rebuild); rebuilt lazily after any full build,
        # so it can never span a structural change
        self._label_index: Optional[Dict[int, Set[str]]] = None
        self.last_error: Optional[BaseException] = None
        self.delta_builds = 0
        self.full_builds = 0

    # ------------------------------------------------------------------

    def build(
        self,
        my_node_name: str,
        area_link_states: Dict,
        prefix_state,
        prev_db: Optional[DecisionRouteDb],
        *,
        dirty_prefixes: Set = frozenset(),
        force_full: bool = False,
        policy_fn: Optional[Callable] = None,
    ) -> Tuple[Optional[DecisionRouteDb], Optional[DecisionRouteUpdate], bool]:
        """Returns (new_db, update, used_delta). new_db is None when this
        node is in no area's graph (build_route_db contract). policy_fn, if
        given, is applied to every (re)computed unicast entry before
        diffing — the RibPolicy hook."""
        self.last_error = None
        changed_nodes: Optional[Set[str]] = None
        try:
            # always drain the solver's accumulated delta, even when this
            # rebuild is forced full — a stale column set left pending
            # would otherwise ride into a later event's dirty set
            changed_nodes = self.solver.poll_device_delta(area_link_states)
        except Exception as exc:  # solve fault: the full path's supervised
            self.last_error = exc  # build_route_db owns retry/fallback
            log.warning("device delta poll failed: %s", exc)
        lfa_on = getattr(self.solver, "compute_lfa_paths", False)
        lfa_ready = getattr(self.solver, "lfa_delta_ready", None)
        if (
            changed_nodes is not None
            and not force_full
            and prev_db is not None
            and (not lfa_on or (lfa_ready is not None and lfa_ready()))
        ):
            try:
                out = self._build_delta(
                    my_node_name,
                    area_link_states,
                    prefix_state,
                    prev_db,
                    changed_nodes,
                    set(dirty_prefixes),
                    policy_fn,
                )
                if out is not None:
                    self.delta_builds += 1
                    return out[0], out[1], True
            except Exception as exc:
                # a delta-path bug must degrade to the full build, never
                # wedge convergence
                self.last_error = exc
                log.exception("delta route build failed; falling back")
        return self._build_full(
            my_node_name, area_link_states, prefix_state, prev_db, policy_fn
        )

    # ------------------------------------------------------------------

    def _build_full(
        self, my_node_name, area_link_states, prefix_state, prev_db, policy_fn
    ):
        new_db = self.solver.build_route_db(
            my_node_name, area_link_states, prefix_state
        )
        self._label_index = None  # labels may have moved; rebuild lazily
        self.full_builds += 1
        if new_db is None:
            return None, None, False
        if policy_fn is not None:
            for entry in new_db.unicast_entries.values():
                policy_fn(entry)
        delta = get_route_delta(new_db, prev_db or DecisionRouteDb())
        return new_db, delta, False

    def _build_delta(
        self,
        my_node_name: str,
        area_link_states: Dict,
        prefix_state,
        prev_db: DecisionRouteDb,
        changed_nodes: Set[str],
        dirty_prefixes: Set,
        policy_fn: Optional[Callable],
    ) -> Optional[Tuple[DecisionRouteDb, DecisionRouteUpdate]]:
        """The partial rebuild; None bails to the full path (collision
        cases whose arbitration needs the whole table)."""
        dirty = dirty_prefixes
        dirty |= prefix_state.prefixes_for_nodes(changed_nodes)
        dirty |= set(prefix_state.mpls_forwarding_prefixes)

        update = DecisionRouteUpdate()
        scratch: Dict = {}
        for prefix in sorted(dirty):
            prefix_entries = prefix_state.prefixes.get(prefix)
            new_entry = None
            if prefix_entries:
                self.solver.build_unicast_route(
                    scratch,
                    my_node_name,
                    prefix,
                    prefix_entries,
                    area_link_states,
                    prefix_state,
                )
                new_entry = scratch.pop(prefix, None)
            old_entry = prev_db.unicast_entries.get(prefix)
            if new_entry is None:
                if old_entry is not None:
                    update.unicast_routes_to_delete.append(prefix)
                continue
            if policy_fn is not None:
                policy_fn(new_entry)
            if old_entry is None or old_entry != new_entry:
                update.unicast_routes_to_update.append(new_entry)

        # node-label routes of the changed destinations (their distance /
        # nexthop set moved); adjacency-label routes depend only on my own
        # links, which never qualify for the delta path
        label_index = self._ensure_label_index(area_link_states)
        for area, link_state in sorted(area_link_states.items()):
            adj_dbs = link_state.get_adjacency_databases()
            for node in sorted(changed_nodes):
                adj_db = adj_dbs.get(node)
                if adj_db is None:
                    continue
                label = adj_db.node_label
                if label == 0 or not is_mpls_label_valid(label):
                    continue
                if len(label_index.get(label, ())) > 1:
                    # duplicate-label arbitration scans the whole table:
                    # leave it to the full path
                    return None
                entry = self.solver.build_node_label_route(
                    my_node_name, area, adj_db, area_link_states
                )
                old = prev_db.mpls_entries.get(label)
                if entry is None:
                    if old is not None:
                        update.mpls_routes_to_delete.append(label)
                elif old is None or old != entry:
                    update.mpls_routes_to_update.append(entry)

        return apply_route_delta(prev_db, update), update

    def _ensure_label_index(self, area_link_states) -> Dict[int, Set[str]]:
        """node-label -> advertising nodes, across areas. Built once per
        full build (labels only move in batches that force the full path),
        so delta events pay O(changes) lookups, not an O(n) scan."""
        if self._label_index is None:
            index: Dict[int, Set[str]] = {}
            for link_state in area_link_states.values():
                for adj_db in link_state.get_adjacency_databases().values():
                    if adj_db.node_label:
                        index.setdefault(adj_db.node_label, set()).add(
                            adj_db.this_node_name
                        )
            self._label_index = index
        return self._label_index
