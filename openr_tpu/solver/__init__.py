"""SPF solvers: route types, CPU oracle, and the TPU batched solver.

The solver consumes LinkState + PrefixState and produces a DecisionRouteDb
(unicast IP / IP2MPLS routes + MPLS label routes), mirroring
openr/decision/Decision.cpp SpfSolver. Two interchangeable backends:
  - cpu.SpfSolver: faithful oracle (per-source memoized Dijkstra)
  - tpu.TpuSpfSolver: batched min-plus solver on TPU via JAX
plus supervisor.SolverSupervisor, the fault-domain facade that serves the
TPU backend under a circuit breaker with the CPU oracle as the degraded
path (docs/Robustness.md), and flight_recorder.FlightRecorder, the
per-solve trace ring + forensics layer the supervisor records into
(docs/Monitoring.md "Flight recorder & profiling").
"""

from openr_tpu.solver.routes import (
    DecisionRouteDb,
    DecisionRouteUpdate,
    RibMplsEntry,
    RibUnicastEntry,
    apply_route_delta,
    get_route_delta,
)
from openr_tpu.solver.cpu import SpfSolver
from openr_tpu.solver.delta import DeltaRouteBuilder
from openr_tpu.solver.flight_recorder import FlightRecorder, SolveTrace
from openr_tpu.solver.supervisor import SolverSupervisor, SupervisorConfig
from openr_tpu.solver.tpu import TpuSpfSolver

__all__ = [
    "FlightRecorder",
    "SolveTrace",
    "SolverSupervisor",
    "SupervisorConfig",
    "TpuSpfSolver",
    "DecisionRouteDb",
    "DecisionRouteUpdate",
    "DeltaRouteBuilder",
    "RibMplsEntry",
    "RibUnicastEntry",
    "apply_route_delta",
    "get_route_delta",
    "SpfSolver",
]
