"""RIB value types and route-db diffing.

Equivalents of openr/decision/RibEntry.h (RibUnicastEntry:37, RibMplsEntry:93),
openr/decision/RouteUpdate.h (DecisionRouteUpdate) and the getRouteDelta diff
in openr/decision/Decision.cpp:47-85.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from openr_tpu.types import (
    IpPrefix,
    MplsRoute,
    NextHop,
    PrefixEntry,
    UnicastRoute,
)


@dataclass
class RibUnicastEntry:
    """A computed unicast route: prefix + ECMP nexthop set + best-path info."""

    prefix: IpPrefix
    nexthops: Set[NextHop] = field(default_factory=set)
    best_prefix_entry: Optional[PrefixEntry] = None
    best_area: Optional[str] = None
    do_not_install: bool = False
    best_nexthop: Optional[NextHop] = None  # for BGP route re-advertising

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, RibUnicastEntry)
            and self.prefix == other.prefix
            and self.nexthops == other.nexthops
            and self.best_prefix_entry == other.best_prefix_entry
            and self.best_nexthop == other.best_nexthop
            and self.do_not_install == other.do_not_install
        )

    def to_unicast_route(self) -> UnicastRoute:
        return UnicastRoute(self.prefix, tuple(sorted(
            self.nexthops, key=lambda nh: (nh.address, nh.iface or "")
        )))


@dataclass
class RibMplsEntry:
    """A computed MPLS label route: top label + nexthop set."""

    label: int
    nexthops: Set[NextHop] = field(default_factory=set)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, RibMplsEntry)
            and self.label == other.label
            and self.nexthops == other.nexthops
        )

    def to_mpls_route(self) -> MplsRoute:
        return MplsRoute(self.label, tuple(sorted(
            self.nexthops, key=lambda nh: (nh.address, nh.iface or "")
        )))


@dataclass
class DecisionRouteDb:
    """Full computed RIB from one node's perspective."""

    unicast_entries: Dict[IpPrefix, RibUnicastEntry] = field(
        default_factory=dict
    )
    mpls_entries: Dict[int, RibMplsEntry] = field(default_factory=dict)


@dataclass
class DecisionRouteUpdate:
    """Incremental route delta published to Fib (RouteUpdate.h)."""

    unicast_routes_to_update: List[RibUnicastEntry] = field(
        default_factory=list
    )
    unicast_routes_to_delete: List[IpPrefix] = field(default_factory=list)
    mpls_routes_to_update: List[RibMplsEntry] = field(default_factory=list)
    mpls_routes_to_delete: List[int] = field(default_factory=list)
    perf_events: Optional[object] = None
    # monotonic stage trace riding the delta to Fib (monitor.spans.Span);
    # host-local only — never serialized, never compared
    span: Optional[object] = None

    def empty(self) -> bool:
        return not (
            self.unicast_routes_to_update
            or self.unicast_routes_to_delete
            or self.mpls_routes_to_update
            or self.mpls_routes_to_delete
        )


def apply_route_delta(
    old_db: DecisionRouteDb, delta: DecisionRouteUpdate
) -> DecisionRouteDb:
    """The diff's inverse: fold an update into a route db, returning a new
    db that shares unchanged entry objects with the old one. The DeltaPath
    route build uses this to keep Decision's full RouteDatabase current
    without rebuilding it — apply_route_delta(old, get_route_delta(new,
    old)) == new for any pair of dbs."""
    unicast = dict(old_db.unicast_entries)
    mpls = dict(old_db.mpls_entries)
    for prefix in delta.unicast_routes_to_delete:
        unicast.pop(prefix, None)
    for entry in delta.unicast_routes_to_update:
        unicast[entry.prefix] = entry
    for label in delta.mpls_routes_to_delete:
        mpls.pop(label, None)
    for entry in delta.mpls_routes_to_update:
        mpls[entry.label] = entry
    return DecisionRouteDb(unicast_entries=unicast, mpls_entries=mpls)


def get_route_delta(
    new_db: DecisionRouteDb, old_db: DecisionRouteDb
) -> DecisionRouteUpdate:
    """Diff two route dbs (Decision.cpp:47-85)."""
    delta = DecisionRouteUpdate()
    for prefix, entry in new_db.unicast_entries.items():
        old = old_db.unicast_entries.get(prefix)
        if old is not None and old == entry:
            continue
        delta.unicast_routes_to_update.append(entry)
    for prefix in old_db.unicast_entries:
        if prefix not in new_db.unicast_entries:
            delta.unicast_routes_to_delete.append(prefix)
    for label, entry in new_db.mpls_entries.items():
        old = old_db.mpls_entries.get(label)
        if old is not None and old == entry:
            continue
        delta.mpls_routes_to_update.append(entry)
    for label in old_db.mpls_entries:
        if label not in new_db.mpls_entries:
            delta.mpls_routes_to_delete.append(label)
    return delta
