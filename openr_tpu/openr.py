"""Daemon composition root.

Behavioral port of openr/Main.cpp: builds the inter-module queues
(Main.cpp:244-250), constructs every module against its seams, starts them
in dependency order ConfigStore → Monitor → KvStore → PrefixManager →
PrefixAllocator → Spark → LinkMonitor → Decision → Fib → CtrlServer
(Main.cpp:355-586) and stops in reverse with queue closing
(Main.cpp:597-654). One asyncio loop replaces the per-module EventBase
threads; each module is an independent task set on that loop, watched by
the Watchdog.

Seams (all injectable, mirroring the reference's test wrappers):
  - io_provider:  Spark's packet transport (UDP or MockIoNetwork endpoint)
  - kv_transport: KvStore's peer transport (TCP or InProcessTransport)
  - fib_service:  route programming agent (NetlinkFibHandler or mock)
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from openr_tpu.config import Config
from openr_tpu.configstore import PersistentStore
from openr_tpu.ctrl import CtrlServer
from openr_tpu.decision import Decision, DecisionConfig
from openr_tpu.fib import Fib, FibConfig
from openr_tpu.kvstore import KvStore, KvStoreClient, KvStoreParams
from openr_tpu.linkmonitor.link_monitor import LinkMonitor, LinkMonitorConfig
from openr_tpu.messaging import ReplicateQueue
from openr_tpu.monitor import (
    MetricsExporter,
    Monitor,
    Watchdog,
    WatchdogConfig,
)
from openr_tpu.platform import MockFibHandler
from openr_tpu.prefixmanager import PrefixManager, PrefixManagerConfig
from openr_tpu.spark.spark import Spark, SparkConfig as SparkModuleConfig

log = logging.getLogger(__name__)


class OpenrDaemon:
    """All modules of one Open/R node on one asyncio loop."""

    def __init__(
        self,
        config: Config,
        *,
        io_provider,
        kv_transport,
        fib_service=None,
        config_store_path: Optional[str] = None,
        ctrl_port: Optional[int] = None,
        kvstore_host: str = "127.0.0.1",
        kvstore_port: int = 0,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        from openr_tpu.kvstore import KvStoreTcpServer, TcpTransport

        self.config = config
        self._loop = loop
        # real-socket deployment: when KvStore peers over TCP, this daemon
        # must also *serve* the peer RPC surface, Spark must advertise the
        # serving port in its handshake, and LinkMonitor must peer by
        # host:port instead of node id
        self._kv_tcp = isinstance(kv_transport, TcpTransport)
        self._kv_transport = kv_transport
        self.kvstore_server: Optional[KvStoreTcpServer] = None
        c = config.config
        node = c.node_name
        areas = config.get_area_ids()

        # --- queues (Main.cpp:244-250) --------------------------------
        self.route_updates_queue = ReplicateQueue()
        self.interface_updates_queue = ReplicateQueue()
        self.neighbor_updates_queue = ReplicateQueue()
        self.prefix_updates_queue = ReplicateQueue()
        self.static_routes_queue = ReplicateQueue()
        self.log_sample_queue = ReplicateQueue()

        # --- config store ---------------------------------------------
        self.config_store = PersistentStore(
            config_store_path or f"/tmp/openr_tpu_{node}.bin",
            dryrun=config_store_path is None,
            loop=loop,
        )

        # --- monitor + watchdog + exporter ----------------------------
        mc = c.monitor_config
        self.monitor = Monitor(
            node,
            self.log_sample_queue.get_reader(),
            max_event_log=mc.max_event_log,
            rollup_window_s=mc.rollup_window_s,
            rollup_max_windows=mc.rollup_max_windows,
            loop=loop,
        )
        self.exporter = MetricsExporter(
            self.monitor,
            push_target=mc.exporter_push_target,
            push_interval_s=mc.exporter_push_interval_s,
            loop=loop,
        )
        # the exporter registers like any module so its own overhead
        # metrics (monitor.exporter.*) ride every scrape
        self.monitor.register_module("monitor", self.exporter)
        self.watchdog: Optional[Watchdog] = None
        if c.enable_watchdog:
            self.watchdog = Watchdog(
                WatchdogConfig(
                    interval_s=c.watchdog_config.interval_s,
                    thread_timeout_s=c.watchdog_config.thread_timeout_s,
                    max_memory_mb=c.watchdog_config.max_memory_mb,
                ),
                loop=loop,
            )

        # --- kvstore ---------------------------------------------------
        self.kvstore = KvStore(
            node,
            areas,
            kv_transport,
            KvStoreParams(
                node_id=node,
                ttl_decrement_ms=c.kvstore_config.ttl_decrement_ms,
                flood_rate=(
                    float(c.kvstore_config.flood_rate.flood_msg_per_sec)
                    if c.kvstore_config.flood_rate is not None
                    else None
                ),
                enable_flood_optimization=(
                    c.kvstore_config.enable_flood_optimization
                ),
                is_flood_root=c.kvstore_config.is_flood_root,
                use_native_store=c.kvstore_config.enable_native_store,
                damping_enabled=c.kvstore_config.damping_enabled,
                damping_half_life_s=c.kvstore_config.damping_half_life_s,
                damping_max_hold_s=c.kvstore_config.damping_max_hold_s,
                damping_suppress_limit=(
                    c.kvstore_config.damping_suppress_limit
                ),
                damping_reuse_limit=c.kvstore_config.damping_reuse_limit,
                quarantine_enabled=c.kvstore_config.quarantine_enabled,
                peer_suspect_failures=(
                    c.kvstore_config.peer_suspect_failures
                ),
                peer_quarantine_failures=(
                    c.kvstore_config.peer_quarantine_failures
                ),
                peer_probe_min_backoff=(
                    c.kvstore_config.peer_probe_min_backoff_s
                ),
                peer_probe_max_backoff=(
                    c.kvstore_config.peer_probe_max_backoff_s
                ),
                peer_probe_successes=c.kvstore_config.peer_probe_successes,
                anti_entropy_enabled=(
                    c.kvstore_config.anti_entropy_enabled
                ),
                anti_entropy_interval_s=float(
                    c.kvstore_config.sync_interval_s
                ),
                flood_duplicate_budget=(
                    c.kvstore_config.flood_duplicate_budget
                ),
                forensics_dir=c.decision_config.solver_forensics_dir,
            ),
            loop=loop,
            # flood-trace samples (FLOOD_TRACE) drain into the monitor's
            # event-log ring next to the convergence traces
            log_sample_fn=self.log_sample_queue.push,
        )
        # mutual-TLS contexts (Main.cpp:517-543): one server + one client
        # context shared by the ctrl server and the KvStore peering
        server_ssl = client_ssl = None
        if c.enable_secure_thrift_server:
            from openr_tpu.utils.tls import (
                client_ssl_context,
                server_ssl_context,
            )

            if not (c.x509_cert_path and c.x509_key_path and c.x509_ca_path):
                raise ValueError(
                    "enable_secure_thrift_server requires x509_cert_path, "
                    "x509_key_path and x509_ca_path"
                )
            server_ssl = server_ssl_context(
                c.x509_cert_path, c.x509_key_path, c.x509_ca_path
            )
            client_ssl = client_ssl_context(
                c.x509_ca_path, c.x509_cert_path, c.x509_key_path
            )
            if self._kv_tcp:
                kv_transport.set_ssl_context(client_ssl)
        self._server_ssl = server_ssl
        if self._kv_tcp:
            self.kvstore_server = KvStoreTcpServer(
                self.kvstore,
                host=kvstore_host,
                port=kvstore_port,
                ssl_context=server_ssl,
                tls_acceptable_peers=c.tls_acceptable_peers or None,
            )
        # config_store attaches the warm-boot version floors: after a
        # graceful restart, self-originated keys (prefix advertisements,
        # fibTime markers) re-advertise strictly above the versions peers
        # held through the GR window (docs/Robustness.md)
        self.kvstore_client = KvStoreClient(
            self.kvstore, node, loop, config_store=self.config_store
        )

        # --- prefix manager -------------------------------------------
        self.prefix_manager = PrefixManager(
            PrefixManagerConfig(node_name=node, areas=areas),
            self.kvstore_client,
            config_store=self.config_store,
            prefix_updates=self.prefix_updates_queue.get_reader(),
            route_updates=self.route_updates_queue.get_reader(),
            loop=loop,
        )

        # --- prefix allocator (optional) -------------------------------
        self.prefix_allocator = None
        if config.is_prefix_allocation_enabled():
            from openr_tpu.allocators import (
                PrefixAllocationMode,
                PrefixAllocationParams,
                PrefixAllocator,
                PrefixAllocatorConfig,
            )
            from openr_tpu.types import IpPrefix, PrefixEntry, PrefixType

            pac = c.prefix_allocation_config
            params = None
            if pac.seed_prefix and pac.allocate_prefix_len:
                params = PrefixAllocationParams(
                    IpPrefix(pac.seed_prefix), pac.allocate_prefix_len
                )
            self.prefix_allocator = PrefixAllocator(
                PrefixAllocatorConfig(
                    node_name=node,
                    mode=PrefixAllocationMode(pac.prefix_allocation_mode),
                    params=params,
                    set_loopback_addr=pac.set_loopback_addr,
                    loopback_iface=pac.loopback_interface,
                ),
                self.kvstore_client,
                config_store=self.config_store,
                on_advertise=lambda entry: (
                    self.prefix_manager.advertise_prefixes([entry])
                ),
                on_withdraw=lambda prefix: (
                    self.prefix_manager.withdraw_prefixes(
                        [
                            PrefixEntry(
                                prefix=prefix,
                                type=PrefixType.PREFIX_ALLOCATOR,
                            )
                        ]
                    )
                ),
                loop=loop,
            )

        # --- spark -----------------------------------------------------
        sc = c.spark_config
        self.spark = Spark(
            SparkModuleConfig(
                node_name=node,
                domain=c.domain,
                area_configs=[
                    (a.area_id, r)
                    for a in c.areas
                    for r in (a.neighbor_regexes or [".*"])
                ]
                or [("0", ".*")],
                hello_time=sc.hello_time_s,
                fastinit_hello_time=sc.fastinit_hello_time_ms / 1000.0,
                keepalive_time=sc.keepalive_time_s,
                hold_time=sc.hold_time_s,
                graceful_restart_time=sc.graceful_restart_time_s,
                **({"kvstore_host": kvstore_host} if self._kv_tcp else {}),
            ),
            io_provider,
            self.neighbor_updates_queue,
            loop=loop,
        )

        # --- link monitor ---------------------------------------------
        lmc = c.link_monitor_config
        self.link_monitor = LinkMonitor(
            LinkMonitorConfig(
                node_name=node,
                enable_rtt_metric=lmc.use_rtt_metric,
                flap_initial_backoff=lmc.linkflap_initial_backoff_ms / 1000,
                flap_max_backoff=lmc.linkflap_max_backoff_ms / 1000,
                areas=areas,
                peer_addr_mode="tcp" if self._kv_tcp else "node_id",
            ),
            self.neighbor_updates_queue.get_reader(),
            self.kvstore,
            self.spark,
            config_store=self.config_store,
            interface_updates_queue=self.interface_updates_queue,
            loop=loop,
        )

        # --- decision --------------------------------------------------
        dc = c.decision_config
        self.decision = Decision(
            DecisionConfig(
                my_node_name=node,
                areas=areas,
                solver_backend=dc.solver_backend,
                solver_mesh=(
                    tuple(dc.solver_mesh) if dc.solver_mesh else None
                ),
                solver_supervised=dc.solver_supervised,
                solver_failure_threshold=dc.solver_failure_threshold,
                solver_max_attempts=dc.solver_max_attempts,
                solver_deadline_s=dc.solver_deadline_s,
                solver_probe_interval_s=dc.solver_probe_interval_s,
                solver_probe_successes=dc.solver_probe_successes,
                solver_audit_interval=dc.solver_audit_interval,
                solver_mesh_degrade=dc.solver_mesh_degrade,
                solver_apsp=dc.solver_apsp,
                solver_apsp_max_nodes=dc.solver_apsp_max_nodes,
                solver_trace_ring=dc.solver_trace_ring,
                solver_trace_sample_every=dc.solver_trace_sample_every,
                solver_forensics_dir=dc.solver_forensics_dir,
                solver_mem_headroom_frac=dc.solver_mem_headroom_frac,
                solver_mem_capacity_bytes=dc.solver_mem_capacity_bytes,
                enable_v4=c.enable_v4,
                compute_lfa_paths=dc.compute_lfa_paths,
                enable_ordered_fib=c.enable_ordered_fib_programming,
                bgp_use_igp_metric=c.bgp_use_igp_metric,
                debounce_min=dc.debounce_min_ms / 1000.0,
                debounce_max=dc.debounce_max_ms / 1000.0,
                eor_time_s=float(c.eor_time_s or 0),
            ),
            self.kvstore.updates_queue.get_reader(),
            self.route_updates_queue,
            static_routes_updates=self.static_routes_queue.get_reader(),
            loop=loop,
            # solver fault domain: the supervisor stamps solve sections
            # into the watchdog heartbeat map and emits breaker/audit
            # events into the monitor's log-sample ring
            watchdog=self.watchdog,
            log_sample_fn=self.log_sample_queue.push,
        )

        # --- fib -------------------------------------------------------
        if fib_service is None:
            if c.enable_fib_agent:
                # standalone native agent (platform_linux equivalent) at
                # fib_port; Fib's aliveSince keep-alive handles restarts
                from openr_tpu.platform import RemoteFibService

                fib_service = RemoteFibService(port=c.fib_port)
            elif config.is_netlink_fib_handler_enabled():
                from openr_tpu.platform import NetlinkFibHandler

                fib_service = NetlinkFibHandler(loop=loop)
            else:
                fib_service = MockFibHandler()
        self.fib_service = fib_service
        self.fib = Fib(
            FibConfig(
                my_node_name=node,
                dryrun=c.dryrun,
                enable_segment_routing=c.enable_segment_routing,
                enable_ordered_fib=c.enable_ordered_fib_programming,
                has_eor_time=c.eor_time_s is not None,
                cold_start_duration=c.fib_config.cold_start_duration_s,
                stale_sweep_deadline_s=c.fib_config.stale_sweep_deadline_s,
                # restart forensics share the solver fault domain's
                # artifact directory (PR 13 dump path)
                forensics_dir=dc.solver_forensics_dir,
            ),
            fib_service,
            self.route_updates_queue.get_reader(),
            self.interface_updates_queue.get_reader(),
            kvstore_client=self.kvstore_client,
            # finished convergence spans (CONVERGENCE_TRACE) drain into the
            # monitor's event-log ring like every other LogSample
            log_sample_fn=self.log_sample_queue.push,
            loop=loop,
        )

        # --- streaming control plane (docs/Streaming.md) ---------------
        from openr_tpu.streaming import (
            AdmissionConfig,
            AdmissionController,
            StreamConfig,
            StreamManager,
        )

        stc = c.stream_config
        self.stream_manager = StreamManager(
            kvstore_updates=self.kvstore.updates_queue,
            route_updates=self.route_updates_queue,
            config=StreamConfig(
                subscriber_max_pending=stc.subscriber_max_pending,
                coalesce_budget=stc.coalesce_budget,
                max_subscribers=stc.max_subscribers,
                shared_encode=stc.shared_encode,
            ),
            loop=loop,
        )
        self.admission = AdmissionController(
            AdmissionConfig(
                capacity=stc.admission_capacity,
                max_wait_s=stc.admission_max_wait_s,
                max_queue=stc.admission_max_queue,
                max_queue_per_client=stc.admission_max_queue_per_client,
            )
        )

        # --- state journal (docs/Journal.md) ---------------------------
        from openr_tpu.journal import JournalConfig, StateJournal

        jc = c.journal_config
        self.journal = StateJournal(
            node,
            JournalConfig(
                enabled=jc.enabled,
                ring_size=jc.ring_size,
                key_history=jc.key_history,
                sample_every=jc.sample_every,
                path=jc.path,
                flush_interval_s=jc.flush_interval_s,
                min_compact_bytes=jc.min_compact_bytes,
            ),
            kvstore_updates=self.kvstore.updates_queue,
            route_updates=self.route_updates_queue,
            # replay re-derives routes through the CPU oracle with the
            # same flags Decision solves under
            solver_flags={
                "enable_v4": c.enable_v4,
                "compute_lfa_paths": dc.compute_lfa_paths,
                "enable_ordered_fib": c.enable_ordered_fib_programming,
                "bgp_use_igp_metric": c.bgp_use_igp_metric,
            },
            loop=loop,
        )

        # --- ctrl server ----------------------------------------------
        self.ctrl_server = CtrlServer(
            node,
            host="127.0.0.1",
            port=ctrl_port if ctrl_port is not None else c.openr_ctrl_port,
            kvstore=self.kvstore,
            decision=self.decision,
            fib=self.fib,
            link_monitor=self.link_monitor,
            prefix_manager=self.prefix_manager,
            monitor=self.monitor,
            exporter=self.exporter,
            config_store=self.config_store,
            config=config,
            stream_manager=self.stream_manager,
            admission=self.admission,
            journal=self.journal,
            loop=loop,
            ssl_context=self._server_ssl,
            tls_acceptable_peers=c.tls_acceptable_peers or None,
        )

        for name, module in (
            ("kvstore", self.kvstore),
            ("decision", self.decision),
            ("fib", self.fib),
            ("link_monitor", self.link_monitor),
            ("spark", self.spark),
            ("prefix_manager", self.prefix_manager),
            # the fan-out + admission layers register like modules so
            # ctrl.stream.* / ctrl.admission.* ride every scrape
            ("ctrl_stream", self.stream_manager),
            ("ctrl_admission", self.admission),
            ("journal", self.journal),
        ):
            self.monitor.register_module(name, module)

    # ------------------------------------------------------------------

    async def start(self) -> int:
        """Start modules in dependency order; returns the ctrl port."""
        if self.kvstore_server is not None:
            # serve KvStore peering before anyone can discover us; the
            # bound (possibly ephemeral) port goes into Spark's handshake
            await self.kvstore_server.start()
            self.spark.config.kvstore_cmd_port = self.kvstore_server.port
        self.monitor.start()
        self.exporter.start()  # push loop only when a sink is configured
        if self.watchdog is not None:
            for name in ("kvstore", "decision", "fib", "link_monitor"):
                self.watchdog.add_module(name)
            self.watchdog.start()
        self.prefix_manager.start()
        if self.prefix_allocator is not None:
            self.prefix_allocator.start()
        self.link_monitor.start()
        self.decision.start()
        self.fib.start()
        # fan-out dispatch must drain before the ctrl server can accept
        # subscribers (its readers consume the module queues continuously)
        self.stream_manager.start()
        self.journal.start()
        port = await self.ctrl_server.start()
        if self.config.config.enable_bgp_peering:
            # extension seam (Main.cpp:589-595, plugin/Plugin.h:24-34);
            # only build PluginArgs (and register its queue reader) when a
            # plugin is actually installed — an undrained reader would
            # accumulate every route update forever
            from openr_tpu.plugin import PluginArgs, has_plugin, plugin_start

            if has_plugin():
                plugin_start(
                    PluginArgs(
                        prefix_updates_queue=self.prefix_updates_queue,
                        static_routes_queue=self.static_routes_queue,
                        route_updates_reader=(
                            self.route_updates_queue.get_reader()
                        ),
                        config=self.config,
                    )
                )
        log.info(
            "openr-tpu daemon %s up, ctrl on :%d",
            self.config.node_name,
            port,
        )
        return port

    async def stop(self) -> None:
        """Reverse-order shutdown with queue closing (Main.cpp:597-654).

        Graceful restart: with `spark_config.graceful_restart_enabled`,
        restarting hellos go out FIRST — before any module stops — so
        neighbors enter the Spark RESTART hold (keeping adjacencies and
        the routes through them for graceful_restart_time_s) instead of
        tearing the node out of the topology on hold expiry. The restarted
        incarnation then warm-boots: Fib keeps the agent forwarding on
        stale routes, KvStore re-advertisements ride the persisted version
        floors (docs/Robustness.md "Graceful restart & warm boot")."""
        if self.config.config.spark_config.graceful_restart_enabled:
            self.spark.flood_restarting()
        if self.config.config.enable_bgp_peering:
            from openr_tpu.plugin import plugin_stop

            plugin_stop()
        await self.ctrl_server.stop()
        self.journal.stop()  # flushes the pending durable-log batch
        self.stream_manager.stop()
        self.fib.stop()
        self.decision.stop()
        self.link_monitor.stop()
        self.spark.stop()
        if self.prefix_allocator is not None:
            self.prefix_allocator.stop()
        self.prefix_manager.stop()
        self.kvstore_client.stop()
        if self.kvstore_server is not None:
            await self.kvstore_server.stop()
        if self._kv_tcp:
            self._kv_transport.close()  # persistent peer connections
        self.kvstore.stop()
        if self.watchdog is not None:
            self.watchdog.stop()
        self.exporter.stop()
        self.monitor.stop()
        self.config_store.stop()
        for q in (
            self.route_updates_queue,
            self.interface_updates_queue,
            self.neighbor_updates_queue,
            self.prefix_updates_queue,
            self.static_routes_queue,
            self.log_sample_queue,
        ):
            q.close()
