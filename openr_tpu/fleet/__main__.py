"""Fleet observer CLI: attach to a running fleet or replay artifacts.

    # watch a live fleet (host:port ctrl endpoints) for 60s
    python -m openr_tpu.fleet --hosts 10.0.0.1:2018,10.0.0.2:2018 \
        --seconds 60 --out fleet.json

    # ctrl-free replay of a recorded soak artifact
    python -m openr_tpu.fleet --replay SOAK_r01.json

`breeze fleet report fleet.json` renders the written report.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    from openr_tpu.fleet import (
        FleetConfig,
        SloConfig,
        replay_scrape_files,
        replay_soak_report,
        watch_hosts,
    )

    parser = argparse.ArgumentParser(
        prog="fleet",
        description="fleet observer: telemetry collector + SLO watchdog",
    )
    parser.add_argument(
        "--hosts",
        default="",
        help="comma-separated host:port ctrl endpoints to attach to",
    )
    parser.add_argument("--seconds", type=float, default=30.0)
    parser.add_argument("--interval", type=float, default=1.0)
    parser.add_argument(
        "--budget-ms",
        type=float,
        default=1000.0,
        help="convergence e2e p95 budget (SLO)",
    )
    parser.add_argument(
        "--no-stream",
        action="store_true",
        help="scrape-only (skip the per-node subscribeKvStore streams)",
    )
    parser.add_argument(
        "--forensics-dir", default=None, help="write breach dumps here"
    )
    parser.add_argument(
        "--replay",
        nargs="+",
        default=None,
        help="offline: a soak report JSON, or exposition text files",
    )
    parser.add_argument("--out", default=None, help="JSON report path")
    args = parser.parse_args(argv)

    slo = SloConfig(convergence_p95_budget_ms=args.budget_ms)
    if args.replay:
        first = args.replay[0]
        if first.endswith(".json"):
            with open(first) as fh:
                report = replay_soak_report(json.load(fh), slo=slo)
        else:
            report = replay_scrape_files(args.replay, slo=slo)
    else:
        hosts = [h for h in args.hosts.split(",") if h]
        if not hosts:
            parser.error("--hosts or --replay is required")
        report = watch_hosts(
            hosts,
            seconds=args.seconds,
            config=FleetConfig(
                scrape_interval_s=args.interval,
                stream=not args.no_stream,
                forensics_dir=args.forensics_dir,
                slo=slo,
            ),
        )
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
    verdict = report["verdict"]
    print(
        json.dumps(
            {
                "fleet": "PASS" if verdict["pass"] else "BREACH",
                "nodes": len(report.get("nodes", [])),
                "findings": len(report.get("findings", [])),
                "ticks": report.get("ticks", 0),
            }
        )
    )
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
