"""Bounded fleet time-series store.

The fleet observer (`fleet/observer.py`) scrapes every node's Prometheus
exposition and folds the samples here: one bounded ring per
(node, metric) pair holding (timestamp, value) points, with **exact
eviction accounting** (`recorded == retained + evicted`, the same
invariant the flight recorder and windowed rollups keep) so a verdict
can always say how much history it judged from.

Two non-scalar companions ride next to the rings:

  - **gap markers**: when a node's telemetry stream breaks — a scrape
    fails, a subscription overflows into a marked resync, a restart
    window swallows a poll — the store records a typed gap for that
    node instead of silently interpolating over the hole. Rules that
    difference consecutive points consult the gaps so a breach is never
    synthesized across a discontinuity, and tests can prove "no silent
    holes" by asserting the marker exists.
  - **histogram snapshots**: the latest cumulative `Histogram` per
    (node, metric), rehydrated from the scrape via the sparse codec
    (`utils/counters.py to_sparse/from_sparse`) and mergeable
    fleet-wide with `Histogram.merge` — the distribution view forensics
    dumps and reports serve.
"""

from __future__ import annotations

import collections
from typing import Any, Deque, Dict, List, Optional, Tuple

from openr_tpu.utils.counters import Histogram


class SeriesRing:
    """One (node, metric) ring: bounded (ts, value) points with exact
    eviction accounting."""

    __slots__ = ("capacity", "points", "recorded", "evicted")

    def __init__(self, capacity: int) -> None:
        self.capacity = max(1, int(capacity))
        self.points: Deque[Tuple[float, float]] = collections.deque()
        self.recorded = 0
        self.evicted = 0

    def append(self, ts: float, value: float) -> None:
        self.points.append((float(ts), float(value)))
        self.recorded += 1
        while len(self.points) > self.capacity:
            self.points.popleft()
            self.evicted += 1

    def values(self) -> List[float]:
        return [v for _, v in self.points]

    def last(self) -> Optional[Tuple[float, float]]:
        return self.points[-1] if self.points else None


class FleetStore:
    """Per-node x per-metric bounded rings + gap markers + the latest
    per-node histogram snapshots (sparse-codec mergeable)."""

    def __init__(self, capacity: int = 512, max_gaps: int = 256) -> None:
        self.capacity = int(capacity)
        self.max_gaps = int(max_gaps)
        self._rings: Dict[Tuple[str, str], SeriesRing] = {}
        # node -> bounded [(ts, reason)] discontinuity markers
        self._gaps: Dict[str, Deque[Tuple[float, str]]] = {}
        self.gaps_marked = 0
        # (node, metric) -> latest cumulative Histogram (sparse-rehydrated)
        self._hists: Dict[Tuple[str, str], Histogram] = {}

    # -- scalar rings ---------------------------------------------------

    def record(self, node: str, metric: str, ts: float, value: float) -> None:
        ring = self._rings.get((node, metric))
        if ring is None:
            ring = self._rings[(node, metric)] = SeriesRing(self.capacity)
        ring.append(ts, value)

    def series(self, node: str, metric: str) -> List[float]:
        ring = self._rings.get((node, metric))
        return ring.values() if ring is not None else []

    def last(self, node: str, metric: str) -> Optional[float]:
        ring = self._rings.get((node, metric))
        if ring is None or not ring.points:
            return None
        return ring.points[-1][1]

    def nodes(self) -> List[str]:
        return sorted({node for node, _ in self._rings})

    def metrics(self, node: str) -> List[str]:
        return sorted(m for n, m in self._rings if n == node)

    def accounting(self) -> Dict[str, int]:
        """recorded == retained + evicted across every ring — the exact
        eviction invariant the store's tests and verdicts pin."""
        recorded = sum(r.recorded for r in self._rings.values())
        retained = sum(len(r.points) for r in self._rings.values())
        evicted = sum(r.evicted for r in self._rings.values())
        return {
            "recorded": recorded,
            "retained": retained,
            "evicted": evicted,
            "rings": len(self._rings),
        }

    # -- gap markers ----------------------------------------------------

    def mark_gap(self, node: str, ts: float, reason: str) -> None:
        """Typed discontinuity for one node's telemetry (scrape failure,
        stream resync, restart window). Never silent: bounded like the
        rings, but the `gaps_marked` total is exact."""
        gaps = self._gaps.get(node)
        if gaps is None:
            gaps = self._gaps[node] = collections.deque()
        gaps.append((float(ts), str(reason)))
        self.gaps_marked += 1
        while len(gaps) > self.max_gaps:
            gaps.popleft()

    def gaps(self, node: str) -> List[Tuple[float, str]]:
        return list(self._gaps.get(node, ()))

    def gap_since(self, node: str, ts: float) -> bool:
        """Any discontinuity for `node` at or after `ts` — the guard a
        differencing rule consults before trusting an interval."""
        return any(g_ts >= ts for g_ts, _ in self._gaps.get(node, ()))

    # -- histogram snapshots (sparse codec) -----------------------------

    def record_histogram_sparse(
        self, node: str, metric: str, sparse: Dict[str, Any]
    ) -> None:
        self._hists[(node, metric)] = Histogram.from_sparse(sparse)

    def record_histogram(
        self, node: str, metric: str, hist: Histogram
    ) -> None:
        self._hists[(node, metric)] = hist

    def node_histogram(self, node: str, metric: str) -> Optional[Histogram]:
        return self._hists.get((node, metric))

    def merged_histogram(self, metric: str) -> Histogram:
        """Fleet-wide distribution: every node's latest snapshot folded
        with Histogram.merge (the sparse-codec mergeability contract)."""
        out = Histogram()
        for (node, m), hist in self._hists.items():
            if m == metric:
                out.merge(hist)
        return out

    # -- export ---------------------------------------------------------

    def tail(self, node: str, points: int = 32) -> Dict[str, Any]:
        """One node's recent evidence — what a forensics dump embeds."""
        series = {
            metric: [
                [round(ts, 3), value]
                for ts, value in list(
                    self._rings[(n, metric)].points
                )[-points:]
            ]
            for (n, metric) in sorted(self._rings)
            if n == node
        }
        return {
            "node": node,
            "series": series,
            "gaps": [[round(ts, 3), reason] for ts, reason in
                     self.gaps(node)][-points:],
            "histograms": {
                metric: hist.to_sparse()
                for (n, metric), hist in sorted(self._hists.items())
                if n == node
            },
        }

    def snapshot(self) -> Dict[str, Any]:
        """JSON-serializable store summary for fleet reports."""
        return {
            "accounting": self.accounting(),
            "gaps_marked": self.gaps_marked,
            "nodes": {
                node: {
                    "metrics": self.metrics(node),
                    "gaps": len(self._gaps.get(node, ())),
                }
                for node in self.nodes()
            },
        }
