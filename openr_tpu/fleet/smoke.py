"""FLEET_SMOKE tier-1 smoke (the fleet sibling of FAULT/TRACE/SOAK/
RESTART_SMOKE): a small VirtualNetwork with the fleet observer attached
over real ctrl sockets, one injected fault, and the observer must raise
*exactly* the expected SLO breach — correct rule, correct node, correct
per-stage attribution — with a well-formed forensics dump.

Sequence:

  1. an N-node line converges; the observer scrapes + streams every
     node; a clean flap runs and NO rule may fire (false-positive
     guard — solver, stream, admission and restart rules all stay armed);
  2. ONE fault is injected: the `fib.program` action hook sets the
     victim's `program_throttle_s` (a deterministically slow FIB agent,
     docs/Robustness.md), so the victim's next convergence span carries
     the delay in its fib.program stage;
  3. a second flap runs; the observer's convergence_p95 rule must breach
     on the victim with `fib.program_ms` named in the attribution, emit
     one FLEET_SLO_BREACH sample carrying the forensics id, and the dump
     must embed the victim's series tail + its solve traces.

Topology size scales via FLEET_SMOKE_NODES; returns a summary dict with
the full fleet report (`breeze fleet report --json` round-trips it).
"""

from __future__ import annotations

import asyncio
import os
from typing import Any, Dict

from openr_tpu.fleet.observer import FleetConfig, FleetObserver
from openr_tpu.fleet.rules import SloConfig
from openr_tpu.testing.faults import FaultInjector, injected


def run_fleet_smoke() -> Dict[str, Any]:
    from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

    n = max(3, int(os.environ.get("FLEET_SMOKE_NODES", "3")))
    budget_ms = float(os.environ.get("FLEET_SMOKE_BUDGET_MS", "250"))
    throttle_s = max(0.6, budget_ms / 1000.0 * 3)
    mid = n // 2
    victim = "n0"

    async def body() -> Dict[str, Any]:
        net = VirtualNetwork()
        for i in range(n):
            net.add_node(f"n{i}", loopback_prefix=f"10.{i}.0.0/24")
        await net.start_all()
        for i in range(n - 1):
            net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")

        def converged() -> bool:
            for i in range(n):
                got = set(net.wrappers[f"n{i}"].programmed_prefixes())
                want = {f"10.{j}.0.0/24" for j in range(n) if j != i}
                if not want.issubset(got):
                    return False
            return True

        def partitioned() -> bool:
            left = net.wrappers["n0"].programmed_prefixes()
            return f"10.{n - 1}.0.0/24" not in left

        observer = FleetObserver.for_network(
            net,
            config=FleetConfig(
                scrape_interval_s=0.15,
                eval_every=1,
                slo=SloConfig(
                    convergence_p95_budget_ms=budget_ms,
                    # the budget rule is the expected breach; the trend
                    # detector would *also* flag the same step — keep the
                    # smoke's "exactly one" assertion meaningful
                    trend_min_windows=0,
                ),
            ),
        )

        def flap():
            net.fail_link(
                f"n{mid}", f"if{mid}r", f"n{mid + 1}", f"if{mid + 1}l"
            )

        def heal():
            net.restore_link(
                f"n{mid}", f"if{mid}r", f"n{mid + 1}", f"if{mid + 1}l"
            )

        with injected(FaultInjector(seed=5)) as inj:
            try:
                await wait_until(converged, timeout=60.0)
                await observer.start()
                # streams up: every node delivered its initial snapshot
                await wait_until(
                    lambda: observer.counters.get("fleet.stream_frames", 0)
                    >= n,
                    timeout=30.0,
                )
                # phase 1: a clean flap — no rule may fire
                flap()
                await wait_until(partitioned, timeout=60.0)
                heal()
                await wait_until(converged, timeout=60.0)
                await wait_until(
                    lambda: observer.store.series(victim,
                        "interval.convergence.e2e_p95_ms") != [],
                    timeout=30.0,
                )
                await asyncio.sleep(0.5)  # a few clean evaluation ticks
                clean_findings = len(observer.findings)

                # phase 2: ONE injected fault — the victim's next route
                # programming stalls for throttle_s (a slow FIB agent)
                victim_fib = net.wrappers[victim].daemon.fib
                inj.arm(
                    "fib.program",
                    times=1,
                    when=lambda ctx: ctx is victim_fib,
                    action=lambda fib: setattr(
                        fib, "program_throttle_s", throttle_s
                    ),
                )
                flap()
                await wait_until(partitioned, timeout=60.0)
                await wait_until(
                    lambda: len(observer.findings) > clean_findings,
                    timeout=60.0,
                )
                heal()
                await wait_until(converged, timeout=60.0)
                fired = inj.fired("fib.program")
            finally:
                await observer.stop()
                await net.stop_all()

        report = observer.report()
        summary = {
            "nodes": n,
            "victim": victim,
            "throttle_s": throttle_s,
            "budget_ms": budget_ms,
            "clean_findings": clean_findings,
            "faults_fired": fired,
            "findings": [f.to_dict() for f in observer.findings],
            "samples": [s.values() for s in observer.samples],
            "forensics": observer.forensics,
            "report": report,
        }
        # -- the smoke's contract ----------------------------------------
        assert fired == 1, summary["faults_fired"]
        assert clean_findings == 0, summary["findings"]
        assert len(observer.findings) == 1, summary["findings"]
        finding = observer.findings[0]
        assert finding.kind == "convergence_p95", finding.to_dict()
        assert finding.node == victim, finding.to_dict()
        assert finding.value > budget_ms, finding.to_dict()
        stages = [s["stage"] for s in finding.attribution]
        assert "fib.program_ms" in stages, finding.to_dict()
        # the breach sample is typed and carries the forensics id
        sample = observer.samples[-1].values()
        assert sample["event"] == "FLEET_SLO_BREACH", sample
        assert sample["rule"] == "convergence_p95", sample
        assert sample["node"] == victim, sample
        assert "fib.program_ms" in sample["stages"], sample
        # well-formed forensics: id linkage, series tail, solve traces
        assert len(observer.forensics) == 1, summary["forensics"]
        dump = observer.forensics[0]
        assert dump["id"] == finding.forensics_id, dump["id"]
        assert dump["id"] == sample["forensics_id"], dump["id"]
        assert dump["reason"] == "convergence_p95", dump
        assert dump["node"] == victim, dump
        tail = dump["store_tail"]
        assert tail["series"].get("interval.convergence.e2e_p95_ms"), tail
        assert isinstance(dump["solve_traces"], dict), dump["solve_traces"]
        acc = dump["accounting"]
        assert acc["recorded"] == acc["retained"] + acc["evicted"], acc
        # the observer actually streamed and scraped the whole fleet
        counters = report["counters"]
        assert counters.get("fleet.scrapes", 0) >= 2 * n, counters
        assert counters.get("fleet.stream_frames", 0) >= n, counters
        assert counters.get("fleet.scrape_errors", 0) == 0, counters
        checks = report["verdict"]["checks"]
        assert checks["store_accounting"]["ok"], checks
        assert checks["scrape_health"]["ok"], checks
        assert not checks["no_slo_breach"]["ok"], checks
        return summary

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(body())
    finally:
        loop.close()
