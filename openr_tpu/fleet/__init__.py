"""Fleet observer: streaming telemetry collector, continuous SLO
watchdog and forensics for whole emulated/real fleets
(docs/Monitoring.md "Fleet observer & SLO watchdog").

A client of the existing surfaces — `getMetricsText` scrapes and
`subscribeKvStore` streams over real ctrl sockets — folded into a
bounded per-node x per-metric time-series store and judged continuously
by standing SLO rules; `python -m openr_tpu.fleet` and
`breeze fleet status|watch|report` are the operator surfaces.
"""

from openr_tpu.fleet.observer import (
    FLEET_SLO_BREACH,
    FleetCollector,
    FleetConfig,
    FleetObserver,
    replay_scrape_files,
    replay_soak_report,
    watch_hosts,
)
from openr_tpu.fleet.rules import Finding, SloConfig, evaluate
from openr_tpu.fleet.store import FleetStore, SeriesRing

__all__ = [
    "FLEET_SLO_BREACH",
    "Finding",
    "FleetCollector",
    "FleetConfig",
    "FleetObserver",
    "FleetStore",
    "SeriesRing",
    "SloConfig",
    "evaluate",
    "replay_scrape_files",
    "replay_soak_report",
    "watch_hosts",
]
