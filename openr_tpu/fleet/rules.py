"""Standing SLO rules — the continuous generalization of the soak judge.

The soak harness judges a run once, after the fact
(`testing/soak.py:_judge`): slope + double-gated step detection over the
windowed p95 series, fault-vs-clean attribution, verdict checks. A fleet
that runs for hours needs the same judgments made *continuously*, over
live telemetry, with the offending node and pipeline stage named at
breach time. Each rule here evaluates one standing check against the
`FleetStore` every watchdog tick and yields typed findings
(`FLEET_SLO_BREACH` LogSamples once the observer stamps them):

  | rule | watches |
  |---|---|
  | convergence_p95   | per-node interval e2e p95 vs the budget, with
  |                   | per-stage attribution from the stage-histogram
  |                   | interval deltas
  | convergence_trend | slope + step detection (`testing/soak.py
  |                   | series_slope/detect_step`, the exact soak
  |                   | detectors) on the per-node p95 series
  | solver_health     | breaker/fallback state: `decision.spf.
  |                   | fallback_active` gauges, breaker trips
  | stream_backpressure | fan-out overflow: coalesce + marked-resync
  |                   | rates per interval
  | admission_rejections | typed server-busy rejections + timeouts
  | restart_health    | warm-boot reconciliation: stale-deadline
  |                   | flushes, stuck stale routes, GR hold expiries
  | flood_health      | dissemination plane: quarantine trips, typed
  |                   | wire rejects, flood duplicate ratio
  | device_memory     | device-memory observatory (monitor/memledger.py):
  |                   | headroom budget vs the capacity verdict gauge,
  |                   | leak trend (series_slope over live-bytes) with
  |                   | per-structure attribution, retained releases

Interval values are computed by the collector (epoch-aware counter
deltas + cumulative-histogram diffs, `monitor/exporter.py`
CounterEpochTracker / histogram_interval) and recorded into the store
under the `interval.*`-prefixed series names below; rules never touch
raw scrapes. Gap markers veto differencing: an interval that spans a
store gap (scrape failure, stream resync, restart window) is not
judged, so a breach is never synthesized across a discontinuity.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional

from openr_tpu.fleet.store import FleetStore
from openr_tpu.monitor.memledger import STRUCT_GAUGES
from openr_tpu.testing.soak import detect_step, series_slope

# store series names the collector records (interval = between two
# consecutive scrapes of one node, within one counter epoch)
E2E_P95 = "interval.convergence.e2e_p95_ms"
E2E_COUNT = "interval.convergence.events"
STAGE_AVG_PREFIX = "interval.stage."  # + <stage histogram name> = avg ms
GAUGE_PREFIX = "gauge."  # + <counter name> = raw gauge reading
RATE_PREFIX = "interval.rate."  # + <counter name> = delta per interval

# pipeline-stage histograms the collector diffs for attribution (the
# convergence span stages that are exported as histograms)
STAGE_HISTOGRAMS = (
    "decision.debounce_ms",
    "decision.route_build_ms",
    "fib.program_ms",
    "link_monitor.adj_advertise_ms",
    "kvstore.flood.e2e_ms",
)

# counter deltas the collector records as interval rates
RATE_COUNTERS = (
    "ctrl.stream.coalesced",
    "ctrl.stream.resyncs",
    "ctrl.stream.publish_errors",
    "ctrl.admission.rejected_queue_full",
    "ctrl.admission.rejected_client_cap",
    "ctrl.admission.timeouts",
    "decision.spf.breaker_trips",
    "decision.spf.solver_failures",
    "fib.stale_deadline_flushes",
    "fib.thrift.failure.add_del_route",
    "spark.gr_hold_expiries",
    "kvstore.flood.received",
    "kvstore.flood.duplicates",
    "kvstore.quarantine.trips",
    "kvstore.wire.rejected_total",
    "decision.mem.retained",
    "decision.mem.capacity_refusals",
    "decision.mem.drift_events",
)

# gauges sampled verbatim (the decision.mem.* per-structure gauges ride
# along for leak attribution — the fixed memledger vocabulary)
GAUGE_COUNTERS = (
    "decision.spf.fallback_active",
    "fib.num_stale_routes",
    "decision.mem.live_bytes_last",
    "decision.mem.peak_bytes_last",
    "decision.mem.headroom_bytes_last",
    "decision.mem.structures_active",
) + tuple(STRUCT_GAUGES.values())


@dataclass
class SloConfig:
    """Budgets for the standing rules (the fleet's SLOs)."""

    # convergence_p95: interval e2e p95 budget (ms); 0 disables
    convergence_p95_budget_ms: float = 1000.0
    # minimum interval events before a p95 is judged (noise floor)
    convergence_min_events: int = 1
    # convergence_trend: step detector thresholds (soak defaults) over
    # at least trend_min_windows per-node p95 points; 0 disables
    trend_min_windows: int = 6
    trend_min_ratio: float = 2.0
    trend_min_delta_ms: float = 5.0
    # stream_backpressure: marked resyncs per interval; 0 disables
    stream_resync_budget: float = 0.0
    # admission_rejections per interval; 0 keeps the rule armed with a
    # zero budget (any rejection breaches) — set <0 to disable
    admission_reject_budget: float = 0.0
    # restart_health: ticks a node may hold stale routes before breach
    stale_route_ticks: int = 8
    # flood_health: dissemination-plane hostility budgets. The duplicate
    # ratio (duplicates/received per interval) breaches above this; <0
    # disables the ratio check entirely
    flood_duplicate_budget: float = -1.0
    # minimum interval flood receives before the ratio is judged
    flood_min_received: int = 8
    # quarantine trips + typed wire rejects per interval; any excess
    # breaches (these should be zero on a healthy fabric)
    flood_quarantine_budget: float = 0.0
    # per-stage attribution: a stage is named when its interval avg is
    # at least this multiple of the fleet-wide cumulative stage avg
    attribution_min_ratio: float = 2.0
    attribution_stages: int = 3
    # device_memory: minimum headroom (bytes) the capacity verdict gauge
    # may report before breaching; <0 disables (and nodes whose gauge is
    # negative — no capacity source — are never judged against it)
    mem_headroom_budget_bytes: float = -1.0
    # device_memory leak trend: live-bytes slope budget (bytes/tick) over
    # at least mem_leak_min_windows points; <0 disables, 0 arms with a
    # zero budget (any sustained growth breaches). Retained releases
    # (`solver.mem.retain` pins) always breach when the trend rule is
    # armed — a pinned free IS the leak, no slope estimation needed
    mem_leak_slope_budget: float = -1.0
    mem_leak_min_windows: int = 4


@dataclass
class Finding:
    """One SLO breach: rule kind, offending node, per-stage attribution
    and the evidence a forensics dump will carry."""

    kind: str
    node: str
    detail: str
    value: float
    budget: float
    attribution: List[Dict[str, Any]] = field(default_factory=list)
    evidence: Dict[str, Any] = field(default_factory=dict)
    ts: float = 0.0
    forensics_id: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "kind": self.kind,
            "node": self.node,
            "detail": self.detail,
            "value": round(float(self.value), 4),
            "budget": float(self.budget),
            "attribution": list(self.attribution),
            "evidence": dict(self.evidence),
            "ts": self.ts,
            "forensics_id": self.forensics_id,
        }


def _attribute_stages(
    store: FleetStore, node: str, cfg: SloConfig
) -> List[Dict[str, Any]]:
    """Per-stage attribution of a convergence breach: the pipeline
    stages whose latest interval average stands out against that
    stage's own fleet-wide cumulative average (the stage that regressed
    is the one whose fresh samples are slow *relative to its own
    history*, not merely the slowest stage in absolute terms)."""
    scored: List[Dict[str, Any]] = []
    for stage in STAGE_HISTOGRAMS:
        avg = store.last(node, STAGE_AVG_PREFIX + stage)
        if avg is None or avg <= 0.0:
            continue
        merged = store.merged_histogram(stage)
        if not merged.count:
            continue  # no history at all: the stage cannot be judged
        baseline = merged.avg
        ratio = avg / baseline if baseline > 0 else float("inf")
        scored.append(
            {
                "stage": stage,
                "interval_avg_ms": round(avg, 4),
                "baseline_avg_ms": round(baseline, 4),
                "ratio": round(ratio, 3) if ratio != float("inf") else -1.0,
            }
        )
    scored.sort(key=lambda s: s["interval_avg_ms"], reverse=True)
    named = [
        s
        for s in scored
        if s["ratio"] >= cfg.attribution_min_ratio or s["ratio"] == -1.0
    ]
    return (named or scored[:1])[: cfg.attribution_stages]


def eval_convergence_p95(
    store: FleetStore, cfg: SloConfig
) -> Iterable[Finding]:
    if cfg.convergence_p95_budget_ms <= 0:
        return
    worst: Optional[Finding] = None
    offenders: List[str] = []
    for node in store.nodes():
        p95 = store.last(node, E2E_P95)
        events = store.last(node, E2E_COUNT) or 0.0
        if p95 is None or events < cfg.convergence_min_events:
            continue
        if p95 <= cfg.convergence_p95_budget_ms:
            continue
        offenders.append(node)
        if worst is None or p95 > worst.value:
            worst = Finding(
                kind="convergence_p95",
                node=node,
                detail="",
                value=p95,
                budget=cfg.convergence_p95_budget_ms,
            )
    if worst is None:
        return
    worst.attribution = _attribute_stages(store, worst.node, cfg)
    stages = ",".join(s["stage"] for s in worst.attribution) or "unattributed"
    worst.detail = (
        f"interval e2e p95 {worst.value:.1f}ms > budget "
        f"{worst.budget:.1f}ms on {worst.node} "
        f"({len(offenders)} node(s) over budget; stages: {stages})"
    )
    worst.evidence = {
        "offenders": offenders,
        "events": store.last(worst.node, E2E_COUNT),
        "p95_series": store.series(worst.node, E2E_P95)[-16:],
    }
    yield worst


def eval_convergence_trend(
    store: FleetStore, cfg: SloConfig
) -> Iterable[Finding]:
    if cfg.trend_min_windows <= 0:
        return
    for node in store.nodes():
        series = store.series(node, E2E_P95)
        if len(series) < cfg.trend_min_windows:
            continue
        step = detect_step(
            series,
            min_ratio=cfg.trend_min_ratio,
            min_delta_ms=cfg.trend_min_delta_ms,
        )
        if step is None:
            continue
        slope = series_slope(series)
        attribution = _attribute_stages(store, node, cfg)
        yield Finding(
            kind="convergence_trend",
            node=node,
            detail=(
                f"p95 step break on {node}: {step['before_ms']:.1f} -> "
                f"{step['after_ms']:.1f}ms at point {int(step['index'])} "
                f"(slope {slope:+.3f}ms/tick)"
            ),
            value=step["after_ms"],
            budget=step["before_ms"] * cfg.trend_min_ratio,
            attribution=attribution,
            evidence={"step": step, "slope": round(slope, 4),
                      "series": series[-32:]},
        )


def eval_solver_health(
    store: FleetStore, cfg: SloConfig
) -> Iterable[Finding]:
    for node in store.nodes():
        fallback = store.last(
            node, GAUGE_PREFIX + "decision.spf.fallback_active"
        )
        trips = store.last(
            node, RATE_PREFIX + "decision.spf.breaker_trips"
        )
        if not fallback and not trips:
            continue
        yield Finding(
            kind="solver_health",
            node=node,
            detail=(
                f"solver degraded on {node}: fallback_active="
                f"{int(fallback or 0)}, breaker trips this interval="
                f"{int(trips or 0)}"
            ),
            value=float(fallback or trips or 0),
            budget=0.0,
            evidence={
                "fallback_active": fallback,
                "breaker_trips": trips,
                "solver_failures": store.last(
                    node, RATE_PREFIX + "decision.spf.solver_failures"
                ),
            },
        )


def eval_stream_backpressure(
    store: FleetStore, cfg: SloConfig
) -> Iterable[Finding]:
    if cfg.stream_resync_budget < 0:
        return
    for node in store.nodes():
        resyncs = store.last(node, RATE_PREFIX + "ctrl.stream.resyncs") or 0
        errors = (
            store.last(node, RATE_PREFIX + "ctrl.stream.publish_errors")
            or 0
        )
        if resyncs <= cfg.stream_resync_budget and not errors:
            continue
        coalesced = (
            store.last(node, RATE_PREFIX + "ctrl.stream.coalesced") or 0
        )
        yield Finding(
            kind="stream_backpressure",
            node=node,
            detail=(
                f"fan-out overflow on {node}: {int(resyncs)} marked "
                f"resync(s), {int(coalesced)} coalesce(s), "
                f"{int(errors)} publish error(s) this interval"
            ),
            value=float(resyncs + errors),
            budget=cfg.stream_resync_budget,
            evidence={
                "resyncs": resyncs,
                "coalesced": coalesced,
                "publish_errors": errors,
            },
        )


def eval_admission_rejections(
    store: FleetStore, cfg: SloConfig
) -> Iterable[Finding]:
    if cfg.admission_reject_budget < 0:
        return
    for node in store.nodes():
        rejected = sum(
            store.last(node, RATE_PREFIX + name) or 0
            for name in (
                "ctrl.admission.rejected_queue_full",
                "ctrl.admission.rejected_client_cap",
                "ctrl.admission.timeouts",
            )
        )
        if rejected <= cfg.admission_reject_budget:
            continue
        yield Finding(
            kind="admission_rejections",
            node=node,
            detail=(
                f"{int(rejected)} typed server-busy rejection(s) on "
                f"{node} this interval (budget "
                f"{cfg.admission_reject_budget:g})"
            ),
            value=float(rejected),
            budget=cfg.admission_reject_budget,
            evidence={"rejected": rejected},
        )


def eval_restart_health(
    store: FleetStore, cfg: SloConfig
) -> Iterable[Finding]:
    for node in store.nodes():
        flushes = (
            store.last(node, RATE_PREFIX + "fib.stale_deadline_flushes")
            or 0
        )
        expiries = (
            store.last(node, RATE_PREFIX + "spark.gr_hold_expiries") or 0
        )
        stale_series = store.series(
            node, GAUGE_PREFIX + "fib.num_stale_routes"
        )
        stuck = (
            len(stale_series) >= cfg.stale_route_ticks
            and all(v > 0 for v in stale_series[-cfg.stale_route_ticks:])
        )
        if not flushes and not expiries and not stuck:
            continue
        reasons = []
        if flushes:
            reasons.append(f"{int(flushes)} stale-deadline flush(es)")
        if expiries:
            reasons.append(f"{int(expiries)} GR hold expiry(ies)")
        if stuck:
            reasons.append(
                f"stale routes stuck >0 for {cfg.stale_route_ticks} ticks"
            )
        yield Finding(
            kind="restart_health",
            node=node,
            detail=f"restart reconciliation unhealthy on {node}: "
            + ", ".join(reasons),
            value=float(flushes + expiries) or 1.0,
            budget=0.0,
            evidence={
                "stale_deadline_flushes": flushes,
                "gr_hold_expiries": expiries,
                "stale_routes": stale_series[-8:],
            },
        )


def eval_flood_health(
    store: FleetStore, cfg: SloConfig
) -> Iterable[Finding]:
    """Dissemination-plane health: quarantine trips, typed wire rejects
    and the flood duplicate ratio — the live counterpart of the chaos
    smoke's hostile-network evidence (docs/Robustness.md)."""
    for node in store.nodes():
        trips = (
            store.last(node, RATE_PREFIX + "kvstore.quarantine.trips") or 0
        )
        rejects = (
            store.last(node, RATE_PREFIX + "kvstore.wire.rejected_total")
            or 0
        )
        received = (
            store.last(node, RATE_PREFIX + "kvstore.flood.received") or 0
        )
        duplicates = (
            store.last(node, RATE_PREFIX + "kvstore.flood.duplicates") or 0
        )
        ratio = duplicates / received if received > 0 else 0.0
        ratio_breach = (
            cfg.flood_duplicate_budget >= 0
            and received >= cfg.flood_min_received
            and ratio > cfg.flood_duplicate_budget
        )
        hard_breach = (trips + rejects) > cfg.flood_quarantine_budget
        if not ratio_breach and not hard_breach:
            continue
        reasons = []
        if trips:
            reasons.append(f"{int(trips)} quarantine trip(s)")
        if rejects:
            reasons.append(f"{int(rejects)} typed wire reject(s)")
        if ratio_breach:
            reasons.append(
                f"duplicate ratio {ratio:.2f} over "
                f"{int(received)} receive(s)"
            )
        yield Finding(
            kind="flood_health",
            node=node,
            detail=f"dissemination plane unhealthy on {node}: "
            + ", ".join(reasons),
            value=float(trips + rejects) or ratio,
            budget=cfg.flood_quarantine_budget,
            evidence={
                "quarantine_trips": trips,
                "wire_rejects": rejects,
                "flood_received": received,
                "flood_duplicates": duplicates,
                "duplicate_ratio": round(ratio, 4),
            },
        )


def _attribute_structures(
    store: FleetStore, node: str
) -> List[Dict[str, Any]]:
    """Leak attribution: the ledger structures whose per-structure gauge
    series GREW over the observation window — a leak pins one structure's
    bytes while the others keep returning to baseline, so the growing
    series names the offender (the device-memory analogue of per-stage
    convergence attribution). Growth is measured from the window's
    trough, not its first sample: a pinned buffer raises the series'
    floor, and the window may open mid-churn at a transient peak."""
    scored: List[Dict[str, Any]] = []
    for structure, gauge in STRUCT_GAUGES.items():
        series = store.series(node, GAUGE_PREFIX + gauge)
        if len(series) < 2:
            continue
        growth = series[-1] - min(series)
        if growth <= 0:
            continue
        scored.append(
            {
                "structure": structure,
                "growth_bytes": int(growth),
                "live_bytes": int(series[-1]),
                "slope": round(series_slope(series), 2),
            }
        )
    scored.sort(key=lambda s: s["growth_bytes"], reverse=True)
    return scored[:6]


# retain-signal trailing window (in scrape sweeps): long enough to
# bridge per-node scrape skew on the shared counter, short enough that
# a single pin ages out and the episode clears
_RETAIN_WINDOW = 8

# rules whose signal is one shared device pool, not per-node state: the
# observer holds one breach episode per kind (not per node) for these —
# per-node scrape windows see the same global counters at different
# ticks, and per-node episodes would re-report one exhaustion N times
POOL_WIDE_RULES = frozenset({"device_memory"})


def eval_device_memory(
    store: FleetStore, cfg: SloConfig
) -> Iterable[Finding]:
    """Device-memory observatory rule (docs/Monitoring.md "Device-memory
    observatory"): a node breaches when its capacity headroom falls under
    the budget, or when the leak-trend check is armed and either a
    release was pinned live (`solver.mem.retain` — the injected-leak
    signature) or the live-bytes series shows sustained growth.

    Like `eval_convergence_p95`, at most ONE finding per tick — the worst
    offender, the rest listed in evidence. Nodes sharing a device pool
    (and, in the emulator, the process-global ledger) report the same
    exhaustion at once; one episode per incident keeps MEM_SMOKE's
    "exactly one breach" assertion — and a paging policy — meaningful."""
    headroom_armed = cfg.mem_headroom_budget_bytes >= 0
    trend_armed = cfg.mem_leak_slope_budget >= 0
    if not headroom_armed and not trend_armed:
        return
    worst: Optional[Finding] = None
    offenders: List[str] = []
    for node in sorted(store.nodes()):
        reasons: List[str] = []
        value = 0.0
        budget = 0.0
        headroom = store.last(
            node, GAUGE_PREFIX + "decision.mem.headroom_bytes_last"
        )
        # a negative headroom gauge means no capacity source exists on
        # that node (the ledger folds -1) — not judgeable
        if (
            headroom_armed
            and headroom is not None
            and headroom >= 0
            and headroom < cfg.mem_headroom_budget_bytes
        ):
            reasons.append(
                f"headroom {int(headroom)}B under budget "
                f"{int(cfg.mem_headroom_budget_bytes)}B"
            )
            value = float(headroom)
            budget = cfg.mem_headroom_budget_bytes
        # judged over a trailing window, not just the last interval: in
        # a shared-pool deployment (the emulator's process-global ledger
        # especially) each node's scrape picks the same global counter
        # delta up in a different sweep, and a last-interval read would
        # surface one incident to different ticks on different nodes
        retained_series = store.series(
            node, RATE_PREFIX + "decision.mem.retained"
        )
        retained = sum(
            s for s in retained_series[-_RETAIN_WINDOW:] if s > 0
        )
        live_series = store.series(
            node, GAUGE_PREFIX + "decision.mem.live_bytes_last"
        )
        slope = (
            series_slope(live_series)
            if len(live_series) >= cfg.mem_leak_min_windows
            else 0.0
        )
        if trend_armed and retained > 0:
            reasons.append(
                f"{int(retained)} release(s) pinned live in the "
                f"trailing window"
            )
            value = value or float(retained)
        elif trend_armed and slope > cfg.mem_leak_slope_budget:
            reasons.append(
                f"live bytes trending +{slope:.0f}B/tick over "
                f"{len(live_series)} points (budget "
                f"{cfg.mem_leak_slope_budget:g})"
            )
            value = value or slope
            budget = budget or cfg.mem_leak_slope_budget
        if not reasons:
            continue
        offenders.append(node)
        if worst is not None and value <= worst.value:
            continue
        worst = Finding(
            kind="device_memory",
            node=node,
            detail=", ".join(reasons),
            value=value,
            budget=budget,
            evidence={
                "headroom_bytes": headroom,
                "retained": retained,
                "live_slope": round(slope, 2),
                "live_series": live_series[-16:],
                "capacity_refusals": store.last(
                    node, RATE_PREFIX + "decision.mem.capacity_refusals"
                ),
                "drift_events": store.last(
                    node, RATE_PREFIX + "decision.mem.drift_events"
                ),
            },
        )
    if worst is None:
        return
    worst.attribution = _attribute_structures(store, worst.node)
    names = ",".join(
        s["structure"] for s in worst.attribution
    ) or "unattributed"
    worst.detail = (
        f"device memory unhealthy on {worst.node}: {worst.detail}"
        f" ({len(offenders)} node(s) affected; structures: {names})"
    )
    worst.evidence["offenders"] = offenders
    yield worst


RULES = (
    ("convergence_p95", eval_convergence_p95),
    ("convergence_trend", eval_convergence_trend),
    ("solver_health", eval_solver_health),
    ("stream_backpressure", eval_stream_backpressure),
    ("admission_rejections", eval_admission_rejections),
    ("restart_health", eval_restart_health),
    ("flood_health", eval_flood_health),
    ("device_memory", eval_device_memory),
)


def evaluate(store: FleetStore, cfg: SloConfig) -> List[Finding]:
    """One watchdog tick: run every standing rule over the store."""
    findings: List[Finding] = []
    for _, rule in RULES:
        findings.extend(rule(store, cfg))
    return findings
