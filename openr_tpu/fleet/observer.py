"""Fleet observer: streaming telemetry collector + continuous SLO watchdog.

Every observability layer before this one is per-node (histograms/spans,
the exporter, the flight recorder); the fleet observer is the first
*consumer* that watches a whole fleet continuously — and it is a client
of the existing surfaces, not a new wire format:

  - **scrape**: per node, `getMetricsText` over the real ctrl socket
    (the same bytes `GET /metrics` serves), parsed back with
    `parse_metrics_text` and folded into the bounded `FleetStore` —
    epoch-aware counter deltas (`CounterEpochTracker`: a post-restart
    reset is a typed epoch, never a monotonicity violation) and
    cumulative-histogram interval diffs (`histogram_interval`) become
    the per-node interval series the rules judge;
  - **stream**: per node, a `subscribeKvStore` adjacency subscription
    (docs/Streaming.md) for topology liveness — a marked resync or a
    dropped stream records a typed gap in the store, so differencing
    rules never judge across a hole;
  - **watchdog**: every tick, the standing SLO rules (`fleet/rules.py`)
    run over the store; each *new* breach emits one typed
    `FLEET_SLO_BREACH` LogSample with per-stage attribution and
    snapshots a forensics dump — the offending node's recent series +
    its flight-recorder solve traces (`getSolveTraces`), fetched at
    breach time, before the evidence ages out of the rings.

Attach modes: `FleetObserver.for_network(virtual_network)` (emulator —
still over the real ctrl sockets), `FleetObserver.for_hosts([...])`
(host:port list), or offline — `feed_scrape`/`tick` drive the identical
collector/rule path with no sockets (`replay_soak_report`,
`python -m openr_tpu.fleet --replay`).
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from openr_tpu.fleet.rules import (
    E2E_COUNT,
    E2E_P95,
    GAUGE_COUNTERS,
    GAUGE_PREFIX,
    POOL_WIDE_RULES,
    RATE_COUNTERS,
    RATE_PREFIX,
    STAGE_AVG_PREFIX,
    STAGE_HISTOGRAMS,
    Finding,
    SloConfig,
    evaluate,
)
from openr_tpu.fleet.store import FleetStore
from openr_tpu.monitor.exporter import (
    CounterEpochTracker,
    histogram_from_parsed,
    histogram_interval,
    parse_metrics_text,
    prom_name,
)
from openr_tpu.monitor.monitor import LogSample
from openr_tpu.testing.faults import fault_point
from openr_tpu.utils.counters import CountersMixin, HistogramsMixin

FLEET_SLO_BREACH = "FLEET_SLO_BREACH"

E2E_HISTOGRAM = "convergence.e2e_ms"


@dataclass
class FleetConfig:
    """Observer knobs: collection cadence, store bounds, SLO budgets."""

    scrape_interval_s: float = 1.0
    # rules run after every eval_every-th completed scrape sweep
    eval_every: int = 1
    store_capacity: int = 512
    stream: bool = True  # per-node subscribeKvStore liveness streams
    client_label: str = "fleet-observer"
    # forensics: traces fetched per dump, bounded dump index, optional dir
    forensics_traces: int = 8
    forensics_max: int = 32
    forensics_dir: Optional[str] = None
    # journal evidence attached to each breach dump (docs/Journal.md):
    # journal-tail record count and the rib-diff lookback window
    forensics_journal_tail: int = 32
    forensics_rib_window_s: float = 60.0
    # how long after note_restart a node's failures stay attributed
    restart_window_s: float = 30.0
    slo: SloConfig = field(default_factory=SloConfig)


class FleetCollector:
    """Scrape -> store fold: epoch-aware counter deltas, histogram
    interval diffs, gap marking. Shared verbatim by the live scrape
    tasks and the offline replay path."""

    def __init__(self, store: FleetStore) -> None:
        self.store = store
        self.epochs = CounterEpochTracker()
        # (node, histogram) -> previous parsed cumulative snapshot
        self._prev_hists: Dict[Tuple[str, str], Dict[str, Any]] = {}

    @staticmethod
    def _sample(parsed: Dict[str, Any], name: str) -> Optional[float]:
        pname = prom_name(name)
        for view in ("counters", "gauges"):
            if pname in parsed[view]:
                return parsed[view][pname]
        return None

    def fold(self, node: str, ts: float, text_or_parsed) -> Dict[str, Any]:
        """Fold one scrape; returns the epoch observation (reset flag,
        deltas) so callers can surface resets."""
        parsed = (
            parse_metrics_text(text_or_parsed)
            if isinstance(text_or_parsed, str)
            else text_or_parsed
        )
        obs = self.epochs.observe(node, parsed["counters"])
        if obs["reset"]:
            # typed epoch: the node restarted (or re-registered); the
            # interval across the reset is a discontinuity, not data
            self.store.mark_gap(node, ts, "counter_epoch")
        for name in GAUGE_COUNTERS:
            value = self._sample(parsed, name)
            if value is not None:
                self.store.record(node, GAUGE_PREFIX + name, ts, value)
        if not obs["first"]:
            for name in RATE_COUNTERS:
                pname = prom_name(name)
                if pname in obs["deltas"]:
                    self.store.record(
                        node, RATE_PREFIX + name, ts, obs["deltas"][pname]
                    )
        for metric in (E2E_HISTOGRAM,) + STAGE_HISTOGRAMS:
            cur = parsed["histograms"].get(prom_name(metric))
            if cur is None:
                continue
            prev = self._prev_hists.get((node, metric))
            self._prev_hists[(node, metric)] = cur
            self.store.record_histogram(
                node, metric, histogram_from_parsed(cur)
            )
            if prev is None:
                continue  # first scrape: no interval yet
            interval = histogram_interval(prev, cur)
            if interval["count"] <= 0:
                continue  # idle interval: no samples, no point
            if metric == E2E_HISTOGRAM:
                self.store.record(node, E2E_P95, ts, interval["p95"])
                self.store.record(node, E2E_COUNT, ts, interval["count"])
            else:
                self.store.record(
                    node, STAGE_AVG_PREFIX + metric, ts, interval["avg"]
                )
        return obs


class FleetObserver(CountersMixin, HistogramsMixin):
    """The fleet-wide collector + watchdog (docs/Monitoring.md "Fleet
    observer & SLO watchdog"). `fleet.*` counters/histograms follow the
    registry convention so an embedding daemon or harness can register
    the observer with a Monitor like any module."""

    def __init__(
        self,
        targets_fn: Optional[
            Callable[[], Dict[str, Tuple[str, int]]]
        ] = None,
        config: Optional[FleetConfig] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.config = config or FleetConfig()
        self.store = FleetStore(capacity=self.config.store_capacity)
        self.collector = FleetCollector(self.store)
        self._targets_fn = targets_fn
        self._loop = loop
        self._tasks: List[asyncio.Task] = []
        self._clients: List[Any] = []
        self._started = False
        self.findings: List[Finding] = []
        self.samples: List[LogSample] = []
        self.forensics: List[Dict[str, Any]] = []
        self._active: Dict[Tuple[str, str], Finding] = {}
        self._restart_until: Dict[str, float] = {}
        self._scrapes_done = 0
        self._ticks = 0
        self._forensics_seq = 0
        self._last_scrape_error = ""
        self._ensure_counters()
        self._ensure_histograms()

    # -- attach helpers -------------------------------------------------

    @classmethod
    def for_network(cls, net, config=None, loop=None) -> "FleetObserver":
        """Attach to a live VirtualNetwork — over the real ctrl sockets
        (the emulator's wrappers publish their ephemeral ports; restart
        waves re-resolve, so a respawned daemon's new port is found)."""

        def targets() -> Dict[str, Tuple[str, int]]:
            return {
                name: ("127.0.0.1", wrapper.ctrl_port)
                for name, wrapper in net.wrappers.items()
                if wrapper.ctrl_port
            }

        return cls(targets, config=config, loop=loop)

    @classmethod
    def for_hosts(cls, hosts, config=None, loop=None) -> "FleetObserver":
        """Attach to a host:port list (real deployments)."""
        resolved: Dict[str, Tuple[str, int]] = {}
        for endpoint in hosts:
            host, _, port = str(endpoint).rpartition(":")
            resolved[str(endpoint)] = (host or "127.0.0.1", int(port))
        return cls(lambda: dict(resolved), config=config, loop=loop)

    # -- lifecycle ------------------------------------------------------

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    async def start(self) -> None:
        assert self._targets_fn is not None, "offline observer: use feed_scrape"
        if self._started:
            return
        self._started = True
        loop = asyncio.get_running_loop()
        self._tasks.append(loop.create_task(self._scrape_loop()))
        if self.config.stream:
            for name in list(self._targets_fn()):
                self._tasks.append(
                    loop.create_task(self._stream_loop(name))
                )
        self._tasks.append(loop.create_task(self._watchdog_loop()))

    async def stop(self) -> None:
        self._started = False
        for task in self._tasks:
            task.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()
        for client in self._clients:
            try:
                await client.close()
            except Exception:
                pass
        self._clients.clear()

    def note_restart(self, node: str, window_s: Optional[float] = None) -> None:
        """A controlled restart of `node` is in flight: scrape failures
        and counter epochs inside the window are *attributed* to the
        restart (counted separately, gap reason "restart") instead of
        counting against scrape health."""
        self._restart_until[node] = time.monotonic() + (
            window_s if window_s is not None else self.config.restart_window_s
        )
        self.collector.epochs.forget(node)
        self.store.mark_gap(node, time.time(), "restart")

    def _in_restart_window(self, node: str) -> bool:
        until = self._restart_until.get(node)
        return until is not None and time.monotonic() < until

    # -- collection (live) ----------------------------------------------

    async def _connect(self, name: str):
        from openr_tpu.ctrl.client import CtrlClient

        host, port = self._targets_fn()[name]
        client = await CtrlClient(host, port).connect()
        self._clients.append(client)
        return client

    def _drop_client(self, client) -> None:
        if client in self._clients:
            self._clients.remove(client)
        writer = getattr(client, "_writer", None)
        if writer is not None:
            writer.close()
        client._writer = client._reader = None

    async def _scrape_node(self, name: str, clients: Dict[str, Any]) -> bool:
        try:
            # named fault seam: deterministic mid-scrape node death
            # (docs/Robustness.md) — fires before the socket I/O
            fault_point("fleet.scrape", name)
            client = clients.get(name)
            if client is None:
                client = clients[name] = await self._connect(name)
            with self._timer("fleet.scrape_ms"):
                text = await client.call("getMetricsText")
                obs = self.collector.fold(name, time.time(), text)
            self._bump("fleet.scrapes")
            self._bump("fleet.samples", len(obs["deltas"]))
            if obs["reset"]:
                self._bump("fleet.epochs")
                if self._in_restart_window(name):
                    self._bump("fleet.restart_attributed")
            return True
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            client = clients.pop(name, None)
            if client is not None:
                self._drop_client(client)
            self.store.mark_gap(
                name,
                time.time(),
                "restart" if self._in_restart_window(name) else "scrape_error",
            )
            if self._in_restart_window(name):
                # a node dying mid-scrape during its restart window is
                # expected churn, not a scrape-health failure
                self._bump("fleet.restart_attributed")
            else:
                self._bump("fleet.scrape_errors")
            self._last_scrape_error = repr(exc)
            return False

    async def _scrape_loop(self) -> None:
        clients: Dict[str, Any] = {}
        try:
            while True:
                names = sorted(self._targets_fn())
                counters = self._ensure_counters()
                counters["fleet.nodes_active"] = len(names)
                for name in names:
                    await self._scrape_node(name, clients)
                self._scrapes_done += 1
                if (
                    self.config.eval_every > 0
                    and self._scrapes_done % self.config.eval_every == 0
                ):
                    await self._tick_async()
                await asyncio.sleep(self.config.scrape_interval_s)
        except asyncio.CancelledError:
            return

    async def _stream_loop(self, name: str) -> None:
        """Topology-liveness subscription: adjacency deltas over the
        node's real ctrl socket. A marked resync means the server-side
        queue overflowed — the store records the gap so no rule ever
        trusts continuity across it."""
        try:
            while True:
                client = None
                try:
                    client = await self._connect(name)
                    async for frame in client.subscribe(
                        "subscribeKvStore",
                        area="0",
                        prefixes=["adj:"],
                        client=self.config.client_label,
                    ):
                        self._bump("fleet.stream_frames")
                        if frame.get("type") == "resync":
                            self._bump("fleet.stream_resyncs")
                            self.store.mark_gap(
                                name, time.time(), "stream_resync"
                            )
                except asyncio.CancelledError:
                    raise
                except Exception:
                    self._bump("fleet.stream_errors")
                finally:
                    if client is not None:
                        self._drop_client(client)
                self.store.mark_gap(
                    name,
                    time.time(),
                    "restart"
                    if self._in_restart_window(name)
                    else "stream_closed",
                )
                await asyncio.sleep(self.config.scrape_interval_s)
        except asyncio.CancelledError:
            return

    async def _watchdog_loop(self) -> None:
        # fallback cadence: rules normally run from the scrape loop
        # (eval_every); this heartbeat covers eval_every=0 embeddings
        try:
            while True:
                await asyncio.sleep(max(self.config.scrape_interval_s, 1.0))
                if self.config.eval_every <= 0:
                    await self._tick_async()
        except asyncio.CancelledError:
            return

    # -- offline / shared fold + tick -----------------------------------

    def feed_scrape(self, node: str, ts: float, text_or_parsed) -> None:
        """Offline replay seam: fold one scrape with no sockets (the
        identical collector path the live loops drive)."""
        with self._timer("fleet.scrape_ms"):
            obs = self.collector.fold(node, ts, text_or_parsed)
        self._bump("fleet.scrapes")
        if obs["reset"]:
            self._bump("fleet.epochs")

    def tick(self) -> List[Finding]:
        """One synchronous watchdog evaluation (offline replay); live
        loops use _tick_async which additionally fetches the offending
        node's flight-recorder traces into the dump."""
        return self._evaluate()

    async def _tick_async(self) -> None:
        for finding in self._evaluate():
            dump = self.forensics[-1] if self.forensics else None
            if dump is not None and dump["id"] == finding.forensics_id:
                await self._attach_forensics(dump, finding)
                self._write_forensics(dump)

    def _evaluate(self) -> List[Finding]:
        self._ticks += 1
        self._bump("fleet.rule_evals")
        with self._timer("fleet.tick_ms"):
            found = evaluate(self.store, self.config.slo)
        now = time.time()
        keys = set()
        new: List[Finding] = []
        for finding in found:
            # pool-wide rules (device_memory: one shared device pool, the
            # rule already elects a single worst offender per tick) hold
            # ONE episode per kind — per-node scrape windows pick up the
            # same global signal at different ticks, and a per-node key
            # would re-open the same exhaustion under each node's name
            key = (
                (finding.kind, "*")
                if finding.kind in POOL_WIDE_RULES
                else (finding.kind, finding.node)
            )
            keys.add(key)
            if key in self._active:
                continue  # still breaching: one sample per episode
            finding.ts = now
            self._active[key] = finding
            self.findings.append(finding)
            self._bump("fleet.breaches")
            self._bump(f"fleet.breaches.{finding.kind}")
            new.append(finding)
        # re-arm cleared rules (episode semantics)
        for key in list(self._active):
            if key not in keys:
                del self._active[key]
        for finding in new:
            # dump first (assigns forensics_id), then the breach sample
            # carries the id — the flight-recorder sample convention
            self._write_forensics(self._dump_index_entry(finding))
            self._emit_breach_sample(finding)
        return new

    # -- breach surfacing -----------------------------------------------

    def _emit_breach_sample(self, finding: Finding) -> None:
        sample = LogSample(timestamp=finding.ts)
        sample.add_string("event", FLEET_SLO_BREACH)
        sample.add_string("rule", finding.kind)
        sample.add_string("node", finding.node)
        sample.add_string("detail", finding.detail)
        sample.add_double("value", finding.value)
        sample.add_double("budget", finding.budget)
        # convergence rules attribute stages; device_memory attributes
        # ledger structures — the sample carries whichever was named
        sample.add_string_vector(
            "stages",
            [
                s.get("stage", s.get("structure", ""))
                for s in finding.attribution
            ],
        )
        if finding.forensics_id:
            sample.add_string("forensics_id", finding.forensics_id)
        self.samples.append(sample)

    def _dump_index_entry(self, finding: Finding) -> Dict[str, Any]:
        """Forensics snapshot at breach time — the flight-recorder dump
        pattern applied fleet-wide: the offending node's recent series
        tail + the finding, taken BEFORE the rings age the evidence out."""
        self._forensics_seq += 1
        dump_id = (
            f"fleet-{finding.node}-{self._forensics_seq}-"
            f"{int(finding.ts)}"
        )
        finding.forensics_id = dump_id
        dump = {
            "id": dump_id,
            "reason": finding.kind,
            "ts": finding.ts,
            "node": finding.node,
            "finding": finding.to_dict(),
            "store_tail": self.store.tail(finding.node),
            "accounting": self.store.accounting(),
            "counters": dict(self._ensure_counters()),
            "solve_traces": None,
            "stream_stats": None,
            "journal_tail": None,
            "rib_diff": None,
            "device_memory": None,
        }
        self.forensics.append(dump)
        del self.forensics[: -self.config.forensics_max]
        self._bump("fleet.forensics_dumps")
        return dump

    async def _attach_forensics(
        self, dump: Dict[str, Any], finding: "Finding"
    ) -> None:
        """Best-effort evidence pull from the offending node over one
        one-shot connection (the scrape client may be mid-request): the
        flight-recorder traces, the stream/admission state (so
        backpressure breaches are self-contained), and the journaled
        state change across the breach window — the journal tail plus a
        rib-diff covering forensics_rib_window_s before the breach."""
        node = finding.node
        if self._targets_fn is None or node not in self._targets_fn():
            return
        client = None
        try:
            client = await self._connect(node)
        except Exception:
            return
        try:
            dump["solve_traces"] = await self._call_quiet(
                client, "getSolveTraces",
                last_n=self.config.forensics_traces,
            )
            dump["stream_stats"] = await self._call_quiet(
                client, "getStreamStats"
            )
            dump["journal_tail"] = await self._call_quiet(
                client, "getJournalTail",
                last_n=self.config.forensics_journal_tail,
            )
            dump["rib_diff"] = await self._call_quiet(
                client, "getRibDiff",
                from_ts=finding.ts - self.config.forensics_rib_window_s,
                to_ts=finding.ts,
            )
            if finding.kind == "device_memory":
                # the ledger snapshot names the leaking structure with
                # exact per-entry evidence — the dump is self-contained
                dump["device_memory"] = await self._call_quiet(
                    client, "getDeviceMemory"
                )
        finally:
            self._drop_client(client)

    @staticmethod
    async def _call_quiet(client, method: str, **params):
        try:
            return await client.call(method, **params)
        except Exception:
            return None

    def _write_forensics(self, dump: Dict[str, Any]) -> None:
        if not self.config.forensics_dir:
            return
        try:
            os.makedirs(self.config.forensics_dir, exist_ok=True)
            path = os.path.join(
                self.config.forensics_dir, dump["id"] + ".json"
            )
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(dump, fh, indent=2, sort_keys=True, default=str)
            os.replace(tmp, path)
            dump["path"] = path
        except OSError:
            self._bump("fleet.forensics_write_failures")

    # -- report ----------------------------------------------------------

    def report(self) -> Dict[str, Any]:
        """The judged fleet report (`breeze fleet report --json` renders
        and round-trips this shape)."""
        counters = dict(self._ensure_counters())
        checks: Dict[str, Dict[str, Any]] = {}

        def check(name: str, ok: bool, detail: str) -> None:
            checks[name] = {"ok": bool(ok), "detail": detail}

        accounting = self.store.accounting()
        check(
            "store_accounting",
            accounting["recorded"]
            == accounting["retained"] + accounting["evicted"],
            f"{accounting['recorded']} points = {accounting['retained']} "
            f"retained + {accounting['evicted']} evicted over "
            f"{accounting['rings']} ring(s)",
        )
        check(
            "scrape_health",
            counters.get("fleet.scrape_errors", 0) == 0,
            f"{counters.get('fleet.scrapes', 0)} scrapes, "
            f"{counters.get('fleet.scrape_errors', 0)} unattributed "
            f"error(s), {counters.get('fleet.restart_attributed', 0)} "
            f"restart-attributed, {counters.get('fleet.epochs', 0)} "
            f"counter epoch(s)",
        )
        check(
            "no_slo_breach",
            not self.findings,
            f"{len(self.findings)} breach(es): "
            + (
                ", ".join(
                    f"{f.kind}@{f.node}" for f in self.findings[:8]
                )
                or "none"
            ),
        )
        from openr_tpu.utils.build_info import (
            ARTIFACT_SCHEMA_VERSION,
            build_fingerprint,
        )

        return {
            "schema_version": ARTIFACT_SCHEMA_VERSION,
            "build": build_fingerprint(),
            "config": {
                "scrape_interval_s": self.config.scrape_interval_s,
                "store_capacity": self.config.store_capacity,
                "slo": asdict(self.config.slo),
            },
            "nodes": self.store.nodes(),
            "ticks": self._ticks,
            "counters": counters,
            "store": self.store.snapshot(),
            "findings": [f.to_dict() for f in self.findings],
            "forensics": [
                {
                    "id": d["id"],
                    "reason": d["reason"],
                    "node": d["node"],
                    "ts": d["ts"],
                    "path": d.get("path"),
                }
                for d in self.forensics
            ],
            "verdict": {
                "pass": all(c["ok"] for c in checks.values()),
                "checks": checks,
            },
        }


# ---------------------------------------------------------------------------
# offline replay of soak / scrape artifacts
# ---------------------------------------------------------------------------


def replay_soak_report(
    soak_report: Dict[str, Any], slo: Optional[SloConfig] = None
) -> Dict[str, Any]:
    """Ctrl-free replay: judge a finished soak artifact
    (`testing/soak.py --out`) with the standing fleet rules — the
    windowed e2e p95 trend becomes the fleet-level series, so the same
    budget/step detectors that watch a live fleet re-judge the recorded
    run (`python -m openr_tpu.fleet --replay soak.json`). Accepts a bare
    soak report or a `SOAK_r*` artifact (the report wrapped under
    "soak")."""
    if "windows" not in soak_report and isinstance(
        soak_report.get("soak"), dict
    ):
        soak_report = soak_report["soak"]
    observer = FleetObserver(config=FleetConfig(slo=slo or SloConfig()))
    node = "soak-fleet"
    for i, window in enumerate(soak_report.get("windows", [])):
        if not window.get("events"):
            continue
        ts = float(window.get("start", i))
        observer.store.record(node, E2E_P95, ts, window["e2e_p95_ms"])
        observer.store.record(node, E2E_COUNT, ts, window["events"])
        if window.get("faulted"):
            # chaos windows are attributed discontinuities, same as a
            # live restart window
            observer.store.mark_gap(node, ts, "soak_chaos")
        observer.tick()
    report = observer.report()
    report["replayed"] = {
        "windows": len(soak_report.get("windows", [])),
        "soak_verdict": soak_report.get("verdict", {}).get("pass"),
    }
    return report


def replay_scrape_files(
    paths, slo: Optional[SloConfig] = None
) -> Dict[str, Any]:
    """Ctrl-free replay of raw exposition files: each file is one scrape
    of one node (node label parsed from the exposition), folded in path
    order through the identical collector + rules path."""
    observer = FleetObserver(config=FleetConfig(slo=slo or SloConfig()))
    for i, path in enumerate(paths):
        with open(path) as fh:
            text = fh.read()
        parsed = parse_metrics_text(text)
        node = "unknown"
        for series in parsed["samples"].values():
            for labels in series:
                if 'node="' in labels:
                    node = labels.split('node="', 1)[1].split('"', 1)[0]
                    break
            if node != "unknown":
                break
        observer.feed_scrape(node, float(i), parsed)
        observer.tick()
    return observer.report()


def watch_hosts(
    hosts,
    seconds: float = 10.0,
    config: Optional[FleetConfig] = None,
) -> Dict[str, Any]:
    """Blocking helper for CLI surfaces: attach to a host:port list,
    observe for `seconds`, return the judged report."""
    cfg = config or FleetConfig()

    async def body() -> Dict[str, Any]:
        observer = FleetObserver.for_hosts(hosts, config=cfg)
        await observer.start()
        try:
            await asyncio.sleep(seconds)
        finally:
            await observer.stop()
        return observer.report()

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(body())
    finally:
        loop.close()
