"""DUAL — Diffusing Update Algorithm (EIGRP-style loop-free SPT).

Equivalent of openr/dual/: powers the KvStore flood-topology optimization
(flood only on a spanning tree instead of the full peer mesh).
"""

from openr_tpu.dual.dual import (
    Dual,
    DualMessage,
    DualMessages,
    DualMessageType,
    DualNode,
    DualState,
    INF_DISTANCE,
)

__all__ = [
    "Dual",
    "DualMessage",
    "DualMessages",
    "DualMessageType",
    "DualNode",
    "DualState",
    "INF_DISTANCE",
]
