"""DUAL: Diffusing Update Algorithm computing a loop-free SPT per root.

Behavioral port of openr/dual/Dual.{h,cpp} (the EIGRP/DUAL algorithm of
Garcia-Luna-Aceves; reference cites cs.cornell.edu/people/egs/615/lunes93):
  - DualStateMachine PASSIVE / ACTIVE0-3 transition matrix (Dual.cpp:12-60).
  - Per-root `Dual` instance: route info (distance, reportDistance,
    feasibleDistance, nexthop), neighbor infos (reportDistance,
    expectReply, needToReply), and the `cornet` stack of pending queries.
  - Feasible condition per SNC: a neighbor with reportDistance <
    feasibleDistance whose (localDistance + reportDistance) equals the
    minimum (Dual.cpp:148-169).
  - Local computation when FC holds (Dual.cpp:191-212); diffusing
    computation (queries to all up neighbors, expectReply tracking) when it
    does not (Dual.cpp:213-246).
  - peerUp/peerDown/peerCostChange and UPDATE/QUERY/REPLY processing with
    the exact active-state distance bookkeeping (Dual.cpp:400-712).
  - `DualNode`: multi-root container discovering roots on the fly; SPT
    peers = nexthop + children; smallest root id with a valid route wins
    (Dual.cpp:716-967). I/O is a seam: subclasses implement
    send_dual_messages + process_nexthop_change (used by KvStore flood
    optimization).
"""

from __future__ import annotations

import enum
import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

log = logging.getLogger(__name__)

INF_DISTANCE = 2**63 - 1  # int64 max sentinel, matches the reference


class DualState(enum.Enum):
    ACTIVE0 = "ACTIVE0"
    ACTIVE1 = "ACTIVE1"
    ACTIVE2 = "ACTIVE2"
    ACTIVE3 = "ACTIVE3"
    PASSIVE = "PASSIVE"


class DualEvent(enum.Enum):
    QUERY_FROM_SUCCESSOR = "QUERY_FROM_SUCCESSOR"
    LAST_REPLY = "LAST_REPLY"
    INCREASE_D = "INCREASE_D"
    OTHERS = "OTHERS"


class DualMessageType(enum.Enum):
    UPDATE = 1
    QUERY = 2
    REPLY = 3


@dataclass
class DualMessage:
    """openr/if/Dual.thrift DualMessage: dst root, report distance, type."""

    dst_id: str
    distance: int
    type: DualMessageType


@dataclass
class DualMessages:
    """openr/if/Dual.thrift DualMessages: sender + batch."""

    src_id: str = ""
    messages: List[DualMessage] = field(default_factory=list)


class DualStateMachine:
    """Transition matrix (Dual.cpp:12-60)."""

    def __init__(self) -> None:
        self.state = DualState.PASSIVE

    def process_event(self, event: DualEvent, fc: bool = True) -> None:
        s = self.state
        if s == DualState.PASSIVE:
            if fc:
                return
            self.state = (
                DualState.ACTIVE3
                if event == DualEvent.QUERY_FROM_SUCCESSOR
                else DualState.ACTIVE1
            )
        elif s == DualState.ACTIVE0:
            if event != DualEvent.LAST_REPLY:
                return
            self.state = DualState.PASSIVE if fc else DualState.ACTIVE2
        elif s == DualState.ACTIVE1:
            if event == DualEvent.INCREASE_D:
                self.state = DualState.ACTIVE0
            elif event == DualEvent.LAST_REPLY:
                self.state = DualState.PASSIVE
            elif event == DualEvent.QUERY_FROM_SUCCESSOR:
                self.state = DualState.ACTIVE2
        elif s == DualState.ACTIVE2:
            if event != DualEvent.LAST_REPLY:
                return
            self.state = DualState.PASSIVE if fc else DualState.ACTIVE3
        elif s == DualState.ACTIVE3:
            if event == DualEvent.LAST_REPLY:
                self.state = DualState.PASSIVE
            elif event == DualEvent.INCREASE_D:
                self.state = DualState.ACTIVE2


@dataclass
class NeighborInfo:
    report_distance: int = INF_DISTANCE
    expect_reply: bool = False
    need_to_reply: bool = False


def _add(d1: int, d2: int) -> int:
    """Saturating distance addition (Dual.cpp addDistances)."""
    if d1 == INF_DISTANCE or d2 == INF_DISTANCE:
        return INF_DISTANCE
    return d1 + d2


MsgsToSend = Dict[str, DualMessages]


class Dual:
    """One root's diffusing computation at one node."""

    def __init__(
        self,
        node_id: str,
        root_id: str,
        local_distances: Dict[str, int],
        nexthop_cb,
    ) -> None:
        self.node_id = node_id
        self.root_id = root_id
        self.local_distances = dict(local_distances)
        self.nexthop_cb = nexthop_cb
        self.sm = DualStateMachine()
        self.distance = INF_DISTANCE
        self.report_distance = INF_DISTANCE
        self.feasible_distance = INF_DISTANCE
        self.nexthop: Optional[str] = None
        self.neighbor_infos: Dict[str, NeighborInfo] = {}
        self.cornet: List[str] = []  # stack of pending queriers
        self._children: Set[str] = set()
        self.counters: Dict[str, Dict[str, int]] = {}
        if root_id == node_id:
            self.distance = 0
            self.report_distance = 0
            self.feasible_distance = 0
            self.nexthop = node_id

    # -- bookkeeping -----------------------------------------------------

    def _info(self, neighbor: str) -> NeighborInfo:
        return self.neighbor_infos.setdefault(neighbor, NeighborInfo())

    def _count(self, neighbor: str, counter: str) -> None:
        c = self.counters.setdefault(neighbor, {})
        c[counter] = c.get(counter, 0) + 1
        total = "total_sent" if counter.endswith("_sent") else "total_recv"
        c[total] = c.get(total, 0) + 1

    def _neighbor_up(self, neighbor: str) -> bool:
        return self.local_distances.get(neighbor, INF_DISTANCE) != INF_DISTANCE

    # -- SPT children / peers -------------------------------------------

    def add_child(self, child: str) -> None:
        self._children.add(child)

    def remove_child(self, child: str) -> None:
        self._children.discard(child)

    def children(self) -> Set[str]:
        return set(self._children)

    def has_valid_route(self) -> bool:
        return (
            self.sm.state == DualState.PASSIVE
            and self.nexthop is not None
            and self.distance != INF_DISTANCE
        )

    def spt_peers(self) -> Set[str]:
        if not self.has_valid_route():
            return set()
        peers = set(self._children)
        if self.nexthop is not None and self.nexthop != self.node_id:
            peers.add(self.nexthop)
        return peers

    # -- core computations ----------------------------------------------

    def _min_distance(self) -> int:
        if self.node_id == self.root_id:
            return 0
        dmin = INF_DISTANCE
        for neighbor, ld in self.local_distances.items():
            rd = self._info(neighbor).report_distance
            dmin = min(dmin, _add(ld, rd))
        return dmin

    def _route_affected(self) -> bool:
        """Dual.cpp:99-146."""
        if not self.local_distances:
            return False
        if self.nexthop == self.node_id:
            return False
        dmin = self._min_distance()
        if self.distance != dmin:
            return True
        if dmin == INF_DISTANCE:
            return False
        nexthops = {
            neighbor
            for neighbor, ld in self.local_distances.items()
            if _add(ld, self._info(neighbor).report_distance) == dmin
        }
        return self.nexthop not in nexthops

    def _meet_feasible_condition(self):
        """SNC feasibility (Dual.cpp:148-169) → (nexthop, distance) | None."""
        dmin = self._min_distance()
        for neighbor, ld in self.local_distances.items():
            if ld == INF_DISTANCE:
                continue
            rd = self._info(neighbor).report_distance
            if rd < self.feasible_distance and _add(ld, rd) == dmin:
                return neighbor, dmin
        return None

    def _flood_updates(self, out: MsgsToSend) -> None:
        for neighbor, ld in self.local_distances.items():
            if ld == INF_DISTANCE:
                continue
            out.setdefault(neighbor, DualMessages()).messages.append(
                DualMessage(
                    self.root_id,
                    self.report_distance,
                    DualMessageType.UPDATE,
                )
            )
            self._count(neighbor, "update_sent")

    def _set_nexthop(self, new_nh: Optional[str]) -> None:
        if self.nexthop != new_nh:
            old = self.nexthop
            self.nexthop = new_nh
            if self.nexthop_cb is not None:
                self.nexthop_cb(old, new_nh)

    def _local_computation(
        self, new_nexthop: str, new_distance: int, out: MsgsToSend
    ) -> None:
        """Dual.cpp:191-212."""
        same_rd = new_distance == self.report_distance
        self._set_nexthop(new_nexthop)
        self.distance = new_distance
        self.report_distance = new_distance
        self.feasible_distance = new_distance
        if not same_rd:
            self._flood_updates(out)

    def _diffusing_computation(self, out: MsgsToSend) -> bool:
        """Dual.cpp:213-246."""
        assert self.nexthop is not None
        ld = self.local_distances[self.nexthop]
        rd = self._info(self.nexthop).report_distance
        new_distance = _add(ld, rd)
        self.distance = new_distance
        self.report_distance = new_distance
        self.feasible_distance = new_distance

        success = False
        for neighbor, ldist in self.local_distances.items():
            if ldist == INF_DISTANCE:
                continue
            out.setdefault(neighbor, DualMessages()).messages.append(
                DualMessage(
                    self.root_id,
                    self.report_distance,
                    DualMessageType.QUERY,
                )
            )
            self._count(neighbor, "query_sent")
            self._info(neighbor).expect_reply = True
            success = True
        return success

    def _send_reply(self, out: MsgsToSend) -> None:
        """Dual.cpp:566-595."""
        assert self.cornet, "send reply on empty cornet"
        dst = self.cornet.pop()
        if not self._neighbor_up(dst):
            self._info(dst).need_to_reply = True
            return
        out.setdefault(dst, DualMessages()).messages.append(
            DualMessage(
                self.root_id, self.report_distance, DualMessageType.REPLY
            )
        )
        self._count(dst, "reply_sent")

    def _try_local_or_diffusing(
        self, event: DualEvent, need_reply: bool, out: MsgsToSend
    ) -> None:
        """Dual.cpp:248-294."""
        if not self._route_affected():
            if need_reply:
                self._send_reply(out)
            return
        fc = self._meet_feasible_condition()
        if self.nexthop is None:
            assert fc is not None, "nexthop invalid, must meet FC"
        if fc is not None:
            new_nexthop, new_distance = fc
            self._local_computation(new_nexthop, new_distance, out)
            if need_reply:
                self._send_reply(out)
        else:
            if need_reply and event != DualEvent.QUERY_FROM_SUCCESSOR:
                self._send_reply(out)
            if self._diffusing_computation(out):
                self.sm.process_event(event, False)
            if self.nexthop is not None and not self._neighbor_up(
                self.nexthop
            ):
                self._set_nexthop(None)

    # -- events ----------------------------------------------------------

    def peer_up(self, neighbor: str, cost: int, out: MsgsToSend) -> None:
        """Dual.cpp:400-464."""
        if self.nexthop == neighbor:
            # non-graceful restart of my parent: reset as-if peer-down
            self._set_nexthop(None)
            self.distance = INF_DISTANCE
        self.local_distances[neighbor] = cost
        self._info(neighbor)

        if self.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(DualEvent.OTHERS, False, out)
        elif self._info(neighbor).expect_reply:
            # pending reply resolved by the neighbor coming back
            self.process_reply(
                neighbor,
                DualMessage(
                    self.root_id,
                    self._info(neighbor).report_distance,
                    DualMessageType.REPLY,
                ),
                out,
            )

        out.setdefault(neighbor, DualMessages()).messages.append(
            DualMessage(
                self.root_id, self.report_distance, DualMessageType.UPDATE
            )
        )
        self._count(neighbor, "update_sent")

        if self._info(neighbor).need_to_reply:
            self._info(neighbor).need_to_reply = False
            out.setdefault(neighbor, DualMessages()).messages.append(
                DualMessage(
                    self.root_id,
                    self.report_distance,
                    DualMessageType.REPLY,
                )
            )
            self._count(neighbor, "reply_sent")

    def peer_down(self, neighbor: str, out: MsgsToSend) -> None:
        """Dual.cpp:466-501."""
        self.counters.pop(neighbor, None)
        self.remove_child(neighbor)
        self.local_distances[neighbor] = INF_DISTANCE
        self._info(neighbor).report_distance = INF_DISTANCE
        if self.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(DualEvent.INCREASE_D, False, out)
        else:
            self.sm.process_event(DualEvent.INCREASE_D)
            if self._info(neighbor).expect_reply:
                # as-if the dead neighbor replied with infinite distance
                self.process_reply(
                    neighbor,
                    DualMessage(
                        self.root_id, INF_DISTANCE, DualMessageType.REPLY
                    ),
                    out,
                )

    def peer_cost_change(
        self, neighbor: str, cost: int, out: MsgsToSend
    ) -> None:
        """Dual.cpp:503-527."""
        event = (
            DualEvent.INCREASE_D
            if cost > self.local_distances.get(neighbor, INF_DISTANCE)
            else DualEvent.OTHERS
        )
        self.local_distances[neighbor] = cost
        if self.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(event, False, out)
        else:
            if self.nexthop == neighbor:
                self.distance = _add(
                    cost, self._info(neighbor).report_distance
                )
            self.sm.process_event(event)

    def process_update(
        self, neighbor: str, update: DualMessage, out: MsgsToSend
    ) -> None:
        """Dual.cpp:529-563."""
        assert update.type == DualMessageType.UPDATE
        assert update.dst_id == self.root_id
        self._count(neighbor, "update_recv")
        self._info(neighbor).report_distance = update.distance
        if neighbor not in self.local_distances:
            return  # UPDATE before LINK-UP
        if self.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(DualEvent.OTHERS, False, out)
        else:
            if self.nexthop == neighbor:
                self.distance = _add(
                    self.local_distances[neighbor], update.distance
                )
            self.sm.process_event(DualEvent.OTHERS)

    def process_query(
        self, neighbor: str, query: DualMessage, out: MsgsToSend
    ) -> None:
        """Dual.cpp:597-633."""
        assert query.type == DualMessageType.QUERY
        assert query.dst_id == self.root_id
        self._count(neighbor, "query_recv")
        self._info(neighbor).report_distance = query.distance
        self.cornet.append(neighbor)
        event = (
            DualEvent.QUERY_FROM_SUCCESSOR
            if self.nexthop == neighbor
            else DualEvent.OTHERS
        )
        if self.sm.state == DualState.PASSIVE:
            self._try_local_or_diffusing(event, True, out)
        else:
            if self.nexthop == neighbor:
                self.distance = _add(
                    self.local_distances[self.nexthop],
                    self._info(self.nexthop).report_distance,
                )
            self.sm.process_event(event)
            self._send_reply(out)

    def process_reply(
        self, neighbor: str, reply: DualMessage, out: MsgsToSend
    ) -> None:
        """Dual.cpp:635-712."""
        assert reply.type == DualMessageType.REPLY
        assert reply.dst_id == self.root_id
        self._count(neighbor, "reply_recv")
        info = self._info(neighbor)
        if not info.expect_reply:
            return  # stale reply after link-down: ignore
        info.report_distance = reply.distance
        info.expect_reply = False
        if any(i.expect_reply for i in self.neighbor_infos.values()):
            return  # not the last reply yet

        # all dependents converged: free to pick the optimal successor
        self.sm.process_event(DualEvent.LAST_REPLY, True)
        dmin = INF_DISTANCE
        new_nh: Optional[str] = None
        for nb, ld in self.local_distances.items():
            d = _add(ld, self._info(nb).report_distance)
            if d < dmin:
                dmin = d
                new_nh = nb
        same_rd = dmin == self.report_distance
        self.distance = dmin
        self.report_distance = dmin
        self.feasible_distance = dmin
        self._set_nexthop(new_nh)
        if not same_rd:
            self._flood_updates(out)
        if self.cornet:
            assert len(self.cornet) == 1, "one diffusion per destination"
            self._send_reply(out)


class DualNode:
    """Multi-root DUAL container; subclass provides I/O (Dual.cpp:716+)."""

    def __init__(self, node_id: str, is_root: bool = False) -> None:
        self.node_id = node_id
        self.is_root = is_root
        self.duals: Dict[str, Dual] = {}
        self.local_distances: Dict[str, int] = {}
        self.pkt_counters: Dict[str, Dict[str, int]] = {}
        if is_root:
            self._add_dual(node_id)

    # -- I/O seam --------------------------------------------------------

    def send_dual_messages(
        self, neighbor: str, msgs: DualMessages
    ) -> bool:
        raise NotImplementedError

    def process_nexthop_change(
        self, root_id: str, old_nh: Optional[str], new_nh: Optional[str]
    ) -> None:
        raise NotImplementedError

    # -- events ----------------------------------------------------------

    def peer_up(self, neighbor: str, cost: int) -> None:
        self.local_distances[neighbor] = cost
        out: MsgsToSend = {}
        for dual in self.duals.values():
            dual.peer_up(neighbor, cost, out)
        self._send_all(out)

    def peer_down(self, neighbor: str) -> None:
        self.local_distances[neighbor] = INF_DISTANCE
        self.pkt_counters.pop(neighbor, None)
        out: MsgsToSend = {}
        for dual in self.duals.values():
            dual.peer_down(neighbor, out)
        self._send_all(out)

    def peer_cost_change(self, neighbor: str, cost: int) -> None:
        self.local_distances[neighbor] = cost
        out: MsgsToSend = {}
        for dual in self.duals.values():
            dual.peer_cost_change(neighbor, cost, out)
        self._send_all(out)

    def process_dual_messages(self, messages: DualMessages) -> None:
        neighbor = messages.src_id
        c = self.pkt_counters.setdefault(neighbor, {})
        c["pkt_recv"] = c.get("pkt_recv", 0) + 1
        c["msg_recv"] = c.get("msg_recv", 0) + len(messages.messages)
        out: MsgsToSend = {}
        for msg in messages.messages:
            self._add_dual(msg.dst_id)
            dual = self.duals[msg.dst_id]
            if msg.type == DualMessageType.UPDATE:
                dual.process_update(neighbor, msg, out)
            elif msg.type == DualMessageType.QUERY:
                dual.process_query(neighbor, msg, out)
            elif msg.type == DualMessageType.REPLY:
                dual.process_reply(neighbor, msg, out)
        self._send_all(out)

    # -- getters ---------------------------------------------------------

    def has_dual(self, root_id: str) -> bool:
        return root_id in self.duals

    def get_dual(self, root_id: str) -> Dual:
        return self.duals[root_id]

    def get_spt_root_id(self) -> Optional[str]:
        """Smallest root id with a valid route (Dual.cpp:786-800)."""
        for root_id in sorted(self.duals):
            if self.duals[root_id].has_valid_route():
                return root_id
        return None

    def get_spt_peers(self, root_id: Optional[str]) -> Set[str]:
        if root_id is None or root_id not in self.duals:
            return set()
        return self.duals[root_id].spt_peers()

    def neighbor_is_up(self, neighbor: str) -> bool:
        return self.local_distances.get(neighbor, INF_DISTANCE) != (
            INF_DISTANCE
        )

    # -- internals -------------------------------------------------------

    def _add_dual(self, root_id: str) -> None:
        if root_id in self.duals:
            return
        self.duals[root_id] = Dual(
            self.node_id,
            root_id,
            self.local_distances,
            lambda old, new, r=root_id: self.process_nexthop_change(
                r, old, new
            ),
        )

    def _send_all(self, out: MsgsToSend) -> None:
        for neighbor, msgs in out.items():
            if not msgs.messages:
                continue
            msgs.src_id = self.node_id
            if not self.send_dual_messages(neighbor, msgs):
                log.error("failed to send dual messages to %s", neighbor)
                continue
            c = self.pkt_counters.setdefault(neighbor, {})
            c["pkt_sent"] = c.get("pkt_sent", 0) + 1
            c["msg_sent"] = c.get("msg_sent", 0) + len(msgs.messages)
