"""MPMC queues with close semantics and fan-out replication.

Reference semantics (openr/messaging/Queue.h:72 RWQueue, ReplicateQueue.h:23):
  - push(item) -> bool: False once closed (push after close is dropped).
  - get() awaits until an item is available; raises QueueClosedError when the
    queue is closed and drained.
  - try_get() non-blocking.
  - close() wakes all pending readers with QueueClosedError.
  - ReplicateQueue.get_reader() registers a new reader queue; each push is
    replicated to every open reader; closing the replicate queue closes all
    readers. Reader count and replication stats are exposed like
    ReplicateQueue::getNumReaders / getNumWrites.
"""

from __future__ import annotations

import asyncio
import collections
from typing import Any, Deque, Generic, List, Optional, TypeVar

T = TypeVar("T")


class QueueClosedError(RuntimeError):
    """Raised by get() on a closed, drained queue."""


class RWQueue(Generic[T]):
    def __init__(self) -> None:
        self._items: Deque[T] = collections.deque()
        self._waiters: Deque[asyncio.Future] = collections.deque()
        self._closed = False
        self._num_writes = 0
        self._num_reads = 0

    def push(self, item: T) -> bool:
        if self._closed:
            return False
        self._num_writes += 1
        self._items.append(item)
        self._wake_one()
        return True

    def _wake_one(self) -> None:
        # wake-up futures carry no payload: the woken reader pops from
        # _items itself, so a reader cancelled mid-wakeup never swallows data
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                return

    async def get(self) -> T:
        while not self._items:
            if self._closed:
                raise QueueClosedError("queue is closed")
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._waiters.append(fut)
            try:
                await fut
            except asyncio.CancelledError:
                # pass the wakeup on if it raced with our cancellation
                if fut.done() and not fut.cancelled():
                    self._wake_one()
                raise
            finally:
                if fut in self._waiters:
                    self._waiters.remove(fut)
        self._num_reads += 1
        return self._items.popleft()

    def try_get(self) -> Optional[T]:
        if self._items:
            self._num_reads += 1
            return self._items.popleft()
        return None

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)  # woken readers observe closed state

    @property
    def closed(self) -> bool:
        return self._closed

    def size(self) -> int:
        return len(self._items)

    def __len__(self) -> int:
        return len(self._items)

    @property
    def num_writes(self) -> int:
        return self._num_writes

    @property
    def num_reads(self) -> int:
        return self._num_reads


class RQueue(Generic[T]):
    """Read-only facade over an RWQueue (openr/messaging/Queue.h:35).

    close() detaches this reader from its ReplicateQueue: the producer drops
    closed readers on the next push (the reference GCs readers by shared_ptr
    use-count, ReplicateQueue-inl.h).
    """

    def __init__(self, queue: RWQueue[T]) -> None:
        self._queue = queue

    def close(self) -> None:
        self._queue.close()

    async def get(self) -> T:
        return await self._queue.get()

    def try_get(self) -> Optional[T]:
        return self._queue.try_get()

    def size(self) -> int:
        return self._queue.size()

    def __len__(self) -> int:
        return len(self._queue)

    @property
    def closed(self) -> bool:
        return self._queue.closed


class ReplicateQueue(Generic[T]):
    """Fan-out queue: every push is replicated to all readers."""

    def __init__(self) -> None:
        self._readers: List[RWQueue[T]] = []
        self._closed = False
        self._num_writes = 0

    def get_reader(self) -> RQueue[T]:
        if self._closed:
            raise QueueClosedError("replicate queue is closed")
        q: RWQueue[T] = RWQueue()
        self._readers.append(q)
        return RQueue(q)

    def push(self, item: T) -> bool:
        if self._closed:
            return False
        self._num_writes += 1
        # drop readers that were closed individually
        self._readers = [r for r in self._readers if not r.closed]
        for reader in self._readers:
            reader.push(item)
        return True

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for reader in self._readers:
            reader.close()
        self._readers.clear()

    def get_num_readers(self) -> int:
        self._readers = [r for r in self._readers if not r.closed]
        return len(self._readers)

    @property
    def num_writes(self) -> int:
        return self._num_writes
