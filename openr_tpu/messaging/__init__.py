"""In-process inter-module pub/sub bus.

Equivalent of openr/messaging/{Queue.h,ReplicateQueue.h}: RWQueue is a
multi-producer/multi-consumer blocking queue (folly fiber batons → asyncio
futures), RQueue is its read-only facade handed to consumer modules, and
ReplicateQueue fans every pushed message out to all registered readers — the
bus that connects Spark → LinkMonitor → KvStore → Decision → Fib.
"""

from openr_tpu.messaging.queue import (
    QueueClosedError,
    RQueue,
    RWQueue,
    ReplicateQueue,
)

__all__ = ["QueueClosedError", "RQueue", "RWQueue", "ReplicateQueue"]
