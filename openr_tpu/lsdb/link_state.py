"""Link-state graph with memoized shortest paths — the CPU oracle.

Behavioral port of openr/decision/LinkState.{h,cpp} (structure re-designed for
Python; semantics preserved and cross-checked by tests):
  - HoldableValue (LinkState.h:36-58, LinkState.cpp:54-125): ordered-FIB
    (RFC 6976) value holds — a metric/overload change is masked for a TTL
    chosen by the direction (up vs down) of the change.
  - Link (LinkState.h:82-175): one bidirectional link, keyed by the unordered
    pair of (node, iface) endpoints, carrying per-direction metric/overload
    holds, adjacency labels and nexthop addresses.
  - LinkState (LinkState.h:177-469): graph over Links +
    update_adjacency_database ordered-diff (LinkState.cpp:564-717), Dijkstra
    run_spf with ECMP nexthop-set union and overloaded-node transit pruning
    (LinkState.cpp:806-880), memoization invalidated on topology change
    (LinkState.cpp:712-715), and k-edge-disjoint path enumeration
    get_kth_paths/trace_one_path (LinkState.cpp:760-789, 398-419).

This oracle defines the exact tie-breaking the TPU solver must reproduce:
  - Dijkstra extract-min orders by (metric, nodeName)  (LinkState.h:488-498)
  - relaxation with >= unions nexthop sets for equal-cost paths
    (LinkState.cpp:855-871)
  - overloaded nodes terminate expansion but are themselves reachable
    (LinkState.cpp:829-836)
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from openr_tpu.types import Adjacency, AdjacencyDatabase

Metric = int


class HoldableValue:
    """A value whose previous state can be held for an ordered-FIB TTL."""

    __slots__ = ("_val", "_held_val", "_has_held", "_hold_ttl")

    def __init__(self, val) -> None:
        self._val = val
        self._held_val = None
        self._has_held = False
        self._hold_ttl = 0

    @property
    def value(self):
        return self._held_val if self._has_held else self._val

    def has_hold(self) -> bool:
        return self._has_held

    def assign(self, val) -> None:
        """Unconditional set, clearing any hold (operator= in the reference)."""
        self._val = val
        self._held_val = None
        self._has_held = False
        self._hold_ttl = 0

    def decrement_ttl(self) -> bool:
        """Returns True if an expiring hold changed the visible value."""
        if self._has_held:
            self._hold_ttl -= 1
            if self._hold_ttl == 0:
                self._held_val = None
                self._has_held = False
                return True
        return False

    def update_value(self, val, hold_up_ttl: int, hold_down_ttl: int) -> bool:
        """Returns True if the visible value changed immediately."""
        if val == self._val:
            return False
        if self._has_held:
            # a hold was already pending: fall back to fast update to avoid
            # prolonging transient loops (LinkState.cpp:93-98)
            self._held_val = None
            self._has_held = False
            self._hold_ttl = 0
        else:
            ttl = (
                hold_up_ttl if self._is_change_bringing_up(val) else hold_down_ttl
            )
            self._hold_ttl = ttl
            if ttl != 0:
                self._held_val = self._val
                self._has_held = True
        self._val = val
        return not self._has_held

    def _is_change_bringing_up(self, val) -> bool:
        if isinstance(self._val, bool):
            # clearing an overload is a "bringing up" event
            return self._val and not val
        # lower metric is a "bringing up" event
        return val < self._val


def _hv_value(x):
    """Visible value of a maybe-held slot.

    Link attribute slots hold PLAIN values until a hold is first requested
    (then a HoldableValue) — cold-start ingest builds ~4 slots per link, and
    at 100k-link scale eagerly allocating HoldableValues dominated the
    whole-LSDB ingest profile."""
    return x.value if type(x) is HoldableValue else x


def _hv_update(cur, val, hold_up_ttl: int, hold_down_ttl: int):
    """update_value on a maybe-held slot; returns (new_slot, visible_changed).

    Plain slots with zero hold TTLs stay plain (straight assignment); a
    nonzero TTL promotes the slot to a HoldableValue carrying the hold."""
    if type(cur) is HoldableValue:
        return cur, cur.update_value(val, hold_up_ttl, hold_down_ttl)
    if val == cur:
        return cur, False
    if hold_up_ttl == 0 and hold_down_ttl == 0:
        return val, True
    hv = HoldableValue(cur)
    return hv, hv.update_value(val, hold_up_ttl, hold_down_ttl)


class Link:
    """A single bidirectional network link (LinkState.h:82)."""

    __slots__ = (
        "area",
        "n1",
        "n2",
        "if1",
        "if2",
        "_metric1",
        "_metric2",
        "_overload1",
        "_overload2",
        "_adj_label1",
        "_adj_label2",
        "_nh_v4_1",
        "_nh_v4_2",
        "_nh_v6_1",
        "_nh_v6_2",
        "_hold_up_ttl",
        "key",
        "_hash",
    )

    def __init__(
        self,
        area: str,
        node1: str,
        adj1: Adjacency,
        node2: str,
        adj2: Adjacency,
    ) -> None:
        self.area = area
        self.n1 = node1
        self.n2 = node2
        self.if1 = adj1.if_name
        self.if2 = adj2.if_name
        # plain values; promoted to HoldableValue on first held update
        # (_hv_update) — see _hv_value for why
        self._metric1 = adj1.metric
        self._metric2 = adj2.metric
        self._overload1 = adj1.is_overloaded
        self._overload2 = adj2.is_overloaded
        self._adj_label1 = adj1.adj_label
        self._adj_label2 = adj2.adj_label
        self._nh_v4_1 = adj1.nexthop_v4
        self._nh_v4_2 = adj2.nexthop_v4
        self._nh_v6_1 = adj1.nexthop_v6
        self._nh_v6_2 = adj2.nexthop_v6
        self._hold_up_ttl = 0
        # essential identity: unordered pair of (node, iface) ordered pairs
        # (LinkState.h:107-110); deterministic across processes (the reference
        # additionally orders by an in-process hash, which is arbitrary)
        p1, p2 = (node1, adj1.if_name), (node2, adj2.if_name)
        self.key: Tuple[Tuple[str, str], Tuple[str, str]] = (
            (p1, p2) if p1 <= p2 else (p2, p1)
        )
        # links live in many sets (link_map, all_links, SPF visited/ignore
        # sets); hashing the nested string tuple per membership op is the
        # single hottest line at 100k-link ingest scale, so cache it
        self._hash = hash(self.key)

    # -- identity ----------------------------------------------------------

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return isinstance(other, Link) and self.key == other.key

    def __lt__(self, other: "Link") -> bool:
        return self.key < other.key

    def first_node_name(self) -> str:
        return self.key[0][0]

    def second_node_name(self) -> str:
        return self.key[1][0]

    # -- directional accessors --------------------------------------------

    def _dir(self, node: str) -> int:
        if node == self.n1:
            return 1
        if node == self.n2:
            return 2
        raise ValueError(f"{node} is not an endpoint of {self}")

    def other_node_name(self, node: str) -> str:
        return self.n2 if self._dir(node) == 1 else self.n1

    def iface_from_node(self, node: str) -> str:
        return self.if1 if self._dir(node) == 1 else self.if2

    def metric_from_node(self, node: str) -> Metric:
        return _hv_value(
            self._metric1 if self._dir(node) == 1 else self._metric2
        )

    def adj_label_from_node(self, node: str) -> int:
        return self._adj_label1 if self._dir(node) == 1 else self._adj_label2

    def overload_from_node(self, node: str) -> bool:
        return _hv_value(
            self._overload1 if self._dir(node) == 1 else self._overload2
        )

    def nh_v4_from_node(self, node: str) -> str:
        return self._nh_v4_1 if self._dir(node) == 1 else self._nh_v4_2

    def nh_v6_from_node(self, node: str) -> str:
        return self._nh_v6_1 if self._dir(node) == 1 else self._nh_v6_2

    def set_nh_v4_from_node(self, node: str, nh: str) -> None:
        if self._dir(node) == 1:
            self._nh_v4_1 = nh
        else:
            self._nh_v4_2 = nh

    def set_nh_v6_from_node(self, node: str, nh: str) -> None:
        if self._dir(node) == 1:
            self._nh_v6_1 = nh
        else:
            self._nh_v6_2 = nh

    def set_metric_from_node(
        self, node: str, metric: Metric, hold_up_ttl: int, hold_down_ttl: int
    ) -> bool:
        if self._dir(node) == 1:
            self._metric1, changed = _hv_update(
                self._metric1, metric, hold_up_ttl, hold_down_ttl
            )
        else:
            self._metric2, changed = _hv_update(
                self._metric2, metric, hold_up_ttl, hold_down_ttl
            )
        return changed

    def set_adj_label_from_node(self, node: str, label: int) -> None:
        if self._dir(node) == 1:
            self._adj_label1 = label
        else:
            self._adj_label2 = label

    def set_overload_from_node(
        self, node: str, overload: bool, hold_up_ttl: int, hold_down_ttl: int
    ) -> bool:
        was_up = self.is_up()
        if self._dir(node) == 1:
            self._overload1, _ = _hv_update(
                self._overload1, overload, hold_up_ttl, hold_down_ttl
            )
        else:
            self._overload2, _ = _hv_update(
                self._overload2, overload, hold_up_ttl, hold_down_ttl
            )
        # simplex overloads unsupported: only a change in effective up-ness is
        # a topology change (LinkState.cpp:342-344)
        return was_up != self.is_up()

    # -- holds -------------------------------------------------------------

    def set_hold_up_ttl(self, ttl: int) -> None:
        self._hold_up_ttl = ttl

    def is_up(self) -> bool:
        return (
            self._hold_up_ttl == 0
            and not _hv_value(self._overload1)
            and not _hv_value(self._overload2)
        )

    def decrement_holds(self) -> bool:
        expired = False
        if self._hold_up_ttl != 0:
            self._hold_up_ttl -= 1
            expired |= self._hold_up_ttl == 0
        for slot in (
            self._metric1, self._metric2, self._overload1, self._overload2
        ):
            if type(slot) is HoldableValue:
                expired |= slot.decrement_ttl()
        return expired

    def has_holds(self) -> bool:
        if self._hold_up_ttl != 0:
            return True
        return any(
            type(slot) is HoldableValue and slot.has_hold()
            for slot in (
                self._metric1, self._metric2, self._overload1, self._overload2
            )
        )

    def __repr__(self) -> str:
        return f"{self.area} - {self.n1}%{self.if1} <---> {self.n2}%{self.if2}"

    def directional_str(self, from_node: str) -> str:
        other = self.other_node_name(from_node)
        return (
            f"{self.area} - {from_node}%{self.iface_from_node(from_node)}"
            f" ---> {other}%{self.iface_from_node(other)}"
        )


class NodeSpfResult:
    """SPF result for one destination: metric, path links, nexthop set.

    path_links is the list of (link, prev_node) pairs on shortest paths into
    this node — enough to trace every shortest path back to the source
    (LinkState.h:203-257).
    """

    __slots__ = ("metric", "path_links", "next_hops")

    def __init__(self, metric: Metric) -> None:
        self.metric: Metric = metric
        self.path_links: List[Tuple[Link, str]] = []
        self.next_hops: Set[str] = set()

    def reset(self, new_metric: Metric) -> None:
        self.metric = new_metric
        self.path_links = []
        self.next_hops = set()


SpfResult = Dict[str, NodeSpfResult]
Path = List[Link]


@dataclass
class LinkStateChange:
    """What an LSDB mutation changed (LinkState.h:306-325)."""

    topology_changed: bool = False
    link_attributes_changed: bool = False
    node_label_changed: bool = False

    def __or__(self, other: "LinkStateChange") -> "LinkStateChange":
        return LinkStateChange(
            self.topology_changed or other.topology_changed,
            self.link_attributes_changed or other.link_attributes_changed,
            self.node_label_changed or other.node_label_changed,
        )


class LinkState:
    """Per-area link-state graph with memoized SPF (LinkState.h:177)."""

    def __init__(self, area: str = "0") -> None:
        self.area = area
        self._link_map: Dict[str, Set[Link]] = {}
        # per-node sorted link lists; SPF iterates these so relaxation order
        # (and thus path_links/kth-path selection) is hash-seed independent
        self._ordered_links: Dict[str, List[Link]] = {}
        self._all_links: Set[Link] = set()
        self._node_overloads: Dict[str, HoldableValue] = {}
        self._adjacency_databases: Dict[str, AdjacencyDatabase] = {}
        # memoization: (node, use_link_metric) -> SpfResult
        self._spf_results: Dict[Tuple[str, bool], SpfResult] = {}
        # memoization: (src, dest, k) -> [Path]
        self._kth_path_results: Dict[Tuple[str, str, int], List[Path]] = {}
        # graph changelog for incremental compiled-graph refresh: entries are
        # ("link", Link) weight/up-down change, ("node", name) node-overload
        # change, ("structure", None) link/node add/remove. Consumers remember
        # their read position (graph_log_pos); on overflow old entries are
        # dropped and stale consumers rebuild from scratch
        self._graph_log: List[Tuple[str, object]] = []
        self._graph_log_base = 0
        # counters (fb303 equivalents)
        self.spf_runs = 0
        # monotonically bumped on every topology change; lets external
        # solvers (TPU backend) cache compiled graphs per snapshot
        self.version = 0

    # -- read API ----------------------------------------------------------

    def has_node(self, node: str) -> bool:
        return node in self._adjacency_databases

    def links_from_node(self, node: str) -> Set[Link]:
        return self._link_map.get(node, set())

    def ordered_links_from_node(self, node: str) -> List[Link]:
        cached = self._ordered_links.get(node)
        if cached is None:
            cached = sorted(self._link_map.get(node, set()))
            self._ordered_links[node] = cached
        return cached

    def is_node_overloaded(self, node: str) -> bool:
        hv = self._node_overloads.get(node)
        return hv is not None and hv.value

    def num_links(self) -> int:
        return len(self._all_links)

    def num_nodes(self) -> int:
        return len(self._link_map)

    @property
    def all_links(self) -> Set[Link]:
        return self._all_links

    def get_adjacency_databases(self) -> Dict[str, AdjacencyDatabase]:
        return self._adjacency_databases

    def has_holds(self) -> bool:
        return any(l.has_holds() for l in self._all_links) or any(
            hv.has_hold() for hv in self._node_overloads.values()
        )

    def node_names(self) -> List[str]:
        return list(self._adjacency_databases.keys())

    # -- mutation ----------------------------------------------------------

    def update_adjacency_database(
        self,
        new_adj_db: AdjacencyDatabase,
        hold_up_ttl: int = 0,
        hold_down_ttl: int = 0,
    ) -> LinkStateChange:
        """Ordered diff of a node's links vs. its previous advertisement.

        Mirrors LinkState.cpp:564-717: walk old and new link lists in sorted
        order; insert/remove mismatches; for matches, carry attribute changes
        onto the existing Link object (preserving its holds).
        """
        assert new_adj_db.area == self.area, (
            f"adjacency db area {new_adj_db.area} != link state area {self.area}"
        )
        change = LinkStateChange()
        node = new_adj_db.this_node_name

        prior = self._adjacency_databases.get(node)
        self._adjacency_databases[node] = new_adj_db
        if prior is None:
            self._log_graph("structure")  # node-name set may change

        old_links = self.ordered_links_from_node(node)
        new_links = sorted(self._make_bidirectional_links(new_adj_db))

        overload_changed = self._update_node_overloaded(
            node, new_adj_db.is_overloaded, hold_up_ttl, hold_down_ttl
        )
        if overload_changed:
            self._log_graph("node", node)
        change.topology_changed |= overload_changed
        change.node_label_changed = (
            prior is None and new_adj_db.node_label != 0
        ) or (prior is not None and prior.node_label != new_adj_db.node_label)

        i = j = 0
        while i < len(new_links) or j < len(old_links):
            if i < len(new_links) and (
                j >= len(old_links) or new_links[i] < old_links[j]
            ):
                link = new_links[i]
                link.set_hold_up_ttl(hold_up_ttl)
                change.topology_changed |= link.is_up()
                self._add_link(link)
                self._log_graph("structure")
                i += 1
                continue
            if j < len(old_links) and (
                i >= len(new_links) or old_links[j] < new_links[i]
            ):
                link = old_links[j]
                change.topology_changed |= link.is_up()
                self._remove_link(link)
                self._log_graph("structure")
                j += 1
                continue
            # same link on both sides: diff attributes in place
            new_link, old_link = new_links[i], old_links[j]
            if new_link.metric_from_node(node) != old_link.metric_from_node(
                node
            ):
                if old_link.set_metric_from_node(
                    node,
                    new_link.metric_from_node(node),
                    hold_up_ttl,
                    hold_down_ttl,
                ):
                    change.topology_changed = True
                    self._log_graph("link", old_link)
            if new_link.overload_from_node(node) != old_link.overload_from_node(
                node
            ):
                if old_link.set_overload_from_node(
                    node,
                    new_link.overload_from_node(node),
                    hold_up_ttl,
                    hold_down_ttl,
                ):
                    change.topology_changed = True
                    self._log_graph("link", old_link)
            if new_link.adj_label_from_node(node) != old_link.adj_label_from_node(
                node
            ):
                change.link_attributes_changed = True
                old_link.set_adj_label_from_node(
                    node, new_link.adj_label_from_node(node)
                )
            if new_link.nh_v4_from_node(node) != old_link.nh_v4_from_node(node):
                change.link_attributes_changed = True
                old_link.set_nh_v4_from_node(
                    node, new_link.nh_v4_from_node(node)
                )
            if new_link.nh_v6_from_node(node) != old_link.nh_v6_from_node(node):
                change.link_attributes_changed = True
                old_link.set_nh_v6_from_node(
                    node, new_link.nh_v6_from_node(node)
                )
            i += 1
            j += 1

        if change.topology_changed:
            self._invalidate()
        return change

    def bulk_update_adjacency_databases(
        self, adj_dbs: List[AdjacencyDatabase]
    ) -> LinkStateChange:
        """Cold-start ingest: apply many adjacency databases in one pass.

        Equivalent to calling update_adjacency_database(db) for each db (no
        ordered-FIB holds — cold start predates any FIB state to order
        against), but O(E) instead of O(sum deg(u)*deg(v)): bidirectional
        matching uses one descriptor map over all adjacencies instead of
        the per-adjacency linear scan of the other node's list
        (_maybe_make_link, mirroring LinkState.cpp:531-547). This is the
        KvStore full-sync ingest path (reference hot path:
        LinkState.cpp:564-717 run once per node at cold start).

        Falls back to the incremental path when any incoming node already
        exists — the fast path's correctness argument is only written for
        fresh nodes (no prior links to diff against, no holds to carry).
        """
        adj_dbs = list(adj_dbs)
        if any(
            db.this_node_name in self._adjacency_databases for db in adj_dbs
        ) or len({db.this_node_name for db in adj_dbs}) != len(adj_dbs):
            change = LinkStateChange()
            for db in adj_dbs:
                change |= self.update_adjacency_database(db)
            return change

        change = LinkStateChange()
        for db in adj_dbs:
            assert db.area == self.area, (db.area, self.area)
            node = db.this_node_name
            self._adjacency_databases[node] = db
            self._node_overloads.setdefault(
                node, HoldableValue(db.is_overloaded)
            )
            change.node_label_changed |= db.node_label != 0
        self._log_graph("structure")  # consumers rebuild wholesale

        # descriptor map over ALL known adjacencies (pre-existing nodes
        # included: an incoming node may peer with one). First-wins per
        # descriptor reproduces _maybe_make_link's first-match scan.
        descr: Dict[Tuple[str, str, str, str], Adjacency] = {}
        for other_db in self._adjacency_databases.values():
            other = other_db.this_node_name
            for adj in other_db.adjacencies:
                descr.setdefault(
                    (other, adj.if_name, adj.other_node_name,
                     adj.other_if_name),
                    adj,
                )

        incoming = {db.this_node_name for db in adj_dbs}
        new_links: List[Link] = []
        any_up = False
        for db in adj_dbs:
            node = db.this_node_name
            for adj in db.adjacencies:
                other = adj.other_node_name
                # both-incoming pairs are discovered from each side; keep
                # exactly the side whose (node, iface) sorts first so each
                # link is constructed once
                if other in incoming and (other, adj.other_if_name) < (
                    node, adj.if_name
                ):
                    continue
                other_adj = descr.get(
                    (other, adj.other_if_name, node, adj.if_name)
                )
                if other_adj is None:
                    continue
                link = Link(self.area, node, adj, other, other_adj)
                new_links.append(link)
                if not any_up:
                    any_up = link.is_up()

        # bulk insertion (the set adds dedupe degenerate duplicate
        # adjacencies the same way repeated _add_link calls would)
        self._all_links.update(new_links)
        link_map = self._link_map
        for link in new_links:
            link_map.setdefault(link.n1, set()).add(link)
            link_map.setdefault(link.n2, set()).add(link)
        # sorted-order caches may exist for pre-existing peer nodes; a bulk
        # event is rare enough that dropping them all is cheaper than
        # tracking which endpoints were touched
        self._ordered_links.clear()

        change.topology_changed |= any_up
        if change.topology_changed:
            self._invalidate()
        return change

    def delete_adjacency_database(self, node: str) -> LinkStateChange:
        change = LinkStateChange()
        if node in self._adjacency_databases:
            self._remove_node(node)
            del self._adjacency_databases[node]
            self._log_graph("structure")
            self._invalidate()
            change.topology_changed = True
        return change

    def decrement_holds(self) -> LinkStateChange:
        change = LinkStateChange()
        for link in self._all_links:
            if link.decrement_holds():
                change.topology_changed = True
                self._log_graph("link", link)
        for node, hv in self._node_overloads.items():
            if hv.decrement_ttl():
                change.topology_changed = True
                self._log_graph("node", node)
        if change.topology_changed:
            self._invalidate()
        return change

    # -- shortest paths ----------------------------------------------------

    def get_spf_result(
        self, node: str, use_link_metric: bool = True
    ) -> SpfResult:
        key = (node, use_link_metric)
        result = self._spf_results.get(key)
        if result is None:
            result = self.run_spf(node, use_link_metric)
            self._spf_results[key] = result
        return result

    def get_metric_from_a_to_b(
        self, a: str, b: str, use_link_metric: bool = True
    ) -> Optional[Metric]:
        if a == b:
            return 0
        res = self.get_spf_result(a, use_link_metric)
        return res[b].metric if b in res else None

    def get_hops_from_a_to_b(self, a: str, b: str) -> Optional[Metric]:
        return self.get_metric_from_a_to_b(a, b, use_link_metric=False)

    def get_max_hops_to_node(self, node: str) -> Metric:
        return max(
            (r.metric for r in self.get_spf_result(node, False).values()),
            default=0,
        )

    def run_spf(
        self,
        src: str,
        use_link_metric: bool = True,
        links_to_ignore: Optional[Set[Link]] = None,
    ) -> SpfResult:
        """Dijkstra with ECMP nexthop-set union (LinkState.cpp:806-880).

        Tie-breaking: extract-min orders by (metric, nodeName). Relaxation with
        '>=': an equal-cost path contributes its path link and unions its
        nexthop set. Overloaded nodes are reachable but do not offer transit.
        """
        self.spf_runs += 1
        ignore = links_to_ignore or set()
        result: SpfResult = {}

        # lazy-deletion binary heap keyed by (metric, nodeName); an entry is
        # stale when the node's current best metric differs
        best: Dict[str, NodeSpfResult] = {src: NodeSpfResult(0)}
        heap: List[Tuple[Metric, str]] = [(0, src)]
        while heap:
            metric, node = heapq.heappop(heap)
            if node in result:
                continue
            node_res = best[node]
            if metric != node_res.metric:
                continue  # stale entry
            result[node] = node_res

            if node != src and self.is_node_overloaded(node):
                # reachable, but offers no transit (drained)
                continue

            for link in self.ordered_links_from_node(node):
                other = link.other_node_name(node)
                if not link.is_up() or other in result or link in ignore:
                    continue
                step = link.metric_from_node(node) if use_link_metric else 1
                new_metric = node_res.metric + step
                other_res = best.get(other)
                if other_res is None:
                    other_res = NodeSpfResult(new_metric)
                    best[other] = other_res
                    heapq.heappush(heap, (new_metric, other))
                if other_res.metric >= new_metric:
                    if other_res.metric > new_metric:
                        other_res.reset(new_metric)
                        heapq.heappush(heap, (new_metric, other))
                    other_res.path_links.append((link, node))
                    if node_res.next_hops:
                        other_res.next_hops |= node_res.next_hops
                    else:
                        # directly connected to the source
                        other_res.next_hops.add(other)
        return result

    def get_kth_paths(self, src: str, dest: str, k: int) -> List[Path]:
        """k-th set of edge-disjoint shortest paths (LinkState.cpp:760-789).

        Paths in set k avoid every link used by sets 1..k-1; within a set,
        paths are edge-disjoint, greedily traced from the SPF DAG.
        """
        assert k >= 1
        key = (src, dest, k)
        cached = self._kth_path_results.get(key)
        if cached is not None:
            return cached

        links_to_ignore: Set[Link] = set()
        for i in range(1, k):
            for path in self.get_kth_paths(src, dest, i):
                links_to_ignore.update(path)

        paths: List[Path] = []
        res = (
            self.get_spf_result(src, True)
            if not links_to_ignore
            else self.run_spf(src, True, links_to_ignore)
        )
        if dest in res:
            visited: Set[Link] = set()
            path = self._trace_one_path(src, dest, res, visited)
            while path:  # non-empty path found
                paths.append(path)
                path = self._trace_one_path(src, dest, res, visited)
        self._kth_path_results[key] = paths
        return paths

    def _trace_one_path(
        self, src: str, dest: str, result: SpfResult, visited: Set[Link]
    ) -> Optional[Path]:
        """Greedy back-trace of one path dest→src over unvisited path links
        (LinkState.cpp:398-419). Marks every considered link visited."""
        if src == dest:
            return []
        for link, prev_node in result[dest].path_links:
            if link not in visited:
                visited.add(link)
                sub = self._trace_one_path(src, prev_node, result, visited)
                if sub is not None:
                    sub.append(link)
                    return sub
        return None

    # -- graph changelog (incremental compiled-graph refresh) --------------

    _GRAPH_LOG_CAP = 4096

    @property
    def graph_log_pos(self) -> int:
        """Absolute position of the changelog tail; snapshot at compile."""
        return self._graph_log_base + len(self._graph_log)

    def graph_changes_since(
        self, pos: int
    ) -> Optional[List[Tuple[str, object]]]:
        """Changelog entries since `pos`, or None when they were dropped
        (consumer too stale: rebuild from scratch)."""
        if pos < self._graph_log_base:
            return None
        return self._graph_log[pos - self._graph_log_base :]

    def _log_graph(self, kind: str, obj: object = None) -> None:
        if len(self._graph_log) >= self._GRAPH_LOG_CAP:
            self._graph_log_base += len(self._graph_log)
            self._graph_log = []
        self._graph_log.append((kind, obj))

    # -- internals ---------------------------------------------------------

    def _invalidate(self) -> None:
        self._spf_results.clear()
        self._kth_path_results.clear()
        self.version += 1

    def _update_node_overloaded(
        self, node: str, overloaded: bool, hold_up_ttl: int, hold_down_ttl: int
    ) -> bool:
        hv = self._node_overloads.get(node)
        if hv is not None:
            return hv.update_value(overloaded, hold_up_ttl, hold_down_ttl)
        self._node_overloads[node] = HoldableValue(overloaded)
        return False  # new node: not a link-state change

    def _maybe_make_link(self, node: str, adj: Adjacency) -> Optional[Link]:
        """Create a Link only when the reverse adjacency is also advertised
        (LinkState.cpp:531-547)."""
        other_db = self._adjacency_databases.get(adj.other_node_name)
        if other_db is None:
            return None
        for other_adj in other_db.adjacencies:
            if (
                other_adj.other_node_name == node
                and adj.other_if_name == other_adj.if_name
                and adj.if_name == other_adj.other_if_name
            ):
                return Link(
                    self.area, node, adj, adj.other_node_name, other_adj
                )
        return None

    def _make_bidirectional_links(self, adj_db: AdjacencyDatabase) -> List[Link]:
        links = []
        for adj in adj_db.adjacencies:
            link = self._maybe_make_link(adj_db.this_node_name, adj)
            if link is not None:
                links.append(link)
        return links

    def _add_link(self, link: Link) -> None:
        self._link_map.setdefault(link.first_node_name(), set()).add(link)
        self._link_map.setdefault(link.second_node_name(), set()).add(link)
        self._ordered_links.pop(link.first_node_name(), None)
        self._ordered_links.pop(link.second_node_name(), None)
        self._all_links.add(link)

    def _remove_link(self, link: Link) -> None:
        self._link_map[link.first_node_name()].discard(link)
        self._link_map[link.second_node_name()].discard(link)
        self._ordered_links.pop(link.first_node_name(), None)
        self._ordered_links.pop(link.second_node_name(), None)
        self._all_links.discard(link)

    def _remove_node(self, node: str) -> None:
        links = self._link_map.pop(node, set())
        self._ordered_links.pop(node, None)
        for link in links:
            other = link.other_node_name(node)
            self._link_map.get(other, set()).discard(link)
            self._ordered_links.pop(other, None)
            self._all_links.discard(link)
        self._node_overloads.pop(node, None)


def path_a_in_path_b(a: Path, b: Path) -> bool:
    """True if path A appears contiguously inside path B (LinkState.h:395)."""
    if len(a) > len(b):
        return False
    for i in range(len(b) - len(a) + 1):
        if all(a[x] == b[i + x] for x in range(len(a))):
            return True
    return False
