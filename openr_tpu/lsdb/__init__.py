"""LSDB graph model: LinkState (per-area topology) and PrefixState.

Equivalent of openr/decision/{LinkState,PrefixState}.{h,cpp} — the pure
compute-facing data model consumed by the SPF solvers.
"""

from openr_tpu.lsdb.link_state import (
    HoldableValue,
    Link,
    LinkState,
    LinkStateChange,
    NodeSpfResult,
    SpfResult,
)
from openr_tpu.lsdb.prefix_state import PrefixState

__all__ = [
    "HoldableValue",
    "Link",
    "LinkState",
    "LinkStateChange",
    "NodeSpfResult",
    "SpfResult",
    "PrefixState",
]
