"""Prefix reachability table: prefix → {node → {area → PrefixEntry}}.

Behavioral port of openr/decision/PrefixState.{h,cpp}: update_prefix_database
returns the set of changed prefixes (withdrawals + new/updated advertisements),
and per-node host loopbacks are tracked for BGP bestNexthop resolution
(PrefixState.cpp:36-125, getLoopbackVias :145-163).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from openr_tpu.types import (
    IpPrefix,
    NextHop,
    PrefixDatabase,
    PrefixEntry,
    PrefixForwardingType,
    PrefixType,
)

# prefix -> node -> area -> PrefixEntry
PrefixEntries = Dict[IpPrefix, Dict[str, Dict[str, PrefixEntry]]]


class PrefixState:
    def __init__(self) -> None:
        self._prefixes: PrefixEntries = {}
        # node -> area -> set of prefixes
        self._node_to_prefixes: Dict[str, Dict[str, Set[IpPrefix]]] = {}
        self._node_host_loopbacks_v4: Dict[str, str] = {}
        self._node_host_loopbacks_v6: Dict[str, str] = {}
        # prefixes with any SR_MPLS-forwarding advertisement: their routes
        # (KSP2 path traces, label stacks) can move on ANY edge change, so
        # the DeltaPath partial rebuild must always recompute them — kept
        # as an index so the delta path never scans the full table
        self._mpls_fwd_prefixes: Set[IpPrefix] = set()

    @property
    def prefixes(self) -> PrefixEntries:
        return self._prefixes

    def update_prefix_database(self, prefix_db: PrefixDatabase) -> Set[IpPrefix]:
        """Apply a node's (per-area) prefix advertisement; return changed set."""
        changed: Set[IpPrefix] = set()
        node = prefix_db.this_node_name
        area = prefix_db.area

        old_set = set(
            self._node_to_prefixes.get(node, {}).get(area, set())
        )
        new_set = {e.prefix for e in prefix_db.prefix_entries}
        self._node_to_prefixes.setdefault(node, {})[area] = new_set

        # withdrawals
        for prefix in old_set - new_set:
            by_originator = self._prefixes.get(prefix)
            if by_originator is None or node not in by_originator:
                continue
            by_originator[node].pop(area, None)
            if not by_originator[node]:
                del by_originator[node]
            if not by_originator:
                del self._prefixes[prefix]
            self._delete_loopback_prefix(prefix, node)
            changed.add(prefix)

        # advertisements / updates
        for entry in prefix_db.prefix_entries:
            by_originator = self._prefixes.setdefault(entry.prefix, {})
            if by_originator.get(node, {}).get(area) == entry:
                continue  # unchanged
            by_originator.setdefault(node, {})[area] = entry
            changed.add(entry.prefix)

            if entry.type == PrefixType.LOOPBACK:
                net = entry.prefix.network
                if net.prefixlen == net.max_prefixlen:
                    host = str(net.network_address)
                    if entry.prefix.is_v4:
                        self._node_host_loopbacks_v4[node] = host
                    else:
                        self._node_host_loopbacks_v6[node] = host

        if not new_set:
            areas = self._node_to_prefixes.get(node)
            if areas is not None:
                areas.pop(area, None)
                if not areas:
                    del self._node_to_prefixes[node]

        # maintain the SR_MPLS-forwarding index for exactly the prefixes
        # this update touched (O(announcers-of-changed-prefixes))
        for prefix in changed:
            by_originator = self._prefixes.get(prefix)
            is_mpls = by_originator is not None and any(
                entry.forwarding_type == PrefixForwardingType.SR_MPLS
                for areas_ in by_originator.values()
                for entry in areas_.values()
            )
            if is_mpls:
                self._mpls_fwd_prefixes.add(prefix)
            else:
                self._mpls_fwd_prefixes.discard(prefix)

        return changed

    def _delete_loopback_prefix(self, prefix: IpPrefix, node: str) -> None:
        net = prefix.network
        if net.prefixlen != net.max_prefixlen:
            return
        host = str(net.network_address)
        table = (
            self._node_host_loopbacks_v4
            if prefix.is_v4
            else self._node_host_loopbacks_v6
        )
        if table.get(node) == host:
            del table[node]

    def get_prefix_databases(self) -> Dict[tuple, PrefixDatabase]:
        """Reconstruct per-(node, area) PrefixDatabases.

        PrefixState.cpp:127-143 keys by node only and silently drops all but
        one area for multi-area nodes; keying by (node, area) is lossless.
        """
        out: Dict[tuple, PrefixDatabase] = {}
        for node, area_to_prefixes in self._node_to_prefixes.items():
            for area, prefixes in area_to_prefixes.items():
                db = PrefixDatabase(this_node_name=node, area=area)
                for prefix in sorted(prefixes):
                    db.prefix_entries.append(
                        self._prefixes[prefix][node][area]
                    )
                out[(node, area)] = db
        return out

    def get_loopback_vias(
        self,
        nodes: Set[str],
        is_v4: bool,
        igp_metric: Optional[int] = None,
    ) -> List[NextHop]:
        """Loopback-address nexthops for BGP best-path (PrefixState.cpp:145)."""
        table = (
            self._node_host_loopbacks_v4
            if is_v4
            else self._node_host_loopbacks_v6
        )
        return [
            NextHop(address=table[node], metric=igp_metric or 0)
            for node in sorted(nodes)
            if node in table
        ]

    def has_prefix(self, prefix: IpPrefix) -> bool:
        return prefix in self._prefixes

    def prefixes_for_nodes(self, nodes: Set[str]) -> Set[IpPrefix]:
        """Prefixes advertised (in any area) by any of `nodes` — the
        DeltaPath dirty set of a changed-destination list, read off the
        node index in O(changes) instead of scanning the table."""
        out: Set[IpPrefix] = set()
        for node in nodes:
            for prefixes in self._node_to_prefixes.get(node, {}).values():
                out.update(prefixes)
        return out

    @property
    def mpls_forwarding_prefixes(self) -> Set[IpPrefix]:
        """Prefixes with any SR_MPLS-forwarding advertisement (their KSP2
        path traces can change on edges no distance column reflects)."""
        return self._mpls_fwd_prefixes
