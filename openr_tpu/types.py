"""Wire types for openr-tpu.

Python-native equivalents of the reference thrift IDL (field semantics match;
representation is idiomatic Python dataclasses):
  - openr/if/Lsdb.thrift: Adjacency:44, AdjacencyDatabase:108, PrefixEntry:231,
    PrefixDatabase:337, PerfEvent/PerfEvents:23-34
  - openr/if/KvStore.thrift: Value:20, KeyVals, Publication:228
  - openr/if/Network.thrift: IpPrefix, BinaryAddress, MplsAction, NextHopThrift,
    UnicastRoute, MplsRoute
These are the LSDB/RIB value types that flow between modules and across nodes.
"""

from __future__ import annotations

import enum
import ipaddress
import time
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Constants (openr/common/Constants.h)
# ---------------------------------------------------------------------------

TTL_INFINITY = -(2**31)  # Constants::kTtlInfinity (Constants.h:96)


# ---------------------------------------------------------------------------
# Network types (openr/if/Network.thrift)
# ---------------------------------------------------------------------------


def _normalize_prefix(prefix: str) -> str:
    """Canonicalize an 'addr/len' prefix string (host bits zeroed)."""
    return str(ipaddress.ip_network(prefix, strict=False))


@dataclass(frozen=True, order=True)
class IpPrefix:
    """An IP prefix, e.g. '10.0.0.0/24' or 'fc00::/64'.

    Reference: openr/if/Network.thrift IpPrefix (prefixAddress + prefixLength).
    """

    prefix: str

    def __post_init__(self) -> None:
        net = ipaddress.ip_network(self.prefix, strict=False)
        object.__setattr__(self, "prefix", str(net))
        object.__setattr__(self, "_net", net)  # parsed once; not a field

    @property
    def is_v4(self) -> bool:
        return isinstance(self._net, ipaddress.IPv4Network)

    @property
    def prefix_length(self) -> int:
        return self._net.prefixlen

    @property
    def network(self) -> ipaddress._BaseNetwork:
        return self._net

    def contains(self, addr: str) -> bool:
        return ipaddress.ip_address(addr) in self.network

    def __str__(self) -> str:
        return self.prefix


class MplsActionCode(enum.Enum):
    """openr/if/Network.thrift MplsActionCode."""

    PUSH = "PUSH"
    SWAP = "SWAP"
    PHP = "PHP"  # pop and forward
    POP_AND_LOOKUP = "POP_AND_LOOKUP"


@dataclass(frozen=True)
class MplsAction:
    """openr/if/Network.thrift MplsAction."""

    action: MplsActionCode
    swap_label: Optional[int] = None
    push_labels: Tuple[int, ...] = ()

    def __post_init__(self) -> None:
        if self.action == MplsActionCode.SWAP:
            assert self.swap_label is not None
        if self.action == MplsActionCode.PUSH:
            assert len(self.push_labels) > 0


MPLS_LABEL_MIN = 16  # valid MPLS label range (RFC 3032 reserved below 16)
MPLS_LABEL_MAX = (1 << 20) - 1


def is_mpls_label_valid(label: int) -> bool:
    """openr/common/Util: isMplsLabelValid."""
    return MPLS_LABEL_MIN <= label <= MPLS_LABEL_MAX


@dataclass(frozen=True)
class NextHop:
    """A resolved nexthop: address + outgoing interface + attributes.

    Reference: openr/if/Network.thrift NextHopThrift (address, weight, metric,
    useNonShortestRoute, mplsAction, area).
    """

    address: str  # link-local or loopback address of the neighbor
    iface: Optional[str] = None
    metric: int = 0
    mpls_action: Optional[MplsAction] = None
    use_non_shortest_route: bool = False
    area: Optional[str] = None
    weight: int = 0
    neighbor_node: Optional[str] = None  # convenience (not on the wire)


@dataclass(frozen=True)
class UnicastRoute:
    """openr/if/Network.thrift UnicastRoute: dest prefix + nexthop set."""

    dest: IpPrefix
    nexthops: Tuple[NextHop, ...]


@dataclass(frozen=True)
class MplsRoute:
    """openr/if/Network.thrift MplsRoute: top label + nexthop set."""

    top_label: int
    nexthops: Tuple[NextHop, ...]


# ---------------------------------------------------------------------------
# LSDB types (openr/if/Lsdb.thrift)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PerfEvent:
    """openr/if/Lsdb.thrift PerfEvent:23 — (node, event-name, unix ts ms).

    unix_ts is wall-clock milliseconds; the reference truncates to int, but
    sub-ms producers (the KvStore flood-hop trace, LinkMonitor's
    adjacency-advertise stamps) may stamp floats — consumers only subtract
    stamps, so both representations interoperate.
    """

    node_name: str
    event_descr: str
    unix_ts: float


@dataclass
class PerfEvents:
    """openr/if/Lsdb.thrift PerfEvents:31 — ordered trace of events."""

    events: List[PerfEvent] = field(default_factory=list)

    def add(self, node_name: str, descr: str) -> None:
        self.events.append(
            PerfEvent(node_name, descr, int(time.time() * 1000))
        )

    def add_fine(self, node_name: str, descr: str) -> None:
        """Stamp with sub-ms (float) resolution — per-hop flood latencies
        inside one emulator host are well under a millisecond."""
        self.events.append(PerfEvent(node_name, descr, time.time() * 1000.0))

    def copy(self) -> "PerfEvents":
        return PerfEvents(list(self.events))


@dataclass(frozen=True)
class Adjacency:
    """One directed adjacency advertised by a node.

    Reference: openr/if/Lsdb.thrift Adjacency:44 — otherNodeName, ifName,
    otherIfName, metric, adjLabel, isOverloaded, rtt, nextHopV4/V6.
    """

    other_node_name: str
    if_name: str
    other_if_name: str = ""
    metric: int = 1
    adj_label: int = 0
    is_overloaded: bool = False
    rtt: int = 0  # microseconds
    timestamp: int = 0
    weight: int = 1
    nexthop_v4: str = "0.0.0.0"
    nexthop_v6: str = "fe80::"


@dataclass
class AdjacencyDatabase:
    """All adjacencies advertised by one node in one area.

    Reference: openr/if/Lsdb.thrift AdjacencyDatabase:108 — thisNodeName,
    isOverloaded, adjacencies, nodeLabel, perfEvents, area.
    """

    this_node_name: str
    adjacencies: List[Adjacency] = field(default_factory=list)
    is_overloaded: bool = False
    node_label: int = 0
    area: str = "0"
    perf_events: Optional[PerfEvents] = None


class PrefixType(enum.Enum):
    """openr/if/Network.thrift PrefixType."""

    LOOPBACK = "LOOPBACK"
    DEFAULT = "DEFAULT"
    BGP = "BGP"
    PREFIX_ALLOCATOR = "PREFIX_ALLOCATOR"
    BREEZE = "BREEZE"
    RIB = "RIB"
    CONFIG = "CONFIG"
    VIP = "VIP"


class PrefixForwardingType(enum.Enum):
    """openr/if/OpenrConfig.thrift PrefixForwardingType — IP or SR_MPLS."""

    IP = 0
    SR_MPLS = 1


class PrefixForwardingAlgorithm(enum.Enum):
    """openr/if/OpenrConfig.thrift PrefixForwardingAlgorithm."""

    SP_ECMP = 0
    KSP2_ED_ECMP = 1


# --- BGP metric vectors (openr/if/Lsdb.thrift MetricVector:199-229) ---------


class CompareType(enum.Enum):
    """openr/if/Lsdb.thrift CompareType: tie-break behavior when an entity is
    present in one vector but not the other."""

    WIN_IF_PRESENT = 1
    WIN_IF_NOT_PRESENT = 2
    IGNORE_IF_NOT_PRESENT = 3


@dataclass(frozen=True)
class MetricEntity:
    """openr/if/Lsdb.thrift MetricEntity:199."""

    id: int
    priority: int
    op: CompareType = CompareType.WIN_IF_PRESENT
    is_best_path_tiebreaker: bool = False
    metric: Tuple[int, ...] = ()


@dataclass(frozen=True)
class MetricVector:
    """openr/if/Lsdb.thrift MetricVector:222 — versioned list of entities."""

    version: int = 1
    metrics: Tuple[MetricEntity, ...] = ()


@dataclass(frozen=True)
class PrefixEntry:
    """One prefix advertisement by one node.

    Reference: openr/if/Lsdb.thrift PrefixEntry:231 — prefix, type, data, mv,
    forwardingType, forwardingAlgorithm, minNexthop, prependLabel, tags,
    area_stack, metrics.
    """

    prefix: IpPrefix
    type: PrefixType = PrefixType.LOOPBACK
    forwarding_type: PrefixForwardingType = PrefixForwardingType.IP
    forwarding_algorithm: PrefixForwardingAlgorithm = (
        PrefixForwardingAlgorithm.SP_ECMP
    )
    mv: Optional[MetricVector] = None  # metric vector, required for BGP
    min_nexthop: Optional[int] = None
    prepend_label: Optional[int] = None
    tags: Tuple[str, ...] = ()
    area_stack: Tuple[str, ...] = ()
    data: bytes = b""


@dataclass
class PrefixDatabase:
    """All prefixes advertised by one node.

    Reference: openr/if/Lsdb.thrift PrefixDatabase:337 — thisNodeName,
    prefixEntries, area, deletePrefix, perfEvents.
    """

    this_node_name: str
    prefix_entries: List[PrefixEntry] = field(default_factory=list)
    area: str = "0"
    delete_prefix: bool = False
    perf_events: Optional[PerfEvents] = None


# ---------------------------------------------------------------------------
# LinkMonitor types (openr/if/LinkMonitor.thrift)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class InterfaceInfo:
    """openr/if/LinkMonitor.thrift InterfaceInfo — isUp, ifIndex, networks."""

    is_up: bool
    if_index: int = 0
    networks: Tuple[str, ...] = ()


@dataclass
class InterfaceDatabase:
    """openr/if/LinkMonitor.thrift InterfaceDatabase — thisNodeName +
    ifName → InterfaceInfo map + perfEvents; published by LinkMonitor,
    consumed by Spark (discovery) and Fib (fast nexthop shrink)."""

    this_node_name: str
    interfaces: Dict[str, InterfaceInfo] = field(default_factory=dict)
    perf_events: Optional[PerfEvents] = None


# ---------------------------------------------------------------------------
# KvStore types (openr/if/KvStore.thrift)
# ---------------------------------------------------------------------------


@dataclass
class Value:
    """A versioned value in the replicated store.

    Reference: openr/if/KvStore.thrift Value:20 — version, originatorId,
    value (optional binary), ttl, ttlVersion, hash.
    CRDT ordering: version > originatorId > value bytes; ttlVersion refreshes.
    """

    version: int
    originator_id: str
    value: Optional[bytes] = None
    ttl: int = TTL_INFINITY  # milliseconds; TTL_INFINITY = never expires
    ttl_version: int = 0
    hash: Optional[int] = None

    def copy(self) -> "Value":
        return Value(
            self.version,
            self.originator_id,
            self.value,
            self.ttl,
            self.ttl_version,
            self.hash,
        )


KeyVals = Dict[str, Value]


def generate_hash(version: int, originator_id: str, value: Optional[bytes]) -> int:
    """Stable hash of (version, originatorId, value).

    Reference: openr/common/Util.cpp generateHash — used so full-sync can
    compare values by hash without shipping bodies; int64 like the reference.
    """
    import hashlib

    h = hashlib.blake2b(digest_size=8)
    h.update(str(version).encode())
    h.update(b"\x00")
    h.update(originator_id.encode())
    h.update(b"\x00")
    if value is not None:
        h.update(value)
    return int.from_bytes(h.digest(), "little", signed=True)


@dataclass
class Publication:
    """A batch of key/value updates flooded between stores.

    Reference: openr/if/KvStore.thrift Publication:228 — keyVals, expiredKeys,
    nodeIds (path vector for loop prevention), tobeUpdatedKeys, area.
    """

    key_vals: KeyVals = field(default_factory=dict)
    expired_keys: List[str] = field(default_factory=list)
    node_ids: Optional[List[str]] = None
    tobe_updated_keys: Optional[List[str]] = None
    area: str = "0"
    # time.monotonic() stamp set by the local KvStore when it hands this
    # publication to internal subscribers — seeds Decision's convergence
    # span (monitor/spans.py). Host-local only: never serialized (wire.py
    # rebuilds publications without it) and meaningless across processes.
    ts_monotonic: Optional[float] = None
    # monotonic (stage, ts) marks that happened BEFORE the publish stamp —
    # spark.neighbor_event → linkmonitor.adj_advertised, handed through the
    # module chain on the originating node so Decision's span covers
    # hello-to-programmed-route. Host-local like ts_monotonic: never
    # serialized, dropped at process boundaries.
    span_stages: Optional[List[Tuple[str, float]]] = None
    # wall-clock flood-hop trace (KVSTORE_FLOOD_ORIGINATED + one
    # KVSTORE_FLOOD_RECEIVED per hop): unlike the two fields above this DOES
    # cross nodes — it rides the KEY_SET RPC next to node_ids (wire.py), so
    # every hop can measure per-hop flood latency and remote nodes can
    # reconstruct the origin stages of their convergence spans.
    perf_events: Optional[PerfEvents] = None


# ---------------------------------------------------------------------------
# Key naming (openr/common/Constants.h kAdjDbMarker/kPrefixDbMarker)
# ---------------------------------------------------------------------------

ADJ_DB_MARKER = "adj:"
PREFIX_DB_MARKER = "prefix:"


def adj_key(node_name: str) -> str:
    return f"{ADJ_DB_MARKER}{node_name}"


def prefix_key(
    node_name: str, prefix: Optional[IpPrefix] = None, area: Optional[str] = None
) -> str:
    """Per-node or per-prefix key naming (openr/common/Util.h parsePrefixKey)."""
    if prefix is None:
        return f"{PREFIX_DB_MARKER}{node_name}"
    area_part = area if area is not None else "0"
    return f"{PREFIX_DB_MARKER}{node_name}:{area_part}:[{prefix}]"


def parse_prefix_key(key: str) -> Tuple[str, Optional[str], Optional[IpPrefix]]:
    """Parse 'prefix:<node>[:<area>:[<prefix>]]' → (node, area, prefix)."""
    assert key.startswith(PREFIX_DB_MARKER)
    rest = key[len(PREFIX_DB_MARKER):]
    if ":[" not in rest:
        return rest, None, None
    node_area, _, pfx = rest.partition(":[")
    node, _, area = node_area.rpartition(":")
    if not node:
        node, area = area, None
    return node, area, IpPrefix(pfx.rstrip("]"))


__all__ = [
    "TTL_INFINITY",
    "IpPrefix",
    "MplsActionCode",
    "MplsAction",
    "is_mpls_label_valid",
    "NextHop",
    "UnicastRoute",
    "MplsRoute",
    "PerfEvent",
    "PerfEvents",
    "Adjacency",
    "AdjacencyDatabase",
    "PrefixType",
    "PrefixForwardingType",
    "PrefixForwardingAlgorithm",
    "CompareType",
    "MetricEntity",
    "MetricVector",
    "PrefixEntry",
    "PrefixDatabase",
    "InterfaceInfo",
    "InterfaceDatabase",
    "Value",
    "KeyVals",
    "generate_hash",
    "Publication",
    "ADJ_DB_MARKER",
    "PREFIX_DB_MARKER",
    "adj_key",
    "prefix_key",
    "parse_prefix_key",
    "replace",
]
