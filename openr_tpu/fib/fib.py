"""Fib module: consumes DecisionRouteUpdate deltas and programs them into a
platform FIB agent, with restart detection and full-resync recovery.

Behavioral port of openr/fib/Fib.{h,cpp}:
  - RouteState caches (Fib.h:183-207): unicast/mpls route maps, dirty
    prefix/label sets (link-down shrunk groups), dirtyRouteDb flag.
  - processRouteUpdates (Fib.cpp:303-352): drop doNotInstall routes, update
    caches, program the delta.
  - processInterfaceDb (Fib.cpp:355-484): on interface down, shrink ECMP
    groups to nexthops on still-up interfaces (delete route if none remain);
    on interface up, restore the full group for dirty routes.
  - updateRoutes (Fib.cpp:498-610): best-nexthop (min-metric) selection;
    skip delta when a full sync is pending; failure marks dirtyRouteDb and
    schedules debounced full sync with exponential backoff (8ms..4096ms,
    Fib.cpp:37-38).
  - syncRouteDb (Fib.cpp:612-672): syncFib/syncMplsFib full-state push,
    clears dirty sets on success.
  - keepAliveCheck (Fib.cpp:681-695): poll agent aliveSince; a change means
    agent restart → enforce full sync.
  - longestPrefixMatch + filtered route getters (Fib.cpp:157-299).
  - perf-event convergence logging (Fib.cpp:760-843): appends
    FIB_ROUTE_DB_RECVD / OPENR_FIB_ROUTES_PROGRAMMED, keeps a bounded
    perfDb_ ring, exports fib.convergence_time_ms; ordered-FIB mode persists
    the local programming time into KvStore under 'fibTime:<node>'.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from openr_tpu.messaging import QueueClosedError, RQueue
from openr_tpu.platform import FIB_CLIENT_OPENR, FibService
from openr_tpu.solver import DecisionRouteUpdate
from openr_tpu.types import (
    InterfaceDatabase,
    IpPrefix,
    MplsActionCode,
    MplsRoute,
    NextHop,
    PerfEvents,
    UnicastRoute,
)
from openr_tpu.testing.faults import fault_point
from openr_tpu.utils import ExponentialBackoff
from openr_tpu.utils.counters import CountersMixin, HistogramsMixin
from openr_tpu.utils.ownership import owned_by

log = logging.getLogger(__name__)

# Constants.h kPerfBufferSize / kConvergenceMaxDuration
PERF_BUFFER_SIZE = 10
CONVERGENCE_MAX_MS = 3000.0
FIB_TIME_MARKER = "fibTime:"  # Constants::kFibTimeMarker
# one LogSample per restart-failure forensics dump (stale-deadline flush,
# GR expiry mid-boot, resync divergence — docs/Monitoring.md event catalog)
FIB_RESTART_FORENSICS_DUMPED = "FIB_RESTART_FORENSICS_DUMPED"


def get_best_nexthops_unicast(nexthops: List[NextHop]) -> List[NextHop]:
    """Min-metric ECMP group (+ useNonShortestRoute KSP2 members).

    Reference: openr/common/Util.cpp getBestNextHopsUnicast:474-495.
    """
    if len(nexthops) <= 1:
        return list(nexthops)
    min_cost = min(nh.metric for nh in nexthops)
    return [
        nh
        for nh in nexthops
        if nh.metric == min_cost or nh.use_non_shortest_route
    ]


def get_best_nexthops_mpls(nexthops: List[NextHop]) -> List[NextHop]:
    """Min-metric MPLS group, preferring PHP over SWAP at equal cost.

    Reference: openr/common/Util.cpp getBestNextHopsMpls:497-535.
    """
    if len(nexthops) <= 1:
        return list(nexthops)
    min_cost = min(nh.metric for nh in nexthops)
    action = MplsActionCode.SWAP
    for nh in nexthops:
        if (
            nh.metric == min_cost
            and nh.mpls_action is not None
            and nh.mpls_action.action == MplsActionCode.PHP
        ):
            action = MplsActionCode.PHP
    return [
        nh
        for nh in nexthops
        if nh.metric == min_cost
        and nh.mpls_action is not None
        and nh.mpls_action.action == action
    ]


def longest_prefix_match(
    addr_prefix: str, unicast_routes: Dict[IpPrefix, UnicastRoute]
) -> Optional[IpPrefix]:
    """Longest-prefix match of 'addr' or 'addr/len' against the route table.

    Reference: openr/fib/Fib.cpp longestPrefixMatch:157-177.
    """
    import ipaddress

    if "/" not in addr_prefix:
        addr_prefix += (
            "/128" if ":" in addr_prefix else "/32"
        )
    net = ipaddress.ip_network(addr_prefix, strict=False)
    best: Optional[IpPrefix] = None
    best_len = -1
    for prefix in unicast_routes:
        db_net = prefix.network
        if db_net.version != net.version:
            continue
        if (
            best_len < db_net.prefixlen <= net.prefixlen
            and net.subnet_of(db_net)
        ):
            best_len = db_net.prefixlen
            best = prefix
    return best


@dataclass
class FibConfig:
    my_node_name: str
    dryrun: bool = False
    enable_segment_routing: bool = False
    enable_ordered_fib: bool = False
    # hold before the first full sync when no EOR gates it (Fib.cpp:73-76
    # coldStartDuration). 0.0 — the seed default — synced immediately and
    # wiped surviving agent routes before Decision had converged; the
    # daemon wires fib_config.cold_start_duration_s (default 1s) and
    # tests that want the old immediate sync pass 0.0 explicitly.
    cold_start_duration: float = 1.0
    # warm boot (docs/Fib.md): agent routes recovered at start are kept
    # forwarding as STALE until the first Decision route db reconciles
    # them; past this deadline the stale set is force-flushed with a
    # forensics dump (the restarted daemon never converged)
    stale_sweep_deadline_s: float = 300.0
    # restart-forensics artifact directory (shares the PR 13 flight-
    # recorder dump path/schema; None = in-memory dumps only)
    forensics_dir: Optional[str] = None
    keep_alive_interval: float = 30.0  # Constants::kKeepAliveCheckInterval
    backoff_min: float = 0.008  # Fib.cpp:37-38
    backoff_max: float = 4.096
    # decorrelated jitter on the full-sync retry schedule: when a fleet's
    # agents restart together, deterministic doubling re-synchronizes every
    # node's resync attempts into storms — jitter (utils/backoff.py)
    # decorrelates them. Seed is injectable for deterministic tests.
    backoff_jitter: bool = True
    backoff_seed: Optional[int] = None
    has_eor_time: bool = False  # eor_time_s set → Decision gates first sync


@dataclass
class _RouteState:
    """Fib.h:183-207 RouteState + the warm-boot stale sets."""

    unicast_routes: Dict[IpPrefix, UnicastRoute] = field(default_factory=dict)
    mpls_routes: Dict[int, MplsRoute] = field(default_factory=dict)
    has_routes_from_decision: bool = False
    dirty_prefixes: Set[IpPrefix] = field(default_factory=set)
    dirty_labels: Set[int] = field(default_factory=set)
    dirty_route_db: bool = False
    # warm boot: agent routes that survived a daemon restart, kept
    # forwarding until the first post-boot sync reconciles them
    # (Fib.cpp:612-672 stale-route sweep)
    stale_prefixes: Set[IpPrefix] = field(default_factory=set)
    stale_labels: Set[int] = field(default_factory=set)

    def has_stale(self) -> bool:
        return bool(self.stale_prefixes or self.stale_labels)


@owned_by("fib-loop")
class Fib(CountersMixin, HistogramsMixin):
    def __init__(
        self,
        config: FibConfig,
        fib_service: FibService,
        route_updates: RQueue,
        interface_updates: Optional[RQueue] = None,
        kvstore_client=None,
        log_sample_fn=None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.config = config
        self.fib_service = fib_service
        self.route_updates = route_updates
        self.interface_updates = interface_updates
        self.kvstore_client = kvstore_client
        # sink for finished convergence spans (monitor log-sample queue's
        # push in the daemon; None drops the CONVERGENCE_TRACE samples)
        self._log_sample_fn = log_sample_fn
        self._loop = loop

        self.route_state = _RouteState()
        self.interface_status_db: Dict[str, bool] = {}
        self.perf_db: List[PerfEvents] = []
        self._recent_perf_ts = 0
        self.has_synced_fib = False
        # one-shot per-delta programming delay (seconds), consumed before
        # the agent RPCs: the `fib.program` fault point's action hook sets
        # it to emulate a slow FIB agent deterministically — the same
        # throttle pattern as `ctrl.stream.deliver` (docs/Robustness.md);
        # the added latency lands in the span's fib.program stage
        self.program_throttle_s = 0.0
        import random as _random

        self._backoff = ExponentialBackoff(
            config.backoff_min,
            config.backoff_max,
            jitter=config.backoff_jitter,
            rng=(
                _random.Random(config.backoff_seed)
                if config.backoff_seed is not None
                else None
            ),
        )
        # single-slot semaphore serializing route programming across the
        # route-update and interface-update consumers (Fib.h:270)
        self._program_lock = asyncio.Lock()
        self._sync_scheduled = False
        self._sync_handle: Optional[asyncio.TimerHandle] = None
        self._tasks: List[asyncio.Task] = []
        # warm boot: stale-sweep deadline timer + the restart-convergence
        # anchor (the monotonic stamp of the previous incarnation's
        # restarting-hello flood; closing the first post-boot sync
        # observes restart.e2e_ms against it)
        self._stale_deadline_handle: Optional[asyncio.TimerHandle] = None
        self._restart_anchor_ts: Optional[float] = None
        self._forensics = None  # lazy FlightRecorder (PR 13 dump path)
        self.counters: Dict[str, int] = {}
        self.histograms: Dict = {}

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        self._tasks.append(self.loop().create_task(self._boot()))

    async def _boot(self) -> None:
        """Warm-boot recovery, then the consumer loops.

        The agent's surviving route table is read BEFORE any programming
        can happen: recovered entries are marked stale and keep
        forwarding; the first full sync is then gated on Decision's
        initial converged route db (`has_eor_time`, or simply the first
        route update) and runs as a reconciliation diff instead of a
        wholesale replace (docs/Fib.md "Cold start, EOR and warm boot").
        Queued route updates wait in the reader until the recovery read
        finishes, so ordering is preserved."""
        await self._recover_agent_routes()
        if not self.config.has_eor_time:
            # no EOR gating: sync once the cold-start hold expires
            # (Fib.cpp:73-76). With a clean (empty) agent the sync is
            # allowed to run routeless — it wipes nothing; with recovered
            # stale routes it additionally waits for the first Decision
            # route db (or the stale-sweep deadline), never wiping a
            # forwarding table before the daemon has reconverged.
            if not self.route_state.has_stale():
                self.route_state.has_routes_from_decision = True
            self._schedule_sync(self.config.cold_start_duration)
        self._tasks.append(self.loop().create_task(self._consume_routes()))
        if self.interface_updates is not None:
            self._tasks.append(
                self.loop().create_task(self._consume_interfaces())
            )
        if not self.config.dryrun:
            self._tasks.append(self.loop().create_task(self._keep_alive()))

    def stop(self) -> None:
        for task in self._tasks:
            task.cancel()
        self._tasks.clear()
        if self._sync_handle is not None:
            self._sync_handle.cancel()
            self._sync_handle = None
        if self._stale_deadline_handle is not None:
            self._stale_deadline_handle.cancel()
            self._stale_deadline_handle = None

    async def _consume_routes(self) -> None:
        while True:
            try:
                delta = await self.route_updates.get()
            except (QueueClosedError, asyncio.CancelledError):
                return
            await self.process_route_updates(delta)

    async def _consume_interfaces(self) -> None:
        while True:
            try:
                if_db = await self.interface_updates.get()
            except (QueueClosedError, asyncio.CancelledError):
                return
            await self.process_interface_db(if_db)

    async def _keep_alive(self) -> None:
        while True:
            try:
                await asyncio.sleep(self.config.keep_alive_interval)
                await self.keep_alive_check()
            except asyncio.CancelledError:
                return
            except Exception:
                self._bump("fib.thrift.failure.keepalive")
                log.exception("fib keepalive failed")

    # ------------------------------------------------------------------
    # warm boot (graceful-restart resilience, docs/Robustness.md)
    # ------------------------------------------------------------------

    async def _recover_agent_routes(self) -> None:
        """Read the agent's surviving route table at start and mark every
        entry stale. The agent keeps forwarding on these through the
        daemon gap; the first reconciliation sync sweeps only the
        leftovers. A failed read (agent down, cold machine boot) is the
        clean cold start — nothing stale, nothing gated."""
        if self.config.dryrun:
            return
        try:
            unicast = await self.fib_service.get_route_table_by_client(
                FIB_CLIENT_OPENR
            )
            mpls: List[MplsRoute] = []
            if self.config.enable_segment_routing:
                mpls = await self.fib_service.get_mpls_route_table_by_client(
                    FIB_CLIENT_OPENR
                )
        except Exception:
            self._bump("fib.thrift.failure.route_dump")
            log.exception("warm-boot route recovery failed; cold start")
            return
        if not unicast and not mpls:
            return
        self.route_state.stale_prefixes = {r.dest for r in unicast}
        self.route_state.stale_labels = {r.top_label for r in mpls}
        self._bump("fib.warm_boots")
        counters = self._ensure_counters()
        counters["fib.warm_boot_routes"] = len(unicast) + len(mpls)
        log.info(
            "warm boot: %d unicast + %d mpls agent routes recovered as "
            "stale; first sync gated on Decision convergence",
            len(unicast),
            len(mpls),
        )
        self._stale_deadline_handle = self.loop().call_later(
            self.config.stale_sweep_deadline_s, self._stale_deadline_expired
        )

    def note_restart_anchor(self, ts_monotonic: float) -> None:
        """Arm the restart-convergence span: `ts_monotonic` is the stamp
        of the previous incarnation's restarting-hello flood (the restart
        harness carries it across the daemon gap). The first successful
        post-boot sync closes the span into `restart.e2e_ms`."""
        self._restart_anchor_ts = ts_monotonic

    def _note_sync_complete(self) -> None:
        """Bookkeeping after any successful full sync: the stale state is
        reconciled (sweep happened or there was nothing stale) and a
        pending restart span closes."""
        if self._stale_deadline_handle is not None:
            self._stale_deadline_handle.cancel()
            self._stale_deadline_handle = None
        self.route_state.stale_prefixes.clear()
        self.route_state.stale_labels.clear()
        if self._restart_anchor_ts is not None:
            self._observe(
                "restart.e2e_ms",
                (time.monotonic() - self._restart_anchor_ts) * 1e3,
            )
            self._restart_anchor_ts = None

    def _stale_deadline_expired(self) -> None:
        """Bounded staleness: Decision never converged within
        `stale_sweep_deadline_s` of the warm boot. Snapshot forensics,
        then force-flush — the sync runs with whatever (possibly empty)
        route db exists, sweeping every leftover stale route. Bounded
        blackholing beats forwarding into a topology that moved on."""
        self._stale_deadline_handle = None
        if not self.route_state.has_stale():
            return
        self._bump("fib.stale_deadline_flushes")
        self.dump_restart_forensics(
            "stale_deadline_flush",
            extra={
                "deadline_s": self.config.stale_sweep_deadline_s,
                "has_routes_from_decision": (
                    self.route_state.has_routes_from_decision
                ),
            },
        )
        log.warning(
            "stale-sweep deadline expired with %d unreconciled routes; "
            "force-flushing",
            len(self.route_state.stale_prefixes)
            + len(self.route_state.stale_labels),
        )
        self.route_state.has_routes_from_decision = True
        self.route_state.dirty_route_db = True
        self._schedule_sync(0.0)

    def dump_restart_forensics(self, reason: str, extra=None) -> Dict:
        """Snapshot a restart-failure forensics artifact through the
        PR 13 flight-recorder dump path (same schema/artifact flow as the
        solver fault domain): stale-deadline flushes dump here directly;
        the restart harness dumps GR-expiry-mid-boot and resync-
        divergence failures through the same seam. Emits one
        FIB_RESTART_FORENSICS_DUMPED LogSample carrying the dump id."""
        from openr_tpu.solver.flight_recorder import FlightRecorder

        if self._forensics is None:
            self._forensics = FlightRecorder(
                node=self.config.my_node_name,
                forensics_dir=self.config.forensics_dir,
            )
        context = {
            "stale_prefixes": sorted(
                str(p) for p in self.route_state.stale_prefixes
            )[:64],
            "stale_labels": sorted(self.route_state.stale_labels)[:64],
            "unicast_routes": len(self.route_state.unicast_routes),
            "has_synced_fib": self.has_synced_fib,
            **(extra or {}),
        }
        dump = self._forensics.dump(
            reason, counters=dict(self.counters), extra=context
        )
        self._bump("fib.forensics_dumps")
        if self._log_sample_fn is not None:
            from openr_tpu.monitor.monitor import LogSample

            sample = LogSample()
            sample.add_string("event", FIB_RESTART_FORENSICS_DUMPED)
            sample.add_string("reason", reason)
            sample.add_string("forensics_id", dump["id"])
            sample.add_int(
                "stale_routes",
                len(self.route_state.stale_prefixes)
                + len(self.route_state.stale_labels),
            )
            try:
                self._log_sample_fn(sample)
            except Exception:
                pass  # a closed monitor queue must never break shutdown
        return dump

    # ------------------------------------------------------------------
    # route update processing
    # ------------------------------------------------------------------

    async def process_route_updates(self, delta: DecisionRouteUpdate) -> None:
        """Fib.cpp:303-352."""
        self.route_state.has_routes_from_decision = True
        perf_events = delta.perf_events
        if isinstance(perf_events, PerfEvents):
            perf_events.add(self.config.my_node_name, "FIB_ROUTE_DB_RECVD")
        span = getattr(delta, "span", None)
        if span is not None:
            span.mark("fib.recv")

        unicast_to_update: List[UnicastRoute] = []
        for entry in delta.unicast_routes_to_update:
            if entry.do_not_install:
                continue
            route = entry.to_unicast_route()
            self.route_state.unicast_routes[route.dest] = route
            self.route_state.dirty_prefixes.discard(route.dest)
            unicast_to_update.append(route)
        mpls_to_update: List[MplsRoute] = []
        for mpls_entry in delta.mpls_routes_to_update:
            route = mpls_entry.to_mpls_route()
            self.route_state.mpls_routes[route.top_label] = route
            self.route_state.dirty_labels.discard(route.top_label)
            mpls_to_update.append(route)
        for dest in delta.unicast_routes_to_delete:
            self.route_state.unicast_routes.pop(dest, None)
            self.route_state.dirty_prefixes.discard(dest)
        for label in delta.mpls_routes_to_delete:
            self.route_state.mpls_routes.pop(label, None)
            self.route_state.dirty_labels.discard(label)

        self._bump("fib.process_route_db")
        await self._update_routes(
            unicast_to_update,
            list(delta.unicast_routes_to_delete),
            mpls_to_update,
            list(delta.mpls_routes_to_delete),
            perf_events,
            span=span,
        )

    async def process_interface_db(self, if_db: InterfaceDatabase) -> None:
        """Fast local reaction to link events: shrink/restore ECMP groups
        (Fib.cpp:355-484)."""
        self._bump("fib.process_interface_db")
        perf_events = if_db.perf_events
        if isinstance(perf_events, PerfEvents):
            perf_events.add(self.config.my_node_name, "FIB_INTF_DB_RECEIVED")
        for if_name, info in if_db.interfaces.items():
            self.interface_status_db[if_name] = info.is_up

        unicast_to_update: List[UnicastRoute] = []
        unicast_to_delete: List[IpPrefix] = []
        for dest, route in self.route_state.unicast_routes.items():
            valid = [
                nh
                for nh in route.nexthops
                if nh.iface is None
                or self.interface_status_db.get(nh.iface, False)
            ]
            prev_best = get_best_nexthops_unicast(list(route.nexthops))
            valid_best = get_best_nexthops_unicast(valid)
            if not valid_best:
                unicast_to_delete.append(dest)
                self.route_state.dirty_prefixes.add(dest)
            elif set(valid_best) != set(prev_best):
                unicast_to_update.append(UnicastRoute(dest, tuple(valid_best)))
                self.route_state.dirty_prefixes.add(dest)
            elif dest in self.route_state.dirty_prefixes:
                # interfaces back up: restore the full group
                unicast_to_update.append(route)
                self.route_state.dirty_prefixes.discard(dest)

        mpls_to_update: List[MplsRoute] = []
        mpls_to_delete: List[int] = []
        for label, mpls_route in self.route_state.mpls_routes.items():
            valid = [
                nh
                for nh in mpls_route.nexthops
                if nh.iface is None
                or self.interface_status_db.get(nh.iface, False)
            ]
            prev_best = get_best_nexthops_mpls(list(mpls_route.nexthops))
            valid_best = get_best_nexthops_mpls(valid)
            if not valid_best:
                mpls_to_delete.append(label)
                self.route_state.dirty_labels.add(label)
            elif set(valid_best) != set(prev_best):
                mpls_to_update.append(MplsRoute(label, tuple(valid_best)))
                self.route_state.dirty_labels.add(label)
            elif label in self.route_state.dirty_labels:
                mpls_to_update.append(mpls_route)
                self.route_state.dirty_labels.discard(label)

        await self._update_routes(
            unicast_to_update,
            unicast_to_delete,
            mpls_to_update,
            mpls_to_delete,
            perf_events,
        )

    # ------------------------------------------------------------------
    # programming
    # ------------------------------------------------------------------

    async def _update_routes(
        self,
        unicast_to_update: List[UnicastRoute],
        unicast_to_delete: List[IpPrefix],
        mpls_to_update: List[MplsRoute],
        mpls_to_delete: List[int],
        perf_events: Optional[PerfEvents],
        span=None,
    ) -> None:
        """Incremental delta programming (Fib.cpp:498-610)."""
        async with self._program_lock:
            self.update_global_counters()
            t0 = time.perf_counter()
            # best-nexthop (min-metric) groups actually get programmed
            unicast_best = [
                UnicastRoute(
                    r.dest, tuple(get_best_nexthops_unicast(list(r.nexthops)))
                )
                for r in unicast_to_update
            ]
            mpls_best = [
                MplsRoute(
                    r.top_label, tuple(get_best_nexthops_mpls(list(r.nexthops)))
                )
                for r in mpls_to_update
            ]

            if self.config.dryrun:
                self.log_perf_events(perf_events)
                self._finish_span(span, t0)
                return
            if self._sync_scheduled:
                return  # pending full sync subsumes this delta
            if self.route_state.dirty_route_db or not self.has_synced_fib:
                self._schedule_sync(0.0)
                return

            try:
                # named fault seam: injected programming failures ride the
                # exact dirty-marking + debounced-resync path a thrift
                # failure would (docs/Robustness.md)
                fault_point("fib.program", self)
                delay, self.program_throttle_s = self.program_throttle_s, 0.0
                if delay:
                    await asyncio.sleep(delay)
                n = 0
                if unicast_to_delete:
                    n += len(unicast_to_delete)
                    await self.fib_service.delete_unicast_routes(
                        FIB_CLIENT_OPENR, unicast_to_delete
                    )
                if unicast_best:
                    n += len(unicast_best)
                    await self.fib_service.add_unicast_routes(
                        FIB_CLIENT_OPENR, unicast_best
                    )
                if self.config.enable_segment_routing and mpls_to_delete:
                    n += len(mpls_to_delete)
                    await self.fib_service.delete_mpls_routes(
                        FIB_CLIENT_OPENR, mpls_to_delete
                    )
                if self.config.enable_segment_routing and mpls_best:
                    n += len(mpls_best)
                    await self.fib_service.add_mpls_routes(
                        FIB_CLIENT_OPENR, mpls_best
                    )
                self._bump("fib.num_of_route_updates", n)
                self.route_state.dirty_route_db = False
                self.log_perf_events(perf_events)
                self._finish_span(span, t0)
            except Exception:
                self._bump("fib.thrift.failure.add_del_route")
                self.route_state.dirty_route_db = True
                log.exception("failed to program route delta; scheduling sync")
                self._schedule_sync(0.0)

    async def sync_route_db(self) -> bool:
        """Full-state push (Fib.cpp:612-672).

        Warm boot turns the first sync into a **reconciliation diff**:
        with stale (agent-recovered) routes outstanding, the desired
        routes are programmed as adds and only the stale leftovers —
        prefixes the agent still carries that Decision no longer wants —
        are deleted. The agent's forwarding table is never wholesale
        replaced, so it stays continuously non-empty through the
        reconvergence; `fib.stale_routes_swept` counts the sweep."""
        unicast = [
            UnicastRoute(
                r.dest, tuple(get_best_nexthops_unicast(list(r.nexthops)))
            )
            for r in self.route_state.unicast_routes.values()
        ]
        mpls = [
            MplsRoute(
                r.top_label, tuple(get_best_nexthops_mpls(list(r.nexthops)))
            )
            for r in self.route_state.mpls_routes.values()
        ]
        if self.config.dryrun:
            self._note_sync_complete()
            return True
        try:
            fault_point("fib.sync", self)
            self._bump("fib.sync_fib_calls")
            if self.route_state.has_stale():
                await self._reconcile_sync(unicast, mpls)
            else:
                await self.fib_service.sync_fib(FIB_CLIENT_OPENR, unicast)
                if self.config.enable_segment_routing:
                    await self.fib_service.sync_mpls_fib(
                        FIB_CLIENT_OPENR, mpls
                    )
            self.route_state.dirty_prefixes.clear()
            self.route_state.dirty_labels.clear()
            self.route_state.dirty_route_db = False
            self._note_sync_complete()
            return True
        except Exception:
            self._bump("fib.thrift.failure.sync_fib")
            self.route_state.dirty_route_db = True
            log.exception("failed to sync route db with fib agent")
            return False

    async def _reconcile_sync(
        self, unicast: List[UnicastRoute], mpls: List[MplsRoute]
    ) -> None:
        """The warm-boot sweep: add every desired route, delete exactly
        the stale leftovers. Raises propagate to sync_route_db's retry
        path with the stale sets intact (the sweep re-runs whole)."""
        desired_prefixes = {r.dest for r in unicast}
        leftover_prefixes = sorted(
            p
            for p in self.route_state.stale_prefixes
            if p not in desired_prefixes
        )
        if unicast:
            await self.fib_service.add_unicast_routes(
                FIB_CLIENT_OPENR, unicast
            )
        if leftover_prefixes:
            await self.fib_service.delete_unicast_routes(
                FIB_CLIENT_OPENR, leftover_prefixes
            )
        swept = len(leftover_prefixes)
        if self.config.enable_segment_routing:
            desired_labels = {r.top_label for r in mpls}
            leftover_labels = sorted(
                l
                for l in self.route_state.stale_labels
                if l not in desired_labels
            )
            if mpls:
                await self.fib_service.add_mpls_routes(FIB_CLIENT_OPENR, mpls)
            if leftover_labels:
                await self.fib_service.delete_mpls_routes(
                    FIB_CLIENT_OPENR, leftover_labels
                )
            swept += len(leftover_labels)
        self._bump("fib.restart_reconciles")
        if swept:
            self._bump("fib.stale_routes_swept", swept)
        log.info(
            "warm-boot reconciliation: %d routes programmed, %d stale "
            "leftovers swept",
            len(unicast) + len(mpls),
            swept,
        )

    def _schedule_sync(self, delay: float) -> None:
        """syncRouteDbDebounced (Fib.cpp:675-680): one pending sync max."""
        if self._sync_scheduled:
            return
        self._sync_scheduled = True
        self._sync_handle = self.loop().call_later(
            delay, lambda: self.loop().create_task(self._run_sync())
        )

    async def _run_sync(self) -> None:
        """syncRoutesTimer_ callback (Fib.cpp:48-62)."""
        async with self._program_lock:
            self._sync_scheduled = False
            self._sync_handle = None
            if not self.route_state.has_routes_from_decision:
                return
            if await self.sync_route_db():
                self.has_synced_fib = True
                self._backoff.report_success()
            else:
                self._backoff.report_error()
                self._schedule_sync(
                    self._backoff.get_time_remaining_until_retry()
                )

    async def keep_alive_check(self) -> None:
        """Agent-restart detection (Fib.cpp:681-695)."""
        # named fault seam, ctx=self: tests arm actions here to kill or
        # restart the stub agent exactly when the poll observes it
        fault_point("fib.keepalive", self)
        alive_since = await self.fib_service.alive_since()
        if getattr(self, "_latest_alive_since", None) not in (
            None,
            alive_since,
        ):
            log.warning("fib agent restarted; scheduling full sync")
            self.route_state.dirty_route_db = True
            self._backoff.report_success()
            self._schedule_sync(0.0)
        self._latest_alive_since = alive_since

    # ------------------------------------------------------------------
    # read APIs (OpenrCtrl surface)
    # ------------------------------------------------------------------

    def get_route_db(self) -> Dict[str, list]:
        return {
            "this_node_name": self.config.my_node_name,
            "unicast_routes": list(self.route_state.unicast_routes.values()),
            "mpls_routes": list(self.route_state.mpls_routes.values()),
        }

    def get_unicast_routes(
        self, prefixes: Optional[List[str]] = None
    ) -> List[UnicastRoute]:
        """All routes, or longest-prefix matches of the filters
        (Fib.cpp:233-281)."""
        if not prefixes:
            return list(self.route_state.unicast_routes.values())
        matched: Set[IpPrefix] = set()
        for prefix_str in prefixes:
            match = longest_prefix_match(
                prefix_str, self.route_state.unicast_routes
            )
            if match is not None:
                matched.add(match)
        return [
            self.route_state.unicast_routes[p] for p in sorted(matched)
        ]

    def get_mpls_routes(
        self, labels: Optional[List[int]] = None
    ) -> List[MplsRoute]:
        if not labels:
            return list(self.route_state.mpls_routes.values())
        label_set = set(labels)
        return [
            r
            for label, r in self.route_state.mpls_routes.items()
            if label in label_set
        ]

    def get_perf_db(self) -> List[PerfEvents]:
        return list(self.perf_db)

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def update_global_counters(self) -> None:
        """Fib.cpp:735-758."""
        counters = self._ensure_counters()
        counters["fib.num_unicast_routes"] = len(
            self.route_state.unicast_routes
        )
        counters["fib.num_mpls_routes"] = len(self.route_state.mpls_routes)
        counters["fib.num_routes"] = (
            counters["fib.num_unicast_routes"]
            + counters["fib.num_mpls_routes"]
        )
        counters["fib.num_dirty_prefixes"] = len(
            self.route_state.dirty_prefixes
        )
        counters["fib.num_dirty_labels"] = len(self.route_state.dirty_labels)
        counters["fib.num_stale_routes"] = len(
            self.route_state.stale_prefixes
        ) + len(self.route_state.stale_labels)
        counters["fib.synced"] = 0 if self._sync_scheduled else 1

    def _finish_span(self, span, t0: float) -> None:
        """Close one convergence span after routes are programmed (or
        dryrun-accepted): programming latency and end-to-end
        publication→programmed latency land in this module's histograms,
        and the finished stage trace goes out as one CONVERGENCE_TRACE
        LogSample through the monitor queue. All math runs on the
        monotonic clock (Span/perf_counter) — wall-clock steps never skew
        these, unlike the PerfEvents-derived fib.convergence_time_ms."""
        self._observe("fib.program_ms", (time.perf_counter() - t0) * 1e3)
        if span is None:
            return
        span.mark("fib.program")
        self._observe("convergence.e2e_ms", span.elapsed_ms())
        self._bump("fib.convergence_spans")
        if self._log_sample_fn is not None:
            self._log_sample_fn(span.to_log_sample())

    def log_perf_events(self, perf_events: Optional[PerfEvents]) -> None:
        """Convergence measurement (Fib.cpp:760-843)."""
        if not isinstance(perf_events, PerfEvents) or not perf_events.events:
            return
        first_ts = perf_events.events[0].unix_ts
        if self._recent_perf_ts >= first_ts:
            return  # stale sample
        self._recent_perf_ts = first_ts
        perf_events.add(
            self.config.my_node_name, "OPENR_FIB_ROUTES_PROGRAMMED"
        )
        total_ms = perf_events.events[-1].unix_ts - first_ts
        if self.config.enable_ordered_fib and self.kvstore_client is not None:
            # local programming time from holds-expiry → programmed
            hold_ts = next(
                (
                    e.unix_ts
                    for e in perf_events.events
                    if e.event_descr == "ORDERED_FIB_HOLDS_EXPIRED"
                ),
                None,
            )
            if hold_ts is not None:
                local_ms = perf_events.events[-1].unix_ts - hold_ts
                if 0 <= local_ms <= CONVERGENCE_MAX_MS:
                    self.kvstore_client.persist_key(
                        FIB_TIME_MARKER + self.config.my_node_name,
                        str(local_ms).encode(),
                    )
        if total_ms < 0 or total_ms > CONVERGENCE_MAX_MS:
            return
        self.perf_db.append(perf_events.copy())
        while len(self.perf_db) >= PERF_BUFFER_SIZE:
            self.perf_db.pop(0)
        self._bump("fib.convergence_time_ms", int(total_ms))
        self._bump("fib.route_convergence_events")
