"""Fib module: programs computed routes into the platform FIB agent.

Equivalent of openr/fib/Fib.{h,cpp}.
"""

from openr_tpu.fib.fib import Fib, FibConfig, get_best_nexthops_mpls, get_best_nexthops_unicast, longest_prefix_match

__all__ = [
    "Fib",
    "FibConfig",
    "get_best_nexthops_unicast",
    "get_best_nexthops_mpls",
    "longest_prefix_match",
]
