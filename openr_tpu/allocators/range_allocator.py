"""Distributed value election over KvStore.

Behavioral port of openr/allocators/RangeAllocator.{h,-inl.h}: claim a value
from an integer range by advertising `<keyPrefix><value>` into KvStore;
conflicts resolve by the CRDT tie-break (higher originatorId wins at equal
version). Losing triggers a re-try with a seeded-random fresh value under
exponential backoff (50ms..2s). `override_owner=False` keeps joiners from
stealing values owned by lower-id incumbents (Terragraph semantics,
RangeAllocator.h:46-49).
"""

from __future__ import annotations

import asyncio
import random
from typing import Callable, Optional, Tuple

from openr_tpu.kvstore import KvStoreClient
from openr_tpu.types import TTL_INFINITY, Value
from openr_tpu.utils import ExponentialBackoff

RANGE_ALLOC_TTL_MS = 30_000  # Constants::kRangeAllocTtl


def _encode(value: int) -> bytes:
    return value.to_bytes(8, "little", signed=False)


def _decode(blob: bytes) -> int:
    return int.from_bytes(blob, "little", signed=False)


class RangeAllocator:
    def __init__(
        self,
        node_name: str,
        key_prefix: str,
        kvstore_client: KvStoreClient,
        callback: Callable[[Optional[int]], None],
        min_backoff: float = 0.05,
        max_backoff: float = 2.0,
        override_owner: bool = True,
        check_value_in_use: Optional[Callable[[int], bool]] = None,
        ttl_ms: int = RANGE_ALLOC_TTL_MS,
        area: str = "0",
        rng: Optional[random.Random] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.node_name = node_name
        self.key_prefix = key_prefix
        self.client = kvstore_client
        self.callback = callback
        self.override_owner = override_owner
        self.check_value_in_use = check_value_in_use
        self.ttl_ms = ttl_ms
        self.area = area
        self._rng = rng or random.Random()
        self._loop = loop
        self._backoff = ExponentialBackoff(min_backoff, max_backoff)
        self._range: Optional[Tuple[int, int]] = None
        self.my_value: Optional[int] = None
        self._requested_value: Optional[int] = None
        self._timer: Optional[asyncio.TimerHandle] = None
        self._started = False

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    # ------------------------------------------------------------------

    def start_allocator(
        self,
        alloc_range: Tuple[int, int],
        init_value: Optional[int] = None,
    ) -> None:
        assert not self._started, "already started"
        assert alloc_range[0] <= alloc_range[1], "invalid range"
        self._started = True
        self._range = alloc_range
        if init_value is None:
            init_value = alloc_range[0]
        else:
            # a stale persisted index may fall outside the configured range
            init_value = min(max(init_value, alloc_range[0]), alloc_range[1])
        self._schedule_try(init_value)

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if self.my_value is not None:
            self.client.unset_key(self._key(self.my_value), area=self.area)

    def get_value(self) -> Optional[int]:
        return self.my_value

    def get_value_from_kvstore(self) -> Optional[int]:
        for key, value in self._dump_range().items():
            if value.originator_id == self.node_name:
                return _decode(value.value)
        return None

    def is_range_consumed(self) -> bool:
        assert self._range is not None
        lo, hi = self._range
        count = sum(
            1
            for value in self._dump_range().values()
            if lo <= _decode(value.value) <= hi
        )
        return count == hi - lo + 1

    # ------------------------------------------------------------------

    def _key(self, value: int) -> str:
        return f"{self.key_prefix}{value}"

    def _dump_range(self):
        from openr_tpu.kvstore.store import KvStoreFilters

        pub = self.client.kvstore.dump_all(
            area=self.area,
            filters=KvStoreFilters(key_prefixes=[self.key_prefix]),
        )
        return {
            k: v for k, v in pub.key_vals.items() if v.value is not None
        }

    def _schedule_try(self, value: int) -> None:
        self._backoff.report_error()
        self._timer = self.loop().call_later(
            self._backoff.get_time_remaining_until_retry(),
            self._try_allocate,
            value,
        )

    def _try_allocate(self, new_val: int) -> None:
        """tryAllocate (RangeAllocator-inl.h:170-250)."""
        self._timer = None
        if self.my_value is not None:
            return
        key = self._key(new_val)
        existing = self.client.get_key(key, area=self.area)

        should_own_other = (
            existing is None
            or (self.override_owner and self.node_name > existing.originator_id)
            # prefer TTL'd keys over infinite-ttl leftovers when not stealing
            or (not self.override_owner and existing.ttl == TTL_INFINITY)
        )
        should_own_mine = (
            existing is not None
            and existing.originator_id == self.node_name
        )
        if not should_own_other and not should_own_mine:
            self._schedule_allocate(new_val)
            return
        if self.check_value_in_use is not None and self.check_value_in_use(
            new_val
        ):
            self._schedule_allocate(new_val)
            return

        if should_own_other:
            self._requested_value = new_val
            ttl_version = existing.ttl_version + 1 if existing else 0
            self.client.kvstore.set_key(
                key,
                Value(
                    version=1,
                    originator_id=self.node_name,
                    value=_encode(new_val),
                    ttl=self.ttl_ms,
                    ttl_version=ttl_version,
                ),
                area=self.area,
            )
            # our write may have lost the CRDT merge to a concurrent claim
            stored = self.client.get_key(key, area=self.area)
            if stored is not None and stored.originator_id == self.node_name:
                self._on_won(new_val)
            else:
                self._schedule_allocate(new_val)
                return
        else:
            # reboot with kvstore intact: refresh ttl and accept
            refreshed = existing.copy()
            refreshed.ttl_version += 1
            refreshed.ttl = self.ttl_ms
            self.client.kvstore.set_key(key, refreshed, area=self.area)
            self._on_won(new_val)

        self.client.subscribe_key(key, self._key_updated, area=self.area)

    def _on_won(self, value: int) -> None:
        self.my_value = value
        self._requested_value = None
        self._backoff.report_success()
        # keep the claim alive: persist re-advertises on clobber + ttl refresh
        self.client.persist_key(
            self._key(value),
            _encode(value),
            area=self.area,
            ttl=self.ttl_ms,
        )
        self.callback(value)

    def _schedule_allocate(self, seed_val: int) -> None:
        """Pick a fresh random value not owned by a higher id
        (RangeAllocator-inl.h:259-304)."""
        assert self._range is not None
        lo, hi = self._range
        size = hi - lo + 1
        new_val = self._rng.randint(lo, hi)
        owners = {
            _decode(v.value): v.originator_id
            for v in self._dump_range().values()
        }
        for _ in range(size):
            owner = owners.get(new_val)
            if owner is None or (
                self.override_owner and self.node_name >= owner
            ):
                if self.check_value_in_use is None or not (
                    self.check_value_in_use(new_val)
                ):
                    break
            new_val = new_val + 1 if new_val < hi else lo
        self._schedule_try(new_val)

    def _key_updated(self, key: str, value: Optional[Value]) -> None:
        """keyValUpdated (RangeAllocator-inl.h:306-345): detect losing our
        claimed/allocated value to a higher originator."""
        if value is None or value.value is None:
            return
        if value.originator_id < self.node_name:
            return  # an intermediate lower id; ours will override
        if value.originator_id == self.node_name:
            if self.my_value is None and self._requested_value is not None:
                self._on_won(_decode(value.value))
            return
        # lost to a higher originator: release and try another value
        lost = _decode(value.value)
        if self.my_value == lost or self._requested_value == lost:
            self.my_value = None
            self._requested_value = None
            self.client.unset_key(key, area=self.area)
            self.callback(None)
            if self._timer is None:
                self._schedule_allocate(lost)
