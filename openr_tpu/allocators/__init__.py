"""Distributed allocators over KvStore.

Equivalents of openr/allocators/: RangeAllocator (generic distributed value
election) and PrefixAllocator (plug-and-play prefix assignment).
"""

from openr_tpu.allocators.range_allocator import RangeAllocator
from openr_tpu.allocators.prefix_allocator import (
    PrefixAllocationMode,
    PrefixAllocationParams,
    PrefixAllocator,
    PrefixAllocatorConfig,
)

__all__ = [
    "RangeAllocator",
    "PrefixAllocationMode",
    "PrefixAllocationParams",
    "PrefixAllocator",
    "PrefixAllocatorConfig",
]
