"""Plug-and-play prefix election.

Behavioral port of openr/allocators/PrefixAllocator.{h,cpp}: each node
elects a unique sub-prefix of a seed prefix and advertises it via
PrefixManager. Three modes (OpenrConfig.thrift:93-97):
  - DYNAMIC_LEAF_NODE: learn seed params from the KvStore key
    'e2e-network-prefix' (Constants.h:109).
  - DYNAMIC_ROOT_NODE: seed params from config; also advertise them into
    KvStore for the leaves.
  - STATIC: a mapping node → prefix under 'e2e-network-allocations'
    (Constants.h:113).
The elected sub-prefix index comes from RangeAllocator over
[0, 2^(alloc_len - seed_len)); the winning index is persisted in the
config store so reboots retry the same index, and the address can be
synced onto the loopback interface (PrefixAllocator.cpp:654-699).
"""

from __future__ import annotations

import asyncio
import enum
import ipaddress
import logging
from dataclasses import dataclass
from typing import Callable, Optional

from openr_tpu.allocators.range_allocator import RangeAllocator
from openr_tpu.configstore import PersistentStore
from openr_tpu.kvstore import KvStoreClient
from openr_tpu.types import IpPrefix, PrefixEntry, PrefixType, Value
from openr_tpu.utils import serializer

log = logging.getLogger(__name__)

SEED_PREFIX_KEY = "e2e-network-prefix"  # Constants.h:109
STATIC_ALLOC_KEY = "e2e-network-allocations"  # Constants.h:113
ALLOC_KEY_MARKER = "allocprefix:"  # Constants.h:199
CONFIG_STORE_KEY = "prefix-allocator-config"


class PrefixAllocationMode(enum.Enum):
    DYNAMIC_LEAF_NODE = "DYNAMIC_LEAF_NODE"
    DYNAMIC_ROOT_NODE = "DYNAMIC_ROOT_NODE"
    STATIC = "STATIC"


@dataclass(frozen=True)
class PrefixAllocationParams:
    seed_prefix: IpPrefix
    alloc_prefix_len: int

    def __post_init__(self) -> None:
        assert self.alloc_prefix_len > self.seed_prefix.prefix_length, (
            "allocation length must exceed seed prefix length"
        )

    @property
    def range_size(self) -> int:
        return 1 << (self.alloc_prefix_len - self.seed_prefix.prefix_length)

    @staticmethod
    def parse(text: str) -> "PrefixAllocationParams":
        """Parse 'fc00:cafe::/56,64' (the KvStore seed-param format)."""
        seed, _, alloc_len = text.partition(",")
        return PrefixAllocationParams(IpPrefix(seed), int(alloc_len))

    def encode(self) -> str:
        return f"{self.seed_prefix},{self.alloc_prefix_len}"


def get_nth_prefix(params: PrefixAllocationParams, index: int) -> IpPrefix:
    """The index-th sub-prefix of alloc_prefix_len under the seed."""
    assert 0 <= index < params.range_size, index
    net = params.seed_prefix.network
    addr_bits = net.max_prefixlen
    base = int(net.network_address)
    sub = base | (index << (addr_bits - params.alloc_prefix_len))
    addr = ipaddress.ip_address(sub)
    return IpPrefix(f"{addr}/{params.alloc_prefix_len}")


@dataclass
class PrefixAllocatorConfig:
    node_name: str
    mode: PrefixAllocationMode = PrefixAllocationMode.DYNAMIC_LEAF_NODE
    # required for DYNAMIC_ROOT_NODE; ignored otherwise
    params: Optional[PrefixAllocationParams] = None
    area: str = "0"
    set_loopback_addr: bool = False
    loopback_iface: str = "lo"


class PrefixAllocator:
    def __init__(
        self,
        config: PrefixAllocatorConfig,
        kvstore_client: KvStoreClient,
        config_store: Optional[PersistentStore] = None,
        # advertise/withdraw hooks: PrefixManager APIs in the full daemon
        on_advertise: Optional[Callable[[PrefixEntry], None]] = None,
        on_withdraw: Optional[Callable[[IpPrefix], None]] = None,
        system_handler=None,  # NetlinkSocket-like, for loopback addr sync
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.config = config
        self.client = kvstore_client
        self.config_store = config_store
        self.on_advertise = on_advertise
        self.on_withdraw = on_withdraw
        self.system_handler = system_handler
        self._loop = loop
        self.params: Optional[PrefixAllocationParams] = None
        self.my_prefix: Optional[IpPrefix] = None
        self._range_alloc: Optional[RangeAllocator] = None
        self._started = False

    # ------------------------------------------------------------------

    def start(self) -> None:
        assert not self._started
        self._started = True
        mode = self.config.mode
        if mode == PrefixAllocationMode.DYNAMIC_LEAF_NODE:
            self.client.subscribe_key(
                SEED_PREFIX_KEY, self._seed_param_updated, area=self.config.area
            )
            existing = self.client.get_key(
                SEED_PREFIX_KEY, area=self.config.area
            )
            if existing is not None and existing.value is not None:
                self._apply_params(
                    PrefixAllocationParams.parse(existing.value.decode())
                )
        elif mode == PrefixAllocationMode.DYNAMIC_ROOT_NODE:
            assert self.config.params is not None, "root mode needs params"
            # advertise seed for the leaves
            self.client.persist_key(
                SEED_PREFIX_KEY,
                self.config.params.encode().encode(),
                area=self.config.area,
            )
            self._apply_params(self.config.params)
        else:  # STATIC
            self.client.subscribe_key(
                STATIC_ALLOC_KEY,
                self._static_alloc_updated,
                area=self.config.area,
            )
            existing = self.client.get_key(
                STATIC_ALLOC_KEY, area=self.config.area
            )
            if existing is not None and existing.value is not None:
                self._static_alloc_updated(STATIC_ALLOC_KEY, existing)

    def stop(self) -> None:
        if self._range_alloc is not None:
            self._range_alloc.stop()
            self._range_alloc = None

    def get_prefix(self) -> Optional[IpPrefix]:
        return self.my_prefix

    # ------------------------------------------------------------------
    # dynamic modes
    # ------------------------------------------------------------------

    def _seed_param_updated(self, key: str, value: Optional[Value]) -> None:
        if value is None or value.value is None:
            return
        try:
            params = PrefixAllocationParams.parse(value.value.decode())
        except Exception:
            log.exception("malformed seed prefix param: %r", value.value)
            return
        self._apply_params(params)

    def _apply_params(self, params: PrefixAllocationParams) -> None:
        if params == self.params:
            return
        if self._range_alloc is not None:
            self._range_alloc.stop()
            self._withdraw()
        self.params = params
        init_index = self._load_index()
        self._range_alloc = RangeAllocator(
            self.config.node_name,
            ALLOC_KEY_MARKER,
            self.client,
            self._index_allocated,
            area=self.config.area,
            loop=self._loop,
        )
        self._range_alloc.start_allocator(
            (0, params.range_size - 1), init_index
        )

    def _index_allocated(self, index: Optional[int]) -> None:
        if index is None:
            self._withdraw()
            return
        assert self.params is not None
        prefix = get_nth_prefix(self.params, index)
        self._save_index(index)
        self._announce(prefix)

    # ------------------------------------------------------------------
    # static mode
    # ------------------------------------------------------------------

    def _static_alloc_updated(self, key: str, value: Optional[Value]) -> None:
        if value is None or value.value is None:
            return
        try:
            alloc = serializer.loads(value.value)
            node_prefixes = dict(alloc)
        except Exception:
            log.exception("malformed static allocation value")
            return
        mine = node_prefixes.get(self.config.node_name)
        if mine is None:
            self._withdraw()
        else:
            self._announce(IpPrefix(str(mine)))

    # ------------------------------------------------------------------
    # announce / withdraw
    # ------------------------------------------------------------------

    def _announce(self, prefix: IpPrefix) -> None:
        if prefix == self.my_prefix:
            return
        self._withdraw()
        self.my_prefix = prefix
        log.info("%s allocated prefix %s", self.config.node_name, prefix)
        if self.on_advertise is not None:
            self.on_advertise(
                PrefixEntry(prefix=prefix, type=PrefixType.PREFIX_ALLOCATOR)
            )
        if self.config.set_loopback_addr and self.system_handler is not None:
            self._sync_loopback(prefix)

    def _withdraw(self) -> None:
        if self.my_prefix is None:
            return
        prefix, self.my_prefix = self.my_prefix, None
        if self.on_withdraw is not None:
            self.on_withdraw(prefix)

    def _sync_loopback(self, prefix: IpPrefix) -> None:
        """Assign the first host address of the prefix to loopback
        (PrefixAllocator.cpp:654-699)."""
        try:
            links = {l.name: l for l in self.system_handler.get_links()}
            lo = links.get(self.config.loopback_iface)
            if lo is None:
                return
            addr = str(next(prefix.network.hosts()))
            self.system_handler.add_addr(
                lo.ifindex, addr, prefix.prefix_length
            )
        except Exception:
            log.exception("failed to sync loopback address")

    # ------------------------------------------------------------------
    # persisted index
    # ------------------------------------------------------------------

    def _load_index(self) -> Optional[int]:
        if self.config_store is None:
            return None
        state = self.config_store.load_obj(CONFIG_STORE_KEY)
        if not isinstance(state, dict):
            return None
        # index only reusable under identical params
        if state.get("params") != (
            self.params.encode() if self.params else None
        ):
            return None
        return state.get("index")

    def _save_index(self, index: int) -> None:
        if self.config_store is None or self.params is None:
            return
        self.config_store.store_obj(
            CONFIG_STORE_KEY,
            {"params": self.params.encode(), "index": index},
        )
