"""Light intraprocedural forward dataflow: alias sets + escape tracking.

The ROADMAP's analysis-depth gap in one example: `d = self.x; d[k] = v`
mutates decision-loop-owned state, and the attribute-rooted mutation walk
cannot see it. This module is the general fix — a statement-ordered
forward pass over one function body that tracks, per local name, a set of
*tagged aliases*:

    ('attr', 'x')     may alias self.x (or an object reachable from it)
    ('device', desc)  flows out of a jit dispatch (a device-resident array)
    ('jit', name)     holds a compiled callable (a solver-factory result)

Transfer rules (deliberately simple — precision over recall, like every
rule in this suite):

  - `d = self.x` / `d = self.x[k]` / `d = self.x.y` bind ('attr', 'x');
    plain Name/Attribute/Subscript loads propagate tags, *calls break
    aliasing* (`d = self.x.copy()` is a fresh value) except when the
    callee is classified by the `classify_call` hook (device producers).
  - tuple-unpacking from a classified call tags every target (a solve's
    unpacked outputs are all device-resident until proven otherwise).
  - rebinding a name replaces its tags (kill on assignment); branches are
    processed in order with no joins — facts accumulate per line, which
    is exactly what a linter needs to point at the binding statement.

The pass reports three event streams, each carrying the alias *chain*
(the binding statements that created the alias) so findings read like the
bug: "self.x aliased as 'd' (line 12), mutated via d[k] = ... (line 14)".

  - mutations():  subscript/attr stores, aug-assigns, `del`, and mutating
    container-method calls on attr-tagged names (plus direct self.x forms)
  - escapes():    attr-tagged values passed to thread/executor/callback
    handoff sinks, queue puts, or returned
  - syncs():      host-sync expressions over device-tagged values
    (np.asarray/np.array, .item()/.tolist(), float(), `for _ in d`)

Two producer-flow evidence streams feed interprocedural fixpoints
(device-transfer's attribute/method producers): `attr_stores` records
every plain `self.<attr> = value` store with the value's tags, and
`returns` records every `return value`'s tags; `classify_attr` closes
the loop by tagging later `self.<attr>` loads.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Callable, Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from openr_tpu.analysis.core import call_name, dotted_name

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

_MUTATOR_METHODS = {
    "append",
    "extend",
    "insert",
    "add",
    "update",
    "setdefault",
    "pop",
    "popitem",
    "remove",
    "discard",
    "clear",
}

# call shapes that hand a value to another execution context: threads,
# executors, loop callbacks scheduled from other threads, queue puts
_HANDOFF_CALLS = {
    "Thread": "a thread target",
    "submit": "an executor",
    "run_in_executor": "an executor",
    "call_soon_threadsafe": "a cross-thread loop callback",
    "put": "a queue",
    "put_nowait": "a queue",
}

# numpy module aliases that force a host copy of their array argument
_NP_SYNC_CALLS = {"asarray", "array"}
_SYNC_METHODS = {"item", "tolist"}


Tag = Tuple[str, str]  # (kind, detail)


@dataclass(frozen=True)
class Alias:
    tag: Tag
    chain: Tuple[str, ...]  # binding descriptions, outermost first

    def extended(self, step: str) -> "Alias":
        return Alias(self.tag, self.chain + (step,))


@dataclass
class Mutation:
    line: int
    alias: Alias  # ('attr', name) tagged — the owned state mutated
    desc: str  # e.g. "d[...] = ..." / "d.update(...)"
    direct: bool  # True for self.x forms, False for alias-mediated


@dataclass
class Escape:
    line: int
    alias: Alias
    sink: str  # human description of where it escaped to


@dataclass
class HostSync:
    line: int
    alias: Alias  # ('device', desc) tagged
    desc: str  # e.g. "np.asarray(d)" / "d.item()" / "iteration over d"


def self_attr_root(node: ast.AST) -> Optional[str]:
    """First attribute name of a self-rooted load/store chain:
    self.x[...] -> 'x', self.a.b -> 'a'; None otherwise."""
    chain: List[str] = []
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute):
            chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and chain:
        return chain[-1]
    return None


class AliasTracker:
    """One pass over one function body (nested defs are NOT entered: they
    are separate functions analyzed in their own right)."""

    def __init__(
        self,
        fn,
        classify_call: Optional[Callable[[ast.Call], Optional[Tag]]] = None,
        np_aliases: Optional[Set[str]] = None,
        track_self_attrs: bool = True,
        classify_attr: Optional[Callable[[str], Optional[Tag]]] = None,
    ):
        self.fn = fn
        self.classify_call = classify_call or (lambda call: None)
        # per-attribute tagging hook: `self.<attr>` loads gain the
        # returned tag (the device-transfer rule's attribute-held
        # producers — `self._d_dev`-style resident arrays)
        self.classify_attr = classify_attr or (lambda attr: None)
        self.np_aliases = np_aliases or set()
        self.track_self_attrs = track_self_attrs
        self.state: Dict[str, Set[Alias]] = {}
        self.mutations: List[Mutation] = []
        self.escapes: List[Escape] = []
        self.syncs: List[HostSync] = []
        # producer-flow evidence for interprocedural rules:
        # (line, attr, value tags) for every plain `self.<attr> = value`
        # store, and (line, tags) for every `return value` — the
        # device-transfer rule's per-class fixpoint reads both to learn
        # which attributes/methods carry device arrays
        self.attr_stores: List[Tuple[int, str, Set[Alias]]] = []
        self.returns: List[Tuple[int, Set[Alias]]] = []
        self._ran = False

    # -- public ----------------------------------------------------------

    def run(self) -> "AliasTracker":
        if not self._ran:
            self._ran = True
            # parameters of the function are opaque (no tags): interproc
            # parameter flow is each rule's business, not the tracker's
            self._exec_block(self.fn.body)
        return self

    # -- expression tagging ----------------------------------------------

    def tags_of(self, node: ast.AST) -> Set[Alias]:
        if isinstance(node, ast.Name):
            return set(self.state.get(node.id, ()))
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            attr = self_attr_root(node)
            if attr is not None:
                out: Set[Alias] = set()
                if self.track_self_attrs:
                    out.add(Alias(("attr", attr), ()))
                extra = self.classify_attr(attr)
                if extra is not None:
                    # attribute-held producer: self._d_dev and loads off
                    # it (self._d_dev[i]) carry the producer tag
                    out.add(Alias(extra, (f"self.{attr}",)))
                if out:
                    return out
            # a load off a tagged root stays tagged: d[0] of a device d is
            # a device scalar; self.x's element is still owned state
            root = node
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            return self.tags_of(root) if isinstance(root, ast.Name) else set()
        if isinstance(node, ast.Call):
            tag = self.classify_call(node)
            if tag is not None:
                return {Alias(tag, ())}
            # a call on a jit-callable local produces a device value:
            # fn = _sell_solver(key); d = fn(rows, ...)
            if isinstance(node.func, ast.Name):
                for alias in self.state.get(node.func.id, ()):
                    if alias.tag[0] == "jit":
                        return {
                            Alias(
                                ("device", f"{node.func.id}(...)"),
                                alias.chain,
                            )
                        }
            return set()  # calls break aliasing
        if isinstance(node, ast.IfExp):
            return self.tags_of(node.body) | self.tags_of(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            out: Set[Alias] = set()
            for e in node.elts:
                out |= self.tags_of(e)
            return out
        if isinstance(node, ast.Starred):
            return self.tags_of(node.value)
        return set()

    # -- statement execution ---------------------------------------------

    def _exec_block(self, body: Iterable[ast.AST]) -> None:
        for stmt in body:
            self._exec_stmt(stmt)

    def _exec_stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, _FuncDef) or isinstance(stmt, ast.ClassDef):
            return  # nested scopes are separate analyses
        if isinstance(stmt, ast.Assign):
            self._scan_expr(stmt.value)
            for target in stmt.targets:
                self._assign(target, stmt.value, stmt.lineno)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._scan_expr(stmt.value)
                self._assign(stmt.target, stmt.value, stmt.lineno)
            return
        if isinstance(stmt, ast.AugAssign):
            self._scan_expr(stmt.value)
            self._store_mutation(stmt.target, stmt.lineno, aug=True)
            return
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Subscript):
                    self._store_mutation(t, stmt.lineno, deleted=True)
                else:
                    attr = self_attr_root(t)
                    if attr is not None and self.track_self_attrs:
                        self.mutations.append(
                            Mutation(
                                stmt.lineno,
                                Alias(("attr", attr), ()),
                                f"del self.{attr}",
                                direct=True,
                            )
                        )
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_expr(stmt.value)
                tags = self.tags_of(stmt.value)
                self.returns.append((stmt.lineno, tags))
                for alias in tags:
                    if alias.tag[0] == "attr":
                        self.escapes.append(
                            Escape(stmt.lineno, alias, "the return value")
                        )
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_expr(stmt.iter)
            for alias in self.tags_of(stmt.iter):
                if alias.tag[0] == "device":
                    self.syncs.append(
                        HostSync(
                            stmt.lineno,
                            alias,
                            "Python iteration over a device array",
                        )
                    )
            # loop variable inherits element tags (device scalar / owned
            # element)
            if isinstance(stmt.target, ast.Name):
                self.state[stmt.target.id] = {
                    a.extended(
                        f"iterated as '{stmt.target.id}' "
                        f"(line {stmt.lineno})"
                    )
                    for a in self.tags_of(stmt.iter)
                }
            self._exec_block(stmt.body)
            self._exec_block(stmt.orelse)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._scan_expr(stmt.test)
            self._exec_block(stmt.body)
            self._exec_block(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_expr(item.context_expr)
            self._exec_block(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self._exec_block(stmt.body)
            for handler in stmt.handlers:
                self._exec_block(handler.body)
            self._exec_block(stmt.orelse)
            self._exec_block(stmt.finalbody)
            return
        if isinstance(stmt, ast.Expr):
            self._scan_expr(stmt.value)
            return
        # fall through (pass/raise/assert/global/...): scan for calls
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self._scan_call(node)

    def _assign(self, target: ast.AST, value: ast.AST, line: int) -> None:
        if isinstance(target, ast.Name):
            tags = self.tags_of(value)
            src = _expr_desc(value)
            self.state[target.id] = {
                a.extended(f"{target.id} = {src} (line {line})")
                for a in tags
            }
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value, (ast.Tuple, ast.List)) and len(
                value.elts
            ) == len(target.elts):
                for t, v in zip(target.elts, value.elts):
                    self._assign(t, v, line)
                return
            # unpacking one producer call: every target inherits its tags
            tags = self.tags_of(value)
            src = _expr_desc(value)
            for t in target.elts:
                if isinstance(t, ast.Name):
                    self.state[t.id] = {
                        a.extended(f"{t.id} unpacked from {src} (line {line})")
                        for a in tags
                    }
                elif isinstance(t, (ast.Attribute, ast.Subscript)):
                    self._record_attr_store(t, tags, line)
                    self._store_mutation(t, line)
            return
        if isinstance(target, (ast.Attribute, ast.Subscript)):
            self._record_attr_store(target, self.tags_of(value), line)
            self._store_mutation(target, line)

    def _record_attr_store(
        self, target: ast.AST, tags: Set[Alias], line: int
    ) -> None:
        """Plain `self.<attr> = value` stores (no subscripts, no deeper
        chains) feed the producer-flow evidence: the device-transfer
        rule learns attribute-held device arrays from these."""
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            self.attr_stores.append((line, target.attr, tags))

    def _store_mutation(
        self, target: ast.AST, line: int, aug: bool = False,
        deleted: bool = False,
    ) -> None:
        """A store through an attribute/subscript: direct self.x forms and
        stores through attr-tagged aliases are owned-state mutations."""
        op = "del " if deleted else ""
        if self.track_self_attrs:
            attr = self_attr_root(target)
            if attr is not None:
                self.mutations.append(
                    Mutation(
                        line,
                        Alias(("attr", attr), ()),
                        f"{op}self.{attr}{'[...]' if _subscripted(target) else ''}"
                        + (" (aug-assign)" if aug else " = ..." if not deleted else ""),
                        direct=True,
                    )
                )
                return
        root = target
        while isinstance(root, (ast.Attribute, ast.Subscript)):
            root = root.value
        if isinstance(root, ast.Name):
            desc = _expr_desc(target)
            for alias in self.state.get(root.id, ()):
                if alias.tag[0] == "attr":
                    self.mutations.append(
                        Mutation(
                            line,
                            alias,
                            f"{op}{desc}" + ("" if deleted else " = ..."),
                            direct=False,
                        )
                    )
        elif isinstance(root, ast.Name) and aug:
            pass  # plain `x += 1` on an untagged local: not a mutation

    def _scan_expr(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._scan_call(sub)

    def _scan_call(self, call: ast.Call) -> None:
        name = call_name(call)
        # mutating container-method calls on tagged receivers
        if (
            isinstance(call.func, ast.Attribute)
            and name in _MUTATOR_METHODS
            and isinstance(call.func.value, ast.Name)
        ):
            recv = call.func.value.id
            for alias in self.state.get(recv, ()):
                if alias.tag[0] == "attr":
                    self.mutations.append(
                        Mutation(
                            call.lineno,
                            alias,
                            f"{recv}.{name}(...)",
                            direct=False,
                        )
                    )
        # host syncs on device-tagged values
        if name in _SYNC_METHODS and isinstance(call.func, ast.Attribute):
            for alias in self.tags_of(call.func.value):
                if alias.tag[0] == "device":
                    self.syncs.append(
                        HostSync(
                            call.lineno,
                            alias,
                            f"{_expr_desc(call.func.value)}.{name}()",
                        )
                    )
        if isinstance(call.func, ast.Name) and name == "float" and call.args:
            for alias in self.tags_of(call.args[0]):
                if alias.tag[0] == "device":
                    self.syncs.append(
                        HostSync(
                            call.lineno,
                            alias,
                            f"float({_expr_desc(call.args[0])})",
                        )
                    )
        if (
            isinstance(call.func, ast.Attribute)
            and name in _NP_SYNC_CALLS
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id in self.np_aliases
            and call.args
        ):
            for alias in self.tags_of(call.args[0]):
                if alias.tag[0] == "device":
                    self.syncs.append(
                        HostSync(
                            call.lineno,
                            alias,
                            f"{call.func.value.id}.{name}"
                            f"({_expr_desc(call.args[0])})",
                        )
                    )
        # escapes: attr-tagged values handed to another execution context
        if name in _HANDOFF_CALLS:
            sink = _HANDOFF_CALLS[name]
            operands = list(call.args) + [kw.value for kw in call.keywords]
            for operand in operands:
                for alias in self.tags_of(operand):
                    if alias.tag[0] == "attr":
                        self.escapes.append(
                            Escape(call.lineno, alias, sink)
                        )


def _subscripted(node: ast.AST) -> bool:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Subscript):
            return True
        node = node.value
    return False


def _expr_desc(node: ast.AST, depth: int = 0) -> str:
    """Short source-ish rendering for finding messages."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return f"{_expr_desc(node.value, depth + 1)}.{node.attr}"
    if isinstance(node, ast.Subscript):
        return f"{_expr_desc(node.value, depth + 1)}[...]"
    if isinstance(node, ast.Call):
        base = _expr_desc(node.func, depth + 1)
        return f"{base}(...)"
    if isinstance(node, (ast.Tuple, ast.List)):
        return "(...)"
    if isinstance(node, ast.Constant):
        return repr(node.value)
    return "<expr>"


# public alias: shapeflow builds its finding messages with the same
# renderer so sentinel/dtype findings read like the dataflow ones
expr_desc = _expr_desc


def alias_chain_text(alias: Alias) -> str:
    """'self.x via d = self.x (line 12)' rendering for finding messages."""
    base = (
        f"self.{alias.tag[1]}"
        if alias.tag[0] == "attr"
        else alias.tag[1] or alias.tag[0]
    )
    if not alias.chain:
        return base
    return f"{base} via " + " -> ".join(alias.chain)
