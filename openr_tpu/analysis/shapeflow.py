"""ShapeFlow: abstract shape/dtype/sentinel interpretation of traced kernels.

The trace-safety rule knows WHICH functions are jit-reachable; nothing so
far checks WHAT flows through them. This module is the missing layer: an
abstract interpreter that walks every traced (and every @shape_contract-
annotated) function propagating three fact domains per value —

  symbolic shapes   dims named from function params, contract symbols, and
                    module constants (``s_pad``, ``n_pad``, ``B``, ``h``);
                    unified with a union-find (DimEnv), so ``[B, B]`` from
                    one operand and ``[128, B]`` from another either agree
                    or produce a finding;
  dtypes            contract-declared or literal-derived, with weak-type
                    modeling (a Python ``2`` does not promote ``int32``;
                    a Python ``1.5`` does);
  sentinel lattice  where a value sits relative to the repo's int32
                    infinity (``INF = 1 << 29``, float analog ``F_INF``):
                    ``lt-inf`` < INF, ``eq-inf`` == INF, ``maybe-inf``
                    <= INF, and the overflow band ``>= 2*INF`` reached by
                    adding two maybe-INF values. ``jnp.minimum(x, INF)``
                    (and ``clip`` / scatter ``.at[..].min``) is the clamp
                    that returns a value to ``maybe-inf``.

Seeding: annotated functions (utils/shape_contract.py) seed from their
declared specs and are verified against them; unannotated traced functions
get inferred summaries (which params live in the sentinel domain, learned
from INF co-occurrence) cached per file-sha in the persistent analysis
cache (analysis/cache.py) and invalidated when any contract changes —
contracts are summary inputs. Cross-module calls resolve on the DeepFlow
call graph, instantiating the callee's contract with fresh dims.

Four rule families ride on one shared interpretation pass (cached on the
AnalysisContext, so the first family pays the cost and the other three
read it):

  shape-mismatch         provable broadcast/rank conflicts, contract
                         violations at call/return seams, tile splits
                         ``a // b`` without a divisibility guard, and
                         frontier buckets that forget to reserve the
                         padding slot (the GraphTiling ``h - 1`` layout)
  sentinel-overflow      int32 addition of two maybe-INF values with no
                         dominating clamp — the (min,+) kernel hazard
                         class; also collective sums of sentinel operands
  dtype-promotion        silent int->float promotion, bool masks used in
                         arithmetic without an explicit cast, true
                         division of ints, float64 inside traced code
  collective-conformance lax.ppermute/psum/pmax axis names checked against
                         the mesh axis vocabulary, ppermute permutation
                         well-formedness

Like every rule in this suite: precision over recall. Unknown shapes and
unresolved calls stay silent; findings require proof from the facts at
hand (docs/Analysis.md has the full semantics).
"""

from __future__ import annotations

import ast
import hashlib
import time
from dataclasses import dataclass, field, replace
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from openr_tpu.analysis.core import (
    ANALYSIS_VERSION,
    AnalysisContext,
    Rule,
    SourceFile,
    call_name,
    dotted_name,
    register,
    walk_nodes,
)
from openr_tpu.analysis.callgraph import (
    CallGraph,
    FunctionInfo,
    build_callgraph,
    scan_imports,
)
from openr_tpu.analysis.dataflow import expr_desc
from openr_tpu.analysis.shard_spec import _const_strs, mesh_axis_vocabulary
from openr_tpu.analysis.trace_safety import _walk_shallow, traced_function_infos
from openr_tpu.utils.shape_contract import (
    ArraySpec,
    Contract,
    ContractError,
    parse_contract,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

# most recent pass stats in this process: contract/function counts + wall
# time, surfaced through get_analysis_info -> get_build_info -> ctrl
# getBuildInfo / `breeze openr version` (next to the per-rule stats)
LAST_SHAPEFLOW_STATS: Dict = {}

FAM_SHAPE = "shape-mismatch"
FAM_SENT = "sentinel-overflow"
FAM_DTYPE = "dtype-promotion"
FAM_COLL = "collective-conformance"

# --------------------------------------------------------------------------
# sentinel lattice
# --------------------------------------------------------------------------

INF_VALUE = 1 << 29

S_NON = "none"  # not in the sentinel domain / unknown
S_LT = "lt-inf"  # provably < INF (literals, indices, counters)
S_EQ = "eq-inf"  # exactly the sentinel
S_MAYBE = "maybe-inf"  # <= INF: the clamped steady state
S_SUM = "2inf"  # may reach >= 2*INF: must be clamped before use

_SENT_ORDER = {S_LT: 0, S_EQ: 1, S_MAYBE: 2, S_SUM: 3}


def sent_join(a: str, b: str) -> str:
    """Least upper bound (jnp.where branches, maximum)."""
    if S_SUM in (a, b):
        return S_SUM
    if a == S_NON and b == S_NON:
        return S_NON
    if a == b:
        return a
    if S_NON in (a, b):
        # unknown joined with a sentinel state: stay in the domain but at
        # the conservative <=INF bound (a where(c, x, INF) marks x's
        # domain even when x itself is opaque)
        other = b if a == S_NON else a
        return other if other in (S_MAYBE, S_SUM) else S_MAYBE
    return S_MAYBE


def sent_min(a: str, b: str) -> str:
    """State of jnp.minimum(a, b): the elementwise lower bound."""
    if S_NON in (a, b):
        return S_NON
    return a if _SENT_ORDER[a] <= _SENT_ORDER[b] else b


# --------------------------------------------------------------------------
# symbolic dims
# --------------------------------------------------------------------------


class DimEnv:
    """Union-find over symbolic dimension names with optional concrete
    values — the unification engine behind shape checks. Dims are ints,
    strings (symbols), or None (unknown/wildcard)."""

    def __init__(self, consts: Optional[Dict[str, int]] = None):
        self._parent: Dict[str, str] = {}
        self._value: Dict[str, int] = dict(consts or {})

    def _find(self, s: str) -> str:
        root = s
        while self._parent.get(root, root) != root:
            root = self._parent[root]
        while self._parent.get(s, s) != s:
            self._parent[s], s = root, self._parent[s]
        return root

    def concrete(self, d) -> Optional[int]:
        if isinstance(d, int):
            return d
        if isinstance(d, str):
            return self._value.get(self._find(d))
        return None

    def bind(self, s: str, v: int) -> bool:
        root = self._find(s)
        cur = self._value.get(root)
        if cur is None:
            self._value[root] = v
            return True
        return cur == v

    def unify(self, a, b) -> bool:
        """Exact unification (contract seams): merge symbol classes, bind
        values; False only on a provable concrete conflict."""
        if a is None or b is None:
            return True
        if isinstance(a, int) and isinstance(b, int):
            return a == b
        if isinstance(a, int):
            return self.bind(b, a)
        if isinstance(b, int):
            return self.bind(a, b)
        ra, rb = self._find(a), self._find(b)
        if ra == rb:
            return True
        va, vb = self._value.get(ra), self._value.get(rb)
        if va is not None and vb is not None and va != vb:
            return False
        self._parent[ra] = rb
        if va is not None:
            self._value[rb] = va
        return True

    def broadcast_pair(self, a, b) -> Tuple[object, bool]:
        """(result dim, ok) under numpy broadcasting: 1 yields to the
        other side; symbols are NOT merged (either could be 1 at runtime)
        — only concrete unequal non-1 pairs conflict."""
        if a is None:
            return b, True
        if b is None:
            return a, True
        va, vb = self.concrete(a), self.concrete(b)
        if va == 1:
            return b, True
        if vb == 1:
            return a, True
        if va is not None and vb is not None:
            return a, va == vb
        # prefer the side with a concrete value for the result dim
        return (a if va is not None else b), True


def _dim_text(env: DimEnv, d) -> str:
    if d is None:
        return "?"
    v = env.concrete(d)
    if isinstance(d, str) and v is not None:
        return f"{d}={v}"
    return str(d)


def _shape_text(env: DimEnv, shape) -> str:
    if shape is None:
        return "[?]"
    return "[" + ",".join(_dim_text(env, d) for d in shape) + "]"


# --------------------------------------------------------------------------
# abstract values
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class AbsVal:
    """One value's abstract facts. shape is a tuple of dims (int | symbol
    str | None) or None when the rank itself is unknown. open_sites carry
    the AST ids of undischarged >=2*INF additions flowing through this
    value — a clamp discharges them, function end flags the rest."""

    shape: Optional[Tuple] = None
    dtype: Optional[str] = None
    weak: bool = False
    sent: str = S_NON
    open_sites: FrozenSet[int] = frozenset()


_UNKNOWN = AbsVal()


def _kind(dtype: Optional[str]) -> Optional[str]:
    if dtype is None:
        return None
    if dtype == "bool":
        return "b"
    if dtype.startswith(("int", "uint")):
        return "i"
    if dtype.startswith(("float", "bfloat")):
        return "f"
    return None


def _promote(l: AbsVal, r: AbsVal) -> Tuple[Optional[str], bool]:
    """(dtype, weak) of a binary op result under jax promotion: known
    beats weak, float beats int beats bool."""
    lk, rk = _kind(l.dtype), _kind(r.dtype)
    if lk is None and rk is None:
        return None, False
    if lk is None:
        return r.dtype, r.weak
    if rk is None:
        return l.dtype, l.weak
    rankof = {"b": 0, "i": 1, "f": 2}
    if rankof[lk] != rankof[rk]:
        hi = l if rankof[lk] > rankof[rk] else r
        lo = r if hi is l else l
        if hi.weak and not lo.weak:
            # weak scalar yields its kind's default width but the array
            # side decides nothing narrower exists: int32 + 1.5 -> float32
            return ("float32" if _kind(hi.dtype) == "f" else hi.dtype), False
        return hi.dtype, hi.weak and lo.weak
    # same kind: the non-weak side wins; equal weakness keeps the left
    if l.weak and not r.weak:
        return r.dtype, False
    return l.dtype, l.weak and r.weak


# --------------------------------------------------------------------------
# per-module view: aliases, constants, INF bindings
# --------------------------------------------------------------------------


@dataclass
class ModuleView:
    jnp: Set[str] = field(default_factory=set)  # names meaning jax.numpy
    lax: Set[str] = field(default_factory=set)  # names meaning jax.lax
    jaxm: Set[str] = field(default_factory=set)  # names meaning jax itself
    np: Set[str] = field(default_factory=set)  # names meaning numpy
    consts: Dict[str, int] = field(default_factory=dict)
    inf_names: Set[str] = field(default_factory=set)  # INF / F_INF bindings
    inf_dtypes: Dict[str, str] = field(default_factory=dict)
    jaxy: bool = False  # module touches jax at all

    @classmethod
    def scan(cls, sf: SourceFile) -> "ModuleView":
        mv = cls()
        from_imports, module_aliases = scan_imports(sf.tree)
        for alias, mod in module_aliases.items():
            if mod == "jax.numpy":
                mv.jnp.add(alias)
            elif mod == "jax.lax":
                mv.lax.add(alias)
            elif mod == "jax":
                mv.jaxm.add(alias)
            elif mod == "numpy":
                mv.np.add(alias)
        for alias, (mod, name) in from_imports.items():
            if mod == "jax" and name == "numpy":
                mv.jnp.add(alias)
            elif mod == "jax" and name == "lax":
                mv.lax.add(alias)
            elif name == "INF":
                mv.inf_names.add(alias)
                mv.inf_dtypes[alias] = "int32"
            elif name == "F_INF":
                mv.inf_names.add(alias)
                mv.inf_dtypes[alias] = "float32"
        for node in sf.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
            ):
                name = node.targets[0].id
                val = _int_const(node.value)
                if val is not None:
                    mv.consts[name] = val
                    if val >= INF_VALUE:
                        mv.inf_names.add(name)
                        mv.inf_dtypes[name] = "int32"
                fval = _float_const(node.value)
                if fval is not None and fval >= 1e8:
                    mv.inf_names.add(name)
                    mv.inf_dtypes[name] = "float32"
        mv.jaxy = bool(mv.jnp or mv.lax or mv.jaxm)
        return mv


def _int_const(node: ast.AST) -> Optional[int]:
    """Literal ints including the `1 << 29` sentinel spelling."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) and not isinstance(node.value, bool):
        return node.value
    if (
        isinstance(node, ast.BinOp)
        and isinstance(node.op, ast.LShift)
        and isinstance(node.left, ast.Constant)
        and isinstance(node.right, ast.Constant)
    ):
        try:
            return node.left.value << node.right.value
        except TypeError:
            return None
    return None


def _float_const(node: ast.AST) -> Optional[float]:
    if isinstance(node, ast.Constant) and isinstance(node.value, float):
        return node.value
    return None


_DTYPE_NAMES = {
    "bool", "bool_", "int8", "int16", "int32", "int64", "uint8",
    "uint16", "uint32", "uint64", "bfloat16", "float16", "float32",
    "float64",
}


def _dtype_of_node(node: Optional[ast.AST]) -> Optional[str]:
    """'float32' for jnp.float32 / np.int32 / 'int32' literals."""
    if node is None:
        return None
    if isinstance(node, ast.Attribute) and node.attr in _DTYPE_NAMES:
        return "bool" if node.attr == "bool_" else node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _DTYPE_NAMES else None
    return None


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


# --------------------------------------------------------------------------
# contracts from the AST (the analyzer never imports kernel modules)
# --------------------------------------------------------------------------


def contract_decorator(fn_node: ast.AST) -> Optional[ast.Call]:
    for dec in getattr(fn_node, "decorator_list", ()):
        if isinstance(dec, ast.Call):
            base = dotted_name(dec.func) or ""
            if base.split(".")[-1] == "shape_contract":
                return dec
    return None


def parse_contract_decorator(
    dec: ast.Call,
) -> Tuple[Optional[Contract], Optional[str]]:
    """(contract, error message): re-parses the runtime grammar from the
    decorator's literal strings; non-literal args disable the contract."""
    specs: List[str] = []
    for arg in dec.args:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            specs.append(arg.value)
        else:
            return None, None  # dynamically built contract: out of scope
    returns = None
    ret_node = _kwarg(dec, "returns")
    if ret_node is not None:
        if isinstance(ret_node, ast.Constant) and isinstance(
            ret_node.value, str
        ):
            returns = ret_node.value
        else:
            return None, None
    try:
        return parse_contract(tuple(specs), returns=returns), None
    except ContractError as exc:
        return None, str(exc)


# --------------------------------------------------------------------------
# the per-function interpreter
# --------------------------------------------------------------------------

# jnp reductions: (drops the `axis` dim from the shape, keeps sentinel
# state for min/max — the reduced value obeys the same bound)
_REDUCTIONS = {"min", "amin", "max", "amax"}
_SUM_REDUCTIONS = {"sum", "mean", "prod"}
_ELEMENTWISE_FLOAT = {"exp", "log", "sqrt", "tanh", "sigmoid", "softmax"}
_ELEMENTWISE_KEEP = {"abs", "negative", "stop_gradient"}

_AXIS_COLLECTIVES = {
    "ppermute", "psum", "pmax", "pmin", "pmean", "all_gather",
    "axis_index", "psum_scatter", "all_to_all",
}


class FnAnalysis:
    """One statement-ordered forward pass over one function body (nested
    defs are separate analyses, mirroring dataflow.AliasTracker)."""

    def __init__(
        self,
        flow: "_ShapeFlowPass",
        fi: FunctionInfo,
        contract: Optional[Contract],
        sentinel_params: Set[str],
    ):
        self.flow = flow
        self.fi = fi
        self.sf = fi.sf
        self.mv = flow.views[fi.sf.rel]
        self.mod = flow.cg.modules.get(fi.module)
        self.contract = contract
        self.env: Dict[str, AbsVal] = {}
        self.dims = DimEnv(self.mv.consts)
        # open >=2*INF additions: id(node) -> (line, description)
        self.open: Dict[int, Tuple[int, str]] = {}
        self._seed_params(sentinel_params)

    # -- seeding -----------------------------------------------------------

    def _seed_params(self, sentinel_params: Set[str]) -> None:
        args = self.fi.node.args
        names = [
            a.arg
            for a in (
                list(getattr(args, "posonlyargs", []) or [])
                + list(args.args)
                + list(args.kwonlyargs)
            )
        ]
        for name in names:
            spec = self.contract.params.get(name) if self.contract else None
            if spec is not None:
                self.env[name] = AbsVal(
                    shape=tuple(spec.dims),
                    dtype=spec.dtype,
                    sent=S_MAYBE if spec.inf else S_NON,
                )
            elif name in sentinel_params:
                self.env[name] = AbsVal(sent=S_MAYBE)
            else:
                self.env[name] = _UNKNOWN

    # -- findings ----------------------------------------------------------

    def emit(self, family: str, check: str, line: int, msg: str) -> None:
        self.flow.emit(family, check, self.sf, line, msg)

    # -- driver ------------------------------------------------------------

    def run(self) -> None:
        self._exec_block(self.fi.node.body)
        for line, desc in sorted(self.open.values()):
            self.emit(
                FAM_SENT,
                "unclamped-add",
                line,
                f"sentinel add without a dominating clamp: {desc} can "
                f"reach the >=2*INF band (INF = 1 << 29 stays int32-safe "
                f"only under jnp.minimum(..., INF))",
            )

    # -- statements --------------------------------------------------------

    def _exec_block(self, body: Iterable[ast.AST]) -> None:
        for stmt in body:
            self._exec_stmt(stmt)

    def _exec_stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, _FuncDef) or isinstance(stmt, ast.ClassDef):
            return  # nested scopes are separate analyses
        if isinstance(stmt, ast.Assign):
            val = self.eval(stmt.value)
            for target in stmt.targets:
                self._assign(target, val, stmt.value)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._assign(stmt.target, self.eval(stmt.value), stmt.value)
            return
        if isinstance(stmt, ast.AugAssign):
            cur = (
                self.env.get(stmt.target.id, _UNKNOWN)
                if isinstance(stmt.target, ast.Name)
                else _UNKNOWN
            )
            val = self._binop_val(stmt, cur, self.eval(stmt.value), stmt.op)
            if isinstance(stmt.target, ast.Name):
                self.env[stmt.target.id] = val
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                val = self.eval(stmt.value)
                self._check_return_contract(stmt, val)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.eval(stmt.iter)
            if isinstance(stmt.target, ast.Name):
                self.env[stmt.target.id] = AbsVal(
                    dtype="int32", weak=True, sent=S_LT
                )
            self._exec_block(stmt.body)
            self._exec_block(stmt.orelse)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self.eval(stmt.test)
            self._exec_block(stmt.body)
            self._exec_block(stmt.orelse)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.eval(item.context_expr)
            self._exec_block(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self._exec_block(stmt.body)
            for handler in stmt.handlers:
                self._exec_block(handler.body)
            self._exec_block(stmt.orelse)
            self._exec_block(stmt.finalbody)
            return
        if isinstance(stmt, (ast.Expr, ast.Assert)):
            self.eval(stmt.value if isinstance(stmt, ast.Expr) else stmt.test)
            return

    def _assign(self, target: ast.AST, val: AbsVal, value_node: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = val
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(value_node, (ast.Tuple, ast.List)) and len(
                value_node.elts
            ) == len(target.elts):
                for t, v in zip(target.elts, value_node.elts):
                    self._assign(t, self.eval(v), v)
                return
            for t in target.elts:
                if isinstance(t, ast.Name):
                    # unpacking an opaque producer: facts don't split, but
                    # open overflow sites must keep flowing
                    self.env[t.id] = AbsVal(open_sites=val.open_sites)

    def _check_return_contract(self, stmt: ast.Return, val: AbsVal) -> None:
        spec = self.contract.returns if self.contract else None
        if spec is None:
            return
        if val.shape is not None:
            if len(val.shape) != spec.rank:
                self.emit(
                    FAM_SHAPE,
                    "return-contract",
                    stmt.lineno,
                    f"return shape {_shape_text(self.dims, val.shape)} "
                    f"conflicts with declared returns "
                    f"{_shape_text(self.dims, tuple(spec.dims))} "
                    f"(rank {len(val.shape)} != {spec.rank})",
                )
                return
            for got, want in zip(val.shape, spec.dims):
                if not self.dims.unify(got, want):
                    self.emit(
                        FAM_SHAPE,
                        "return-contract",
                        stmt.lineno,
                        f"return dim {_dim_text(self.dims, got)} conflicts "
                        f"with declared {_dim_text(self.dims, want)} in "
                        f"returns {_shape_text(self.dims, tuple(spec.dims))}",
                    )
        if (
            val.dtype is not None
            and not val.weak
            and val.dtype != spec.dtype
        ):
            self.emit(
                FAM_SHAPE,
                "return-contract",
                stmt.lineno,
                f"return dtype {val.dtype} conflicts with declared "
                f"{spec.dtype}",
            )

    # -- expressions -------------------------------------------------------

    def eval(self, node: ast.AST) -> AbsVal:
        if isinstance(node, ast.Constant):
            return self._eval_const(node)
        if isinstance(node, ast.Name):
            if node.id in self.mv.inf_names:
                return AbsVal(
                    shape=(),
                    dtype=self.mv.inf_dtypes.get(node.id, "int32"),
                    sent=S_EQ,
                )
            if node.id in self.mv.consts:
                return AbsVal(
                    shape=(),
                    dtype="int32",
                    weak=True,
                    sent=S_LT
                    if self.mv.consts[node.id] < INF_VALUE
                    else S_EQ,
                )
            return self.env.get(node.id, _UNKNOWN)
        if isinstance(node, ast.BinOp):
            l, r = self.eval(node.left), self.eval(node.right)
            return self._binop_val(node, l, r, node.op)
        if isinstance(node, ast.UnaryOp):
            inner = self.eval(node.operand)
            if isinstance(node.op, ast.Not):
                return AbsVal(shape=inner.shape, dtype="bool")
            return replace(inner, sent=S_NON)
        if isinstance(node, ast.Compare):
            vals = [self.eval(node.left)] + [
                self.eval(c) for c in node.comparators
            ]
            shape = self._broadcast(node, vals)
            sites = frozenset().union(*(v.open_sites for v in vals))
            return AbsVal(shape=shape, dtype="bool", open_sites=sites)
        if isinstance(node, ast.BoolOp):
            for v in node.values:
                self.eval(v)
            return AbsVal(dtype="bool")
        if isinstance(node, ast.IfExp):
            self.eval(node.test)
            a, b = self.eval(node.body), self.eval(node.orelse)
            return AbsVal(
                shape=a.shape if a.shape == b.shape else None,
                dtype=a.dtype if a.dtype == b.dtype else None,
                weak=a.weak and b.weak,
                sent=sent_join(a.sent, b.sent),
                open_sites=a.open_sites | b.open_sites,
            )
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node)
        if isinstance(node, ast.Call):
            return self._eval_call(node)
        if isinstance(node, ast.Attribute):
            base = self.eval(node.value)
            if node.attr == "T" and base.shape is not None:
                return replace(base, shape=tuple(reversed(base.shape)))
            if node.attr in ("shape", "size", "ndim", "dtype"):
                return AbsVal(dtype="int32", weak=True, sent=S_LT)
            return _UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List)):
            sites = frozenset()
            for e in node.elts:
                sites |= self.eval(e).open_sites
            return AbsVal(open_sites=sites)
        if isinstance(node, ast.Starred):
            return self.eval(node.value)
        # comprehensions, lambdas, f-strings, ...: host-level, opaque
        return _UNKNOWN

    def _eval_const(self, node: ast.Constant) -> AbsVal:
        v = node.value
        if isinstance(v, bool):
            return AbsVal(shape=(), dtype="bool", weak=True)
        if isinstance(v, int):
            sent = S_LT if abs(v) < 2 ** 28 else (S_EQ if v == INF_VALUE else S_EQ)
            return AbsVal(shape=(), dtype="int32", weak=True, sent=sent)
        if isinstance(v, float):
            sent = S_EQ if v >= 1e8 else S_LT
            return AbsVal(shape=(), dtype="float32", weak=True, sent=sent)
        return _UNKNOWN

    # -- binops: broadcasting + promotion + the sentinel add ---------------

    def _binop_val(
        self, node: ast.AST, l: AbsVal, r: AbsVal, op: ast.AST
    ) -> AbsVal:
        shape = self._broadcast(node, [l, r])
        dtype, weak = _promote(l, r)
        sites = l.open_sites | r.open_sites
        sent = S_NON
        if isinstance(op, ast.Add):
            sent, sites = self._sentinel_add(node, l, r, sites)
        elif isinstance(op, (ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod)):
            sent = S_NON
        self._check_promotion(node, l, r, op)
        return AbsVal(
            shape=shape, dtype=dtype, weak=weak, sent=sent, open_sites=sites
        )

    def _sentinel_add(
        self,
        node: ast.AST,
        l: AbsVal,
        r: AbsVal,
        sites: FrozenSet[int],
    ) -> Tuple[str, FrozenSet[int]]:
        lk, rk = _kind(l.dtype), _kind(r.dtype)
        if "f" in (lk, rk):
            # the overflow band is an int32 hazard; float sentinel sums
            # (F_INF gaps) saturate harmlessly and are compared via
            # `>= F_INF / 2` guards instead
            return S_NON, sites
        hazard = (
            l.sent == S_SUM
            or r.sent == S_SUM
            or (l.sent in (S_EQ, S_MAYBE) and r.sent in (S_EQ, S_MAYBE))
        )
        if hazard:
            desc = f"{expr_desc(node.left)} + {expr_desc(node.right)}"
            self.open[id(node)] = (node.lineno, desc)
            return S_SUM, sites | {id(node)}
        if S_MAYBE in (l.sent, r.sent) or S_EQ in (l.sent, r.sent):
            return S_MAYBE, sites
        if l.sent == S_LT and r.sent == S_LT:
            return S_LT, sites
        return S_NON, sites

    def _discharge(self, *vals: AbsVal) -> None:
        for v in vals:
            for sid in v.open_sites:
                self.open.pop(sid, None)

    def _check_promotion(
        self, node: ast.AST, l: AbsVal, r: AbsVal, op: ast.AST
    ) -> None:
        lk, rk = _kind(l.dtype), _kind(r.dtype)
        line = getattr(node, "lineno", 0)
        if isinstance(op, ast.Div) and lk == "i" and not l.weak:
            self.emit(
                FAM_DTYPE,
                "int-true-div",
                line,
                f"true division of {l.dtype} value "
                f"{expr_desc(node.left)} promotes silently to floating "
                f"point; use // or an explicit astype",
            )
        if not isinstance(op, (ast.Add, ast.Sub, ast.Mult)):
            return
        # bool masks in arithmetic: inline comparisons or declared bools
        for side_node, side_val in (
            (getattr(node, "left", None), l),
            (getattr(node, "right", None), r),
        ):
            is_bool = (
                isinstance(side_node, ast.Compare)
                or (side_val.dtype == "bool" and not side_val.weak)
                or (
                    isinstance(side_node, ast.Subscript)
                    and side_val.dtype == "bool"
                )
            )
            if is_bool:
                self.emit(
                    FAM_DTYPE,
                    "bool-arith",
                    line,
                    f"bool mask {expr_desc(side_node)} promotes silently "
                    f"inside arithmetic; make the cast explicit with "
                    f".astype(...)",
                )
                return
        if {"i", "f"} == {lk, rk}:
            int_side = l if lk == "i" else r
            if not int_side.weak:
                int_node = node.left if int_side is l else node.right
                self.emit(
                    FAM_DTYPE,
                    "silent-promotion",
                    line,
                    f"{int_side.dtype} value {expr_desc(int_node)} "
                    f"promotes silently to floating point in this "
                    f"expression; cast explicitly with .astype(...)",
                )

    # -- broadcasting ------------------------------------------------------

    def _broadcast(
        self, node: ast.AST, vals: List[AbsVal]
    ) -> Optional[Tuple]:
        if any(v.shape is None for v in vals):
            return None  # an unknown operand defeats the check entirely
        shapes = [v.shape for v in vals if v.shape != ()]  # scalars free
        if not shapes:
            return ()
        if len(shapes) == 1:
            return shapes[0]
        maxr = max(len(s) for s in shapes)
        out: List[object] = []
        for pos in range(1, maxr + 1):
            dims = [s[-pos] for s in shapes if len(s) >= pos]
            d = dims[0]
            for other in dims[1:]:
                d, ok = self.dims.broadcast_pair(d, other)
                if not ok:
                    self.emit(
                        FAM_SHAPE,
                        "broadcast",
                        getattr(node, "lineno", 0),
                        f"operands cannot broadcast: dim "
                        f"{_dim_text(self.dims, dims[0])} vs "
                        f"{_dim_text(self.dims, other)} (axis -{pos}) in "
                        f"{expr_desc(node)}",
                    )
                    return None
            out.append(d)
        return tuple(reversed(out))

    # -- subscripts --------------------------------------------------------

    def _eval_subscript(self, node: ast.Subscript) -> AbsVal:
        base = self.eval(node.value)
        items = (
            list(node.slice.elts)
            if isinstance(node.slice, ast.Tuple)
            else [node.slice]
        )
        if base.shape is None:
            for it in items:
                if not isinstance(it, ast.Slice):
                    self.eval(it)
            return replace(base, shape=None)
        consuming = [
            it
            for it in items
            if not (
                isinstance(it, ast.Constant)
                and it.value is None
            )
        ]
        has_ellipsis = any(
            isinstance(it, ast.Constant) and it.value is Ellipsis
            for it in items
        )
        if not has_ellipsis and len(consuming) > len(base.shape):
            self.emit(
                FAM_SHAPE,
                "index-rank",
                node.lineno,
                f"{expr_desc(node)} indexes {len(consuming)} axes of a "
                f"rank-{len(base.shape)} value "
                f"{_shape_text(self.dims, base.shape)}",
            )
            return replace(base, shape=None)
        if has_ellipsis:
            return replace(base, shape=None)
        out: List[object] = []
        dim_iter = iter(base.shape)
        for it in items:
            if isinstance(it, ast.Constant) and it.value is None:
                out.append(1)
                continue
            src_dim = next(dim_iter, None)
            if isinstance(it, ast.Slice):
                if it.lower is None and it.upper is None and it.step is None:
                    out.append(src_dim)
                else:
                    out.append(None)  # partial slice: unknown length
                continue
            idx = self.eval(it)
            if isinstance(it, ast.Constant) and isinstance(it.value, int):
                continue  # integer index drops the dim
            if idx.shape is not None and idx.shape != ():
                out.extend(idx.shape)  # fancy index splices its dims
            elif idx.shape == ():
                continue
            else:
                out.append(None)
        out.extend(dim_iter)
        return replace(base, shape=tuple(out))

    # -- calls -------------------------------------------------------------

    def _api(self, call: ast.Call) -> Optional[Tuple[str, str]]:
        chain = dotted_name(call.func)
        if not chain or "." not in chain:
            return None
        parts = chain.split(".")
        if parts[0] in self.mv.jnp and len(parts) == 2:
            return "jnp", parts[1]
        if parts[0] in self.mv.lax and len(parts) == 2:
            return "lax", parts[1]
        if parts[0] in self.mv.np and len(parts) == 2:
            return "np", parts[1]
        if parts[0] in self.mv.jaxm and len(parts) >= 3:
            if parts[1] == "numpy":
                return "jnp", parts[2]
            if parts[1] == "lax":
                return "lax", parts[2]
        return None

    def _is_inf_node(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.mv.inf_names
        iv = _int_const(node)
        if iv is not None:
            return iv >= 2 ** 28
        fv = _float_const(node)
        if fv is not None:
            return fv >= 1e8
        chain = dotted_name(node)
        return bool(chain) and chain.split(".")[-1] == "INF"

    def _eval_call(self, call: ast.Call) -> AbsVal:
        api = self._api(call)
        if api is not None:
            mod, name = api
            if mod == "lax":
                return self._eval_lax(call, name)
            return self._eval_jnp(call, name)
        if isinstance(call.func, ast.Attribute):
            return self._eval_method(call)
        return self._eval_plain_call(call)

    def _eval_jnp(self, call: ast.Call, name: str) -> AbsVal:
        args = [self.eval(a) for a in call.args]
        if name in ("minimum", "fmin") and len(args) >= 2:
            clamp = any(self._is_inf_node(a) for a in call.args)
            if clamp:
                self._discharge(*args)
                shape = self._broadcast(call, args)
                dtype, weak = _promote(args[0], args[1])
                return AbsVal(shape=shape, dtype=dtype, weak=weak, sent=S_MAYBE)
            sent = sent_min(args[0].sent, args[1].sent)
            sites = args[0].open_sites | args[1].open_sites
            if sent != S_SUM:
                self._discharge(*args)
                sites = frozenset()
            shape = self._broadcast(call, args)
            dtype, weak = _promote(args[0], args[1])
            return AbsVal(
                shape=shape, dtype=dtype, weak=weak, sent=sent,
                open_sites=sites,
            )
        if name == "clip" and args:
            hi = call.args[2] if len(call.args) >= 3 else _kwarg(call, "max")
            if hi is not None and self._is_inf_node(hi):
                self._discharge(*args)
                return replace(args[0], sent=S_MAYBE, open_sites=frozenset())
            return args[0]
        if name in ("maximum", "fmax") and len(args) >= 2:
            shape = self._broadcast(call, args)
            dtype, weak = _promote(args[0], args[1])
            return AbsVal(
                shape=shape, dtype=dtype, weak=weak,
                sent=sent_join(args[0].sent, args[1].sent),
                open_sites=args[0].open_sites | args[1].open_sites,
            )
        if name == "where" and len(args) >= 3:
            shape = self._broadcast(call, args)
            a, b = args[1], args[2]
            inf_branch = any(
                self._is_inf_node(n) for n in call.args[1:3]
            )
            sent = sent_join(a.sent, b.sent)
            if inf_branch and sent == S_NON:
                sent = S_MAYBE
            dtype, weak = _promote(a, b)
            return AbsVal(
                shape=shape, dtype=dtype, weak=weak, sent=sent,
                open_sites=a.open_sites | b.open_sites,
            )
        if name in _REDUCTIONS and args:
            return self._reduce(call, args[0])
        if name in _SUM_REDUCTIONS and args:
            self._discharge(*args)
            v = self._reduce(call, args[0])
            return replace(v, sent=S_NON, open_sites=frozenset())
        if name in ("full",) and call.args:
            shape = self._dims_of_node(call.args[0])
            fill = args[1] if len(args) > 1 else _UNKNOWN
            dtype = _dtype_of_node(_kwarg(call, "dtype")) or fill.dtype
            return AbsVal(shape=shape, dtype=dtype, sent=fill.sent)
        if name == "full_like" and len(args) >= 2:
            dtype = _dtype_of_node(_kwarg(call, "dtype")) or args[0].dtype
            return AbsVal(shape=args[0].shape, dtype=dtype, sent=args[1].sent)
        if name in ("zeros", "ones", "empty") and call.args:
            shape = self._dims_of_node(call.args[0])
            dtype = _dtype_of_node(_kwarg(call, "dtype"))
            return AbsVal(shape=shape, dtype=dtype, sent=S_LT)
        if name in ("zeros_like", "ones_like") and args:
            return replace(
                args[0], sent=S_LT, open_sites=frozenset()
            )
        if name == "arange":
            dtype = _dtype_of_node(_kwarg(call, "dtype")) or "int32"
            return AbsVal(dtype=dtype, sent=S_LT)
        if name == "eye" and call.args:
            d = self._dim_of_node(call.args[0])
            dtype = _dtype_of_node(_kwarg(call, "dtype"))
            return AbsVal(shape=(d, d), dtype=dtype, sent=S_LT)
        if name == "reshape" and len(call.args) >= 2:
            shape = self._dims_of_node(call.args[1])
            return replace(args[0], shape=shape)
        if name == "transpose" and args:
            if args[0].shape is not None and len(call.args) == 1:
                return replace(args[0], shape=tuple(reversed(args[0].shape)))
            return replace(args[0], shape=None)
        if name in ("argsort", "argmin", "argmax") and args:
            return AbsVal(dtype="int32", sent=S_LT)
        if name in _ELEMENTWISE_FLOAT and args:
            return AbsVal(shape=args[0].shape, dtype="float32")
        if name in _ELEMENTWISE_KEEP and args:
            return args[0]
        if name in ("asarray", "array") and args:
            dtype = _dtype_of_node(_kwarg(call, "dtype")) or (
                _dtype_of_node(call.args[1]) if len(call.args) > 1 else None
            )
            return replace(args[0], dtype=dtype or args[0].dtype)
        # unmodeled jnp call: opaque, but overflow sites passed in cannot
        # be proven clamped OR unclamped — precision over recall, drop them
        self._discharge(*args)
        for kw in call.keywords:
            self._discharge(self.eval(kw.value))
        return _UNKNOWN

    def _reduce(self, call: ast.Call, v: AbsVal) -> AbsVal:
        axis_node = (
            call.args[1] if len(call.args) > 1 else _kwarg(call, "axis")
        )
        if _kwarg(call, "keepdims") is not None:
            return replace(v, shape=None)
        if axis_node is None:
            return replace(v, shape=())
        if (
            v.shape is not None
            and isinstance(axis_node, ast.Constant)
            and isinstance(axis_node.value, int)
        ):
            ax = axis_node.value
            if -len(v.shape) <= ax < len(v.shape):
                shape = list(v.shape)
                del shape[ax]
                return replace(v, shape=tuple(shape))
        return replace(v, shape=None)

    def _dim_of_node(self, node: ast.AST):
        iv = _int_const(node)
        if iv is not None:
            return iv
        if isinstance(node, ast.Name):
            return node.id
        return None

    def _dims_of_node(self, node: ast.AST) -> Optional[Tuple]:
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(self._dim_of_node(e) for e in node.elts)
        d = self._dim_of_node(node)
        return (d,) if d is not None else None

    # -- lax + collectives -------------------------------------------------

    def _eval_lax(self, call: ast.Call, name: str) -> AbsVal:
        args = [self.eval(a) for a in call.args]
        if name in _AXIS_COLLECTIVES:
            self.flow.check_collective(self, call, name)
        if name == "ppermute" and args:
            return args[0]
        if name in ("pmax", "pmin") and args:
            return args[0]
        if name == "psum" and args:
            if args[0].sent in (S_EQ, S_MAYBE, S_SUM) and _kind(
                args[0].dtype
            ) != "f":
                self.emit(
                    FAM_SENT,
                    "psum-sentinel",
                    call.lineno,
                    f"lax.psum over a sentinel-domain operand "
                    f"{expr_desc(call.args[0])}: the cross-device sum can "
                    f"leave the INF band; reduce with pmin/pmax or clamp "
                    f"first",
                )
            return replace(args[0], sent=S_NON, open_sites=frozenset())
        if name == "axis_index":
            return AbsVal(shape=(), dtype="int32", sent=S_LT)
        if name == "select" and len(args) >= 3:
            return AbsVal(
                shape=self._broadcast(call, args[1:]),
                sent=sent_join(args[1].sent, args[2].sent),
                open_sites=args[1].open_sites | args[2].open_sites,
            )
        if name in ("dynamic_slice", "dynamic_index_in_dim") and args:
            return replace(args[0], shape=None)
        if name == "stop_gradient" and args:
            return args[0]
        self._discharge(*args)
        return _UNKNOWN

    # -- methods -----------------------------------------------------------

    def _eval_method(self, call: ast.Call) -> AbsVal:
        func = call.func
        name = func.attr
        # scatter through .at[idx].min/set/add(v)
        if (
            isinstance(func.value, ast.Subscript)
            and isinstance(func.value.value, ast.Attribute)
            and func.value.value.attr == "at"
        ):
            base = self.eval(func.value.value.value)
            vals = [self.eval(a) for a in call.args]
            if name == "min":
                # scatter-min against the base: result stays below the
                # base's bound — the clamp idiom of the halo exchange
                self._discharge(*vals)
                return base
            if name in ("set", "max", "add"):
                sites = base.open_sites
                for v in vals:
                    sites |= v.open_sites
                return replace(
                    base,
                    sent=sent_join(
                        base.sent, vals[0].sent if vals else S_NON
                    ),
                    open_sites=sites,
                )
            return base
        base = self.eval(func.value)
        if name == "astype" and call.args:
            dtype = _dtype_of_node(call.args[0])
            return replace(base, dtype=dtype, weak=False)
        if name == "reshape":
            if len(call.args) == 1:
                return replace(base, shape=self._dims_of_node(call.args[0]))
            return replace(
                base,
                shape=tuple(self._dim_of_node(a) for a in call.args),
            )
        if name == "transpose":
            if base.shape is not None and len(call.args) == len(base.shape):
                perm = [_int_const(a) for a in call.args]
                if all(p is not None for p in perm):
                    return replace(
                        base, shape=tuple(base.shape[p] for p in perm)
                    )
            if not call.args and base.shape is not None:
                return replace(base, shape=tuple(reversed(base.shape)))
            return replace(base, shape=None)
        if name in _REDUCTIONS:
            return self._reduce(call, base)
        if name in _SUM_REDUCTIONS or name in ("any", "all"):
            self._discharge(base)
            return AbsVal(
                dtype="bool" if name in ("any", "all") else base.dtype
            )
        args = [self.eval(a) for a in call.args]
        self._discharge(base, *args)
        return _UNKNOWN

    # -- resolved calls: contract verification at the seam -----------------

    def _eval_plain_call(self, call: ast.Call) -> AbsVal:
        args = [self.eval(a) for a in call.args]
        kwargs = {
            kw.arg: self.eval(kw.value)
            for kw in call.keywords
            if kw.arg is not None
        }
        callee = None
        if self.mod is not None:
            for cand in self.flow.cg.resolve_call_defs(self.mod, call):
                if cand is not None and id(cand.node) in self.flow.contracts:
                    callee = cand
                    break
        if callee is None:
            self._discharge(*args, *kwargs.values())
            return _UNKNOWN
        contract = self.flow.contracts[id(callee.node)]
        self.flow.calls_checked += 1
        callee_mv = self.flow.views.get(callee.sf.rel)
        rename = f"{callee.name}@{call.lineno}"

        def fresh(dim):
            if isinstance(dim, str):
                sym = f"{rename}:{dim}"
                cv = (callee_mv.consts.get(dim) if callee_mv else None)
                if cv is not None:
                    self.dims.bind(sym, cv)
                return sym
            return dim

        params = [a.arg for a in callee.node.args.args]
        bound = dict(zip(params, args))
        bound.update({k: v for k, v in kwargs.items() if k in contract.params})
        for pname, spec in contract.params.items():
            got = bound.get(pname)
            if got is None or got.shape is None:
                continue
            want = tuple(fresh(d) for d in spec.dims)
            if len(got.shape) != len(want):
                self.emit(
                    FAM_SHAPE,
                    "call-contract",
                    call.lineno,
                    f"argument {pname!r} of {callee.name} has shape "
                    f"{_shape_text(self.dims, got.shape)} but the "
                    f"contract declares "
                    f"{_shape_text(self.dims, tuple(spec.dims))} "
                    f"(rank {len(got.shape)} != {spec.rank})",
                )
                continue
            for g, w in zip(got.shape, want):
                if not self.dims.unify(g, w):
                    self.emit(
                        FAM_SHAPE,
                        "call-contract",
                        call.lineno,
                        f"argument {pname!r} of {callee.name}: dim "
                        f"{_dim_text(self.dims, g)} conflicts with "
                        f"declared {_dim_text(self.dims, w)} in "
                        f"{_shape_text(self.dims, tuple(spec.dims))}",
                    )
            if (
                got.dtype is not None
                and not got.weak
                and got.dtype != spec.dtype
            ):
                self.emit(
                    FAM_SHAPE,
                    "call-contract",
                    call.lineno,
                    f"argument {pname!r} of {callee.name} is {got.dtype} "
                    f"but the contract declares {spec.dtype}",
                )
        self._discharge(*args, *kwargs.values())
        ret = contract.returns
        if ret is None:
            return _UNKNOWN
        return AbsVal(
            shape=tuple(fresh(d) for d in ret.dims),
            dtype=ret.dtype,
            sent=S_MAYBE if ret.inf else S_NON,
        )


# --------------------------------------------------------------------------
# sentinel-domain inference for unannotated functions
# --------------------------------------------------------------------------


def infer_sentinel_params(fn: ast.AST, mv: ModuleView) -> Set[str]:
    """Params living in the INF distance domain, learned from co-occurrence
    with the sentinel: any name inside an expression that is clamped to,
    compared with, or filled by INF belongs to the domain."""

    def is_inf(node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in mv.inf_names
        iv = _int_const(node)
        if iv is not None and iv >= 2 ** 28:
            return True
        chain = dotted_name(node)
        return bool(chain) and chain.split(".")[-1] == "INF"

    domain: Set[str] = set()

    def names_in(node: ast.AST) -> Set[str]:
        return {
            n.id for n in ast.walk(node) if isinstance(n, ast.Name)
        } - mv.inf_names

    for node in _walk_shallow(fn):
        if isinstance(node, ast.Call):
            cname = call_name(node)
            if cname in ("minimum", "fmin", "clip") and any(
                is_inf(a) for a in node.args
            ):
                for a in node.args:
                    if not is_inf(a):
                        domain |= names_in(a)
            elif cname in ("where", "full_like", "select") and any(
                is_inf(a) for a in node.args
            ):
                for a in node.args[1:]:
                    if not is_inf(a):
                        domain |= names_in(a)
        elif isinstance(node, ast.Compare):
            operands = [node.left] + list(node.comparators)
            if any(is_inf(o) for o in operands):
                for o in operands:
                    if not is_inf(o):
                        domain |= names_in(o)
    params = {
        a.arg
        for a in list(fn.args.args) + list(fn.args.kwonlyargs)
    }
    return domain & params


# --------------------------------------------------------------------------
# the shared pass
# --------------------------------------------------------------------------


class _ShapeFlowPass:
    """One interpretation of the whole analyzed set, cached on the
    AnalysisContext; the four rule families each read their bucket."""

    def __init__(self, ctx: AnalysisContext):
        self.ctx = ctx
        self.cg: CallGraph = build_callgraph(ctx)
        self.views: Dict[str, ModuleView] = {
            sf.rel: ModuleView.scan(sf) for sf in ctx.files
        }
        self.vocab: Set[str] = mesh_axis_vocabulary(ctx)
        self.findings: Dict[str, List[Tuple[str, SourceFile, int, str]]] = {
            FAM_SHAPE: [],
            FAM_SENT: [],
            FAM_DTYPE: [],
            FAM_COLL: [],
        }
        self.contracts: Dict[int, Contract] = {}
        self.calls_checked = 0
        self.functions_seen = 0
        self.inferred = 0

    def emit(
        self, family: str, check: str, sf: SourceFile, line: int, msg: str
    ) -> None:
        self.findings[family].append((check, sf, line, msg))

    # -- contracts ---------------------------------------------------------

    def collect_contracts(self) -> List[FunctionInfo]:
        annotated: List[FunctionInfo] = []
        for fi in self.cg.functions():
            dec = contract_decorator(fi.node)
            if dec is None:
                continue
            contract, err = parse_contract_decorator(dec)
            if err is not None:
                self.emit(
                    FAM_SHAPE,
                    "contract-syntax",
                    fi.sf,
                    dec.lineno,
                    f"malformed @shape_contract on {fi.name}: {err}",
                )
                continue
            if contract is None:
                continue
            params = {
                a.arg
                for a in list(fi.node.args.args)
                + list(fi.node.args.kwonlyargs)
            }
            unknown = set(contract.params) - params
            if unknown:
                self.emit(
                    FAM_SHAPE,
                    "contract-params",
                    fi.sf,
                    dec.lineno,
                    f"@shape_contract on {fi.name} names "
                    f"{sorted(unknown)} which are not parameters",
                )
                continue
            self.contracts[id(fi.node)] = contract
            annotated.append(fi)
        return annotated

    def contracts_fingerprint(self) -> str:
        """Hash of every contract in the analyzed set: contracts are
        summary inputs, so any edit invalidates cached summaries."""
        h = hashlib.sha256()
        entries = []
        for fi in self.cg.functions():
            dec = contract_decorator(fi.node)
            if dec is not None:
                entries.append(
                    f"{fi.module}:{fi.qname}:{ast.dump(dec)}"
                )
        for e in sorted(entries):
            h.update(e.encode())
        return h.hexdigest()

    # -- the interpreter loop ----------------------------------------------

    def run(self) -> None:
        from openr_tpu.analysis.cache import (
            CACHE_NAME,
            load_shapeflow_summaries,
            store_shapeflow_summaries,
        )

        annotated = self.collect_contracts()
        traced, _direct = traced_function_infos(self.ctx)
        targets = sorted(
            set(traced) | set(annotated),
            key=lambda fi: (fi.sf.rel, fi.node.lineno),
        )
        fingerprint = self.contracts_fingerprint()
        cache_path = self.ctx.root / CACHE_NAME
        cached = load_shapeflow_summaries(
            cache_path, ANALYSIS_VERSION, fingerprint
        )
        file_sha: Dict[str, str] = {}
        new_summaries: Dict[str, Dict] = {}
        for fi in targets:
            rel = fi.sf.rel
            sha = file_sha.setdefault(
                rel, hashlib.sha256(fi.sf.source.encode()).hexdigest()
            )
            contract = self.contracts.get(id(fi.node))
            sentinel_params: Set[str] = set()
            if contract is None:
                ent = cached.get(rel)
                fns = (
                    ent["functions"]
                    if ent is not None and ent.get("hash") == sha
                    else None
                )
                if fns is not None and fi.qname in fns:
                    sentinel_params = set(fns[fi.qname])
                else:
                    sentinel_params = infer_sentinel_params(
                        fi.node, self.views[rel]
                    )
                    self.inferred += 1
                new_summaries.setdefault(
                    rel, {"hash": sha, "functions": {}}
                )["functions"][fi.qname] = sorted(sentinel_params)
            self.functions_seen += 1
            FnAnalysis(self, fi, contract, sentinel_params).run()
        store_shapeflow_summaries(
            cache_path, ANALYSIS_VERSION, fingerprint, new_summaries
        )
        # structural per-module checks (host-side shape plumbing included)
        for sf in self.ctx.files:
            mv = self.views[sf.rel]
            if mv.jaxy or mv.np:
                self._check_divisibility(sf)
                self._check_reserved_slot(sf)
            if mv.jaxy:
                self._scan_collectives(sf, mv)
                self._scan_float64(sf, mv, traced)

    # -- tile divisibility -------------------------------------------------

    def _check_divisibility(self, sf: SourceFile) -> None:
        for fn in (
            n for n in walk_nodes(sf.tree) if isinstance(n, _FuncDef)
        ):
            guarded: Set[Tuple[str, str]] = set()
            for node in _walk_shallow(fn):
                if (
                    isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Mod)
                    and isinstance(node.left, ast.Name)
                    and isinstance(node.right, ast.Name)
                ):
                    guarded.add((node.left.id, node.right.id))
            parents: Dict[int, ast.AST] = {}
            for node in _walk_shallow(fn):
                for child in ast.iter_child_nodes(node):
                    parents[id(child)] = node
            for node in _walk_shallow(fn):
                if not (
                    isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.FloorDiv)
                    and isinstance(node.left, ast.Name)
                    and isinstance(node.right, ast.Name)
                ):
                    continue
                pair = (node.left.id, node.right.id)
                if pair in guarded:
                    continue
                # only splits that directly produce a shape-like value:
                # the div reached through bare tuples from a Return or an
                # Assign (a .astype()-wrapped array div is data, not a dim)
                cur = parents.get(id(node))
                while isinstance(cur, (ast.Tuple, ast.List)):
                    cur = parents.get(id(cur))
                if isinstance(cur, (ast.Return, ast.Assign)):
                    self.emit(
                        FAM_SHAPE,
                        "tile-divisibility",
                        sf,
                        node.lineno,
                        f"tile split {pair[0]} // {pair[1]} without a "
                        f"divisibility guard — assert "
                        f"{pair[0]} % {pair[1]} == 0 first (a remainder "
                        f"silently truncates the last tile)",
                    )

    # -- reserved padding slot ---------------------------------------------

    def _check_reserved_slot(self, sf: SourceFile) -> None:
        for fn in (
            n for n in walk_nodes(sf.tree) if isinstance(n, _FuncDef)
        ):
            buckets: Dict[str, ast.Call] = {}
            for node in _walk_shallow(fn):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and (call_name(node.value) or "").endswith(
                        "_next_bucket"
                    )
                ):
                    buckets[node.targets[0].id] = node.value
            if not buckets:
                continue
            for node in _walk_shallow(fn):
                if (
                    isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and isinstance(node.left, ast.Name)
                    and node.left.id in buckets
                    and isinstance(node.right, ast.Constant)
                    and node.right.value == 1
                ):
                    call = buckets[node.left.id]
                    arg = call.args[0] if call.args else None
                    reserves = (
                        isinstance(arg, ast.BinOp)
                        and isinstance(arg.op, ast.Add)
                        and (
                            _int_const(arg.right) == 1
                            or _int_const(arg.left) == 1
                        )
                    )
                    if not reserves:
                        self.emit(
                            FAM_SHAPE,
                            "reserved-slot",
                            sf,
                            call.lineno,
                            f"frontier bucket {node.left.id} uses "
                            f"{node.left.id} - 1 as a padding slot but "
                            f"its _next_bucket argument does not reserve "
                            f"it (+ 1): real segment ids can collide "
                            f"with the padding slot",
                        )

    # -- collectives -------------------------------------------------------

    def check_collective(
        self, fa: FnAnalysis, call: ast.Call, name: str
    ) -> None:
        # axis names: positional slot 1 (axis_index: slot 0), or the
        # axis_name keyword; literal strings / tuples only
        axis_node = _kwarg(call, "axis_name")
        if axis_node is None:
            slot = 0 if name == "axis_index" else 1
            if len(call.args) > slot:
                axis_node = call.args[slot]
        if axis_node is not None and self.vocab:
            for axis in _const_strs(axis_node):
                if axis not in self.vocab:
                    self.emit(
                        FAM_COLL,
                        "unknown-axis",
                        fa.sf,
                        call.lineno,
                        f"lax.{name} names mesh axis {axis!r} which is "
                        f"not in the mesh axis vocabulary "
                        f"{sorted(self.vocab)}",
                    )
        if name == "ppermute":
            perm_node = _kwarg(call, "perm")
            if perm_node is None and len(call.args) > 2:
                perm_node = call.args[2]
            if isinstance(perm_node, (ast.List, ast.Tuple)):
                self._check_perm_literal(fa, call, perm_node)

    def _check_perm_literal(
        self, fa: FnAnalysis, call: ast.Call, perm: ast.AST
    ) -> None:
        srcs: List[int] = []
        dsts: List[int] = []
        for pair in perm.elts:
            if not (
                isinstance(pair, (ast.Tuple, ast.List))
                and len(pair.elts) == 2
            ):
                self.emit(
                    FAM_COLL,
                    "perm-malformed",
                    fa.sf,
                    call.lineno,
                    "lax.ppermute perm entries must be (source, dest) "
                    "pairs",
                )
                return
            s, d = _int_const(pair.elts[0]), _int_const(pair.elts[1])
            if s is None or d is None:
                return  # dynamic entries: cannot prove anything
            srcs.append(s)
            dsts.append(d)
        if len(set(srcs)) != len(srcs) or len(set(dsts)) != len(dsts):
            self.emit(
                FAM_COLL,
                "perm-malformed",
                fa.sf,
                call.lineno,
                f"lax.ppermute perm is not a permutation: sources "
                f"{srcs} / dests {dsts} contain duplicates (a device "
                f"would receive two messages)",
            )

    # -- float64 in traced code --------------------------------------------

    def _scan_float64(
        self, sf: SourceFile, mv: ModuleView, traced: Set[FunctionInfo]
    ) -> None:
        traced_nodes = {fi.node for fi in traced if fi.sf.rel == sf.rel}
        for fn in traced_nodes:
            for node in _walk_shallow(fn):
                hit = None
                if (
                    isinstance(node, ast.Attribute)
                    and node.attr == "float64"
                ):
                    hit = dotted_name(node) or "float64"
                elif (
                    isinstance(node, ast.Constant)
                    and node.value == "float64"
                ):
                    hit = "'float64'"
                if hit is not None:
                    self.emit(
                        FAM_DTYPE,
                        "weak-float64",
                        sf,
                        node.lineno,
                        f"{hit} inside traced code: x64 is disabled on "
                        f"the accelerator path, so this weakly demotes "
                        f"(or forces a recompile under jax_enable_x64)",
                    )

    def _scan_collectives(self, sf: SourceFile, mv: ModuleView) -> None:
        # collective sites OUTSIDE the interpreted set still get their
        # conformance checks (the interpreter already covered traced fns,
        # but a module-level or helper collective must not escape)
        interpreted: Set[int] = set()
        traced, _ = traced_function_infos(self.ctx)
        for fi in traced:
            if fi.sf.rel == sf.rel:
                for node in walk_nodes(fi.node):
                    if isinstance(node, ast.Call):
                        interpreted.add(id(node))
        for fi_node in walk_nodes(sf.tree):
            if not isinstance(fi_node, ast.Call):
                continue
            if id(fi_node) in interpreted:
                continue
            chain = dotted_name(fi_node.func) or ""
            parts = chain.split(".")
            name = parts[-1]
            if name not in _AXIS_COLLECTIVES:
                continue
            is_lax = (
                (len(parts) == 2 and parts[0] in mv.lax)
                or (len(parts) >= 3 and parts[-2] == "lax")
            )
            if not is_lax:
                continue
            shim = _StructuralShim(sf, self.views[sf.rel])
            self.check_collective(shim, fi_node, name)


class _StructuralShim:
    """Minimal FnAnalysis stand-in for structural collective checks."""

    def __init__(self, sf: SourceFile, mv: ModuleView):
        self.sf = sf
        self.mv = mv


# --------------------------------------------------------------------------
# shared-cache entry point + the four rule families
# --------------------------------------------------------------------------


def shapeflow_findings(
    ctx: AnalysisContext,
) -> Dict[str, List[Tuple[str, SourceFile, int, str]]]:
    cached = getattr(ctx, "_shapeflow", None)
    if cached is not None:
        return cached
    t0 = time.perf_counter()
    pass_ = _ShapeFlowPass(ctx)
    pass_.run()
    LAST_SHAPEFLOW_STATS.clear()
    LAST_SHAPEFLOW_STATS.update(
        {
            "contracts": len(pass_.contracts),
            "functions": pass_.functions_seen,
            "calls_checked": pass_.calls_checked,
            "inferred": pass_.inferred,
            "wall_ms": round((time.perf_counter() - t0) * 1e3, 3),
        }
    )
    ctx._shapeflow = pass_.findings
    return pass_.findings


class _ShapeFlowRule(Rule):
    family = ""

    def run(self, ctx: AnalysisContext):
        for check, sf, line, msg in shapeflow_findings(ctx).get(
            self.family, []
        ):
            yield self.finding(check, sf, line, msg)


@register
class ShapeMismatchRule(_ShapeFlowRule):
    name = FAM_SHAPE
    family = FAM_SHAPE
    description = (
        "provable shape conflicts in traced kernels: broadcast/rank "
        "errors, contract violations at call/return seams, unguarded "
        "tile splits, unreserved padding slots"
    )
    severity = "error"


@register
class SentinelOverflowRule(_ShapeFlowRule):
    name = FAM_SENT
    family = FAM_SENT
    description = (
        "int32 sentinel arithmetic leaving the INF band: additions of "
        "two maybe-INF values with no dominating jnp.minimum(..., INF) "
        "clamp, collective sums of sentinel operands"
    )
    severity = "error"


@register
class DtypePromotionRule(_ShapeFlowRule):
    name = FAM_DTYPE
    family = FAM_DTYPE
    description = (
        "silent dtype promotion inside traced code: int->float "
        "promotion, bool masks in arithmetic, int true division, "
        "float64 on the accelerator path"
    )
    severity = "advisory"


@register
class CollectiveConformanceRule(_ShapeFlowRule):
    name = FAM_COLL
    family = FAM_COLL
    description = (
        "lax collective conformance: axis names must be in the mesh "
        "axis vocabulary, ppermute perms must be well-formed "
        "permutations"
    )
    severity = "error"
