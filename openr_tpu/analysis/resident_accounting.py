"""resident-accounting: device-resident state must be ledger-visible.

The device-memory observatory (monitor/memledger.py) only works if every
structure that stays resident on device registers with the ledger —
`predict_fit` calibrates against the solvers' real footprints, the fleet
`device_memory` rule attributes leaks by structure, and `getDeviceMemory`
forensics claim to be the whole picture. A `self._x_dev = <device
value>` store that never meets a ledger seam is residency the
observatory cannot see: invisible to watermarks, unattributable in a
leak, uncounted by admission.

Mechanics: reuses device-transfer's per-class producer fixpoint
(`_class_device_env` — module jit bindings, solver factories, device
attributes, device-returning methods) to find methods that STORE a
device-tagged value on `self`. In the resident-state packages
(openr_tpu/solver, openr_tpu/apsp, openr_tpu/te), such a store is
sanctioned only when the enclosing function touches a ledger seam in the
same body: any name or attribute mentioning `ledger` (`self._ledger.
register`, `get_ledger()`) or starting with `_mem` (`self._mem_register`,
`self._mem_area` bookkeeping). Stores of non-device values (None resets,
host mirrors produced by accounted fetches) are not residency and never
trigger.

Advisory: the device tag is the same heuristic classification
device-transfer builds on, and a store can be legitimately covered by a
register a call away (e.g. a helper invoked right after). `--strict`
promotes it; the tier-1 self-run keeps the tree clean at strict level,
so new unledgered residency shows up in review either way.
"""

from __future__ import annotations

import ast
from typing import Optional, Set, Tuple

from openr_tpu.analysis.callgraph import build_callgraph
from openr_tpu.analysis.core import (
    AnalysisContext,
    Rule,
    call_name,
    dotted_name,
    register,
    walk_nodes,
)
from openr_tpu.analysis.dataflow import AliasTracker
from openr_tpu.analysis.device_transfer import (
    _attr_classifier,
    _class_device_env,
    _with_class_env,
)
from openr_tpu.analysis.trace_safety import (
    _numpy_aliases,
    traced_function_infos,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

# the packages that own device-resident state; everything else (tests,
# benches, ops-level scratch) holds arrays transiently per call
_RESIDENT_PACKAGES = (
    "openr_tpu/solver/",
    "openr_tpu/apsp/",
    "openr_tpu/te/",
)


def _touches_ledger(fn) -> bool:
    """True when the function body meets a ledger seam: any attribute or
    name mentioning `ledger`, or an attribute starting with `_mem` (the
    solver seam vocabulary — `_mem_register`, `_mem_release`,
    `_mem_register_resident`, `_mem_area`)."""
    for node in walk_nodes(fn):
        if isinstance(node, ast.Attribute):
            attr = node.attr.lower()
            if "ledger" in attr or attr.startswith("_mem"):
                return True
        elif isinstance(node, ast.Name) and "ledger" in node.id.lower():
            return True
    return False


@register
class ResidentAccountingRule(Rule):
    name = "resident-accounting"
    severity = "advisory"
    description = (
        "device-resident attribute stores in the solver/apsp/te packages "
        "must happen in functions that touch a device-memory ledger seam "
        "(a `ledger`/`_mem*` reference in the same body) so the "
        "observatory's accounting stays the whole picture"
    )

    def run(self, ctx: AnalysisContext):
        cg = build_callgraph(ctx)
        traced, _ = traced_function_infos(ctx)
        traced_nodes = {id(fi.node) for fi in traced}
        for mod in cg.modules.values():
            path = str(mod.sf.path).replace("\\", "/")
            if not any(pkg in path for pkg in _RESIDENT_PACKAGES):
                continue
            np_aliases = _numpy_aliases(mod.sf.tree)

            def classify(call: ast.Call) -> Optional[Tuple[str, str]]:
                func = call.func
                if isinstance(func, ast.Name):
                    kind = cg.resolve_producer(mod, func.id)
                    if kind in ("jit", "device"):
                        return ("device", f"{func.id}(...)")
                    if kind == "factory":
                        return ("jit", func.id)
                elif isinstance(func, ast.Attribute):
                    chain = dotted_name(func)
                    if chain and not chain.startswith("self."):
                        kind = cg.resolve_producer_chain(mod, chain)
                        if kind in ("jit", "device"):
                            return ("device", f"{chain}(...)")
                        if kind == "factory":
                            return ("jit", chain)
                elif isinstance(func, ast.Call):
                    inner = call_name(func)
                    if (
                        inner
                        and cg.resolve_producer(mod, inner) == "factory"
                    ):
                        return ("device", f"{inner}(...)(...)")
                return None

            for cls in walk_nodes(mod.sf.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                env = _class_device_env(cls, classify, np_aliases)
                for fn in cls.body:
                    if not isinstance(fn, _FuncDef):
                        continue
                    if id(fn) in traced_nodes:
                        continue  # trace-safety's jurisdiction
                    if _touches_ledger(fn):
                        continue  # sanctioned: the seam is in the body
                    tracker = AliasTracker(
                        fn,
                        classify_call=_with_class_env(classify, env),
                        np_aliases=np_aliases,
                        classify_attr=_attr_classifier(env),
                    ).run()
                    seen: Set[str] = set()
                    for line, attr, tags in tracker.attr_stores:
                        if attr in seen:
                            continue
                        if not any(t.tag[0] == "device" for t in tags):
                            continue
                        seen.add(attr)
                        yield self.finding(
                            "unledgered-store",
                            mod.sf,
                            line,
                            f"'{cls.name}.{fn.name}' stores a device-"
                            f"tagged value on self.{attr} without "
                            f"touching a ledger seam — register the "
                            f"residency (self._mem_register / "
                            f"ledger.register) in the same function, "
                            f"or waive with a comment",
                        )
