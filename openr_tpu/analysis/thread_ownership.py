"""thread-ownership: externally-reachable methods must not mutate owned
module state without a declared handover.

Every module's mutable attributes are owned by that module's task set
(`@owned_by("decision-loop")` on the class, utils/ownership.py); the ctrl
server's per-connection tasks and the monitor's drain task call into
modules from outside that ownership. A mutation on such a path is exactly
the class of bug SolverSupervisor's shadow audit only detects *after* the
fact — this rule catches it before merge.

Mechanics:
  - The external surface is computed from the ctrl server itself: every
    method name invoked on a module reference (`self.kvstore...`,
    `self.decision...`, including chained receivers like
    `self.kvstore.db(area).set_key_vals`) inside a class named CtrlServer,
    plus the module attributes the Monitor reads (`counters`,
    `histograms` — rebinding those swaps the dict under the monitor).
  - For every class carrying a class-level `@owned_by(...)`, each method
    whose name is on that surface is an entry point; reachability closes
    over same-class `self.method()` calls.
  - Flagged inside reachable methods: attribute (re)binding
    (`self.x = ...`, `self.a.b = ...`, `self.x[...] = ...`, aug-assign,
    `del`) and mutating container calls on self-rooted receivers
    (`self.links.add(...)`, `.pop`, `.update`, ...).
  - Since 2.0 the rule also runs the alias engine (analysis/dataflow.py)
    over every reachable method: ALIASED mutation — `d = self.x;
    d[k] = v`, `d.update(...)` on a local that may alias owned state,
    including through sub-object loads (`row = self.db[area]`) — is
    flagged with the alias chain in the message (the ROADMAP
    analysis-depth gap this version closes), and owned state handed to
    another execution context (a `Thread(...)` target, `submit`/
    `run_in_executor`, `call_soon_threadsafe`, a queue `put`) from a
    ctrl-reachable method is an `escaped-state` finding: once it crosses,
    the owner serializes nothing.

Declared handovers (not flagged):
  - the entry method is marked shared — `# analysis: shared` on its `def`
    line (or the line above), or a method-level `@owned_by("...")`
    decorator. A shared method must be synchronous: it then runs
    loop-serialized with the owner's callbacks (one asyncio loop), which
    is the architectural reason these handovers are safe. An *async*
    shared method is flagged regardless — it can interleave at awaits.
  - the mutation is lexically inside `with`/`async with` on a context
    whose name mentions a lock (`self._program_lock`, ...).
  - the attribute's `__init__` assignment carries `# analysis: shared`.
  - **subscriber-queue handover** (`# analysis: queue` on the attribute's
    `__init__` assignment): the attribute is a bounded subscriber
    queue/registry whose *publisher-side enqueue is the sanctioned seam*
    (the streaming fan-out pattern, docs/Streaming.md — ctrl connection
    tasks register/deregister, the owner's dispatch task enqueues; all
    interleaving happens at awaits on one loop). Unlike `# analysis:
    shared` on a method — which waives the whole method body — the queue
    marker waives only mutations OF THAT ATTRIBUTE, so an unrelated
    mutation in the same method is still flagged. The sanction requires
    the entry method to be synchronous: a queue-attr mutation reachable
    from an *async* ctrl-facing method is an `async-enqueue` finding
    (it can interleave with the dispatching owner mid-enqueue).

Severity is advisory by default (reachability is name-based and therefore
heuristic); `ANALYSIS_STRICT=1` promotes it.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from openr_tpu.analysis.core import (
    AnalysisContext,
    Rule,
    SourceFile,
    dotted_name,
    register,
    walk_nodes,
)
from openr_tpu.analysis.dataflow import AliasTracker, alias_chain_text

# module references a CtrlServer/Monitor holds (composition in openr.py);
# stream_manager is the streaming control plane's fan-out registry
# (docs/Streaming.md) — its subscriber add/remove/enqueue methods are
# ctrl-reachable like any module method
MODULE_ATTRS = {
    "kvstore",
    "decision",
    "fib",
    "link_monitor",
    "prefix_manager",
    "prefix_allocator",
    "monitor",
    "config_store",
    "spark",
    "stream_manager",
}
# attributes the Monitor aggregates directly off module objects: rebinding
# them from an external path swaps the object under the aggregator
MONITOR_READ_ATTRS = {"counters", "histograms"}

_MUTATOR_METHODS = {
    "append",
    "extend",
    "insert",
    "add",
    "update",
    "setdefault",
    "pop",
    "popitem",
    "remove",
    "discard",
    "clear",
}
_SHARED_RE = re.compile(r"#\s*analysis:\s*shared\b")
_QUEUE_RE = re.compile(r"#\s*analysis:\s*queue\b")
_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


def _decorator_owner(node) -> Optional[str]:
    """The owner string of an @owned_by("...") decorator, if present."""
    for dec in node.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = dotted_name(target) or ""
        if name.split(".")[-1] == "owned_by":
            if isinstance(dec, ast.Call) and dec.args:
                arg = dec.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    return arg.value
            return "?"
    return None


# CtrlServer methods that run in the daemon's lifecycle context (owner
# side), not from client connection tasks: module calls made there are
# not externally reachable and must not widen the surface (the server
# starting/stopping its own stream manager is the owner acting)
_LIFECYCLE_METHODS = {"__init__", "start", "stop"}


def external_surface(ctx: AnalysisContext) -> Set[str]:
    """Method names invoked on module references from the ctrl server's
    request paths (lifecycle methods excluded)."""
    surface: Set[str] = set()
    for sf in ctx.files:
        for node in walk_nodes(sf.tree):
            if not (
                isinstance(node, ast.ClassDef) and node.name == "CtrlServer"
            ):
                continue
            request_methods = [
                n
                for n in node.body
                if not (
                    isinstance(n, _FuncDef)
                    and n.name in _LIFECYCLE_METHODS
                )
            ]
            for method in request_methods:
                for sub in walk_nodes(method):
                    if isinstance(sub, ast.Call) and isinstance(
                        sub.func, ast.Attribute
                    ):
                        chain = dotted_name(sub.func)
                        if chain is None:
                            continue
                        parts = chain.split(".")
                        if (
                            len(parts) >= 3
                            and parts[0] == "self"
                            and parts[1] in MODULE_ATTRS
                        ):
                            surface.add(parts[-1])
    return surface


def _method_is_shared(sf: SourceFile, fn) -> bool:
    if _decorator_owner(fn) is not None:
        return True
    for i in (fn.lineno - 1, fn.lineno - 2):
        if 0 <= i < len(sf.lines) and _SHARED_RE.search(sf.lines[i]):
            return True
    return False


def _marked_attrs(sf: SourceFile, cls: ast.ClassDef, marker) -> Set[str]:
    """Attributes whose __init__ assignment line matches `marker`."""
    marked: Set[str] = set()
    for node in cls.body:
        if isinstance(node, _FuncDef) and node.name == "__init__":
            for sub in walk_nodes(node):
                if isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    targets = (
                        sub.targets
                        if isinstance(sub, ast.Assign)
                        else [sub.target]
                    )
                    for t in targets:
                        attr = _self_attr_root(t)
                        if attr and marker.search(
                            sf.lines[sub.lineno - 1]
                        ):
                            marked.add(attr)
    return marked


def _shared_attrs(sf: SourceFile, cls: ast.ClassDef) -> Set[str]:
    """Attributes whose __init__ assignment is marked `# analysis: shared`."""
    return _marked_attrs(sf, cls, _SHARED_RE)


def _queue_attrs(sf: SourceFile, cls: ast.ClassDef) -> Set[str]:
    """Attributes declared as subscriber-queue handovers
    (`# analysis: queue` on their __init__ assignment): mutations of
    them from SYNC ctrl-reachable methods are the sanctioned
    publisher-side enqueue seam; from async methods they are flagged."""
    return _marked_attrs(sf, cls, _QUEUE_RE)


def _self_attr_root(node: ast.AST) -> Optional[str]:
    """First attribute name of a self-rooted chain: self.x[...] -> 'x',
    self.a.b -> 'a'; None when not rooted at bare self."""
    chain: List[str] = []
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        if isinstance(node, ast.Attribute):
            chain.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name) and node.id == "self" and chain:
        return chain[-1]
    return None


def _lock_spans(fn) -> List[Tuple[int, int]]:
    """(start, end) line spans of `with <...lock...>:` bodies — the
    post-filter for alias-engine findings (the engine itself is
    context-free by design)."""
    spans: List[Tuple[int, int]] = []
    for node in walk_nodes(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                name = dotted_name(item.context_expr) or ""
                if "lock" in name.lower():
                    spans.append(
                        (node.lineno, getattr(node, "end_lineno", node.lineno))
                    )
                    break
    return spans


def _in_spans(line: int, spans: List[Tuple[int, int]]) -> bool:
    return any(s <= line <= e for s, e in spans)


def _lock_guarded(stack: List[ast.AST]) -> bool:
    for node in stack:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                name = dotted_name(item.context_expr) or ""
                if "lock" in name.lower():
                    return True
    return False


def _walk_with_stack(fn) -> Iterable[Tuple[ast.AST, List[ast.AST]]]:
    """(node, enclosing-statement stack), not descending into nested defs."""

    def rec(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FuncDef):
                continue
            yield child, stack
            yield from rec(child, stack + [child])

    yield from rec(fn, [])


def _mutations(fn) -> Iterable[Tuple[int, str, str]]:
    """(line, attr, description) of owned-state mutations in one method."""
    for node, stack in _walk_with_stack(fn):
        if _lock_guarded(stack + [node]):
            continue
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                attr = _self_attr_root(t)
                if attr:
                    yield node.lineno, attr, f"assignment to self.{attr}"
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                attr = _self_attr_root(t)
                if attr:
                    yield node.lineno, attr, f"del of self.{attr}"
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATOR_METHODS
        ):
            attr = _self_attr_root(node.func.value)
            if attr:
                yield (
                    node.lineno,
                    attr,
                    f"self.{attr}.{node.func.attr}(...)",
                )


@register
class ThreadOwnershipRule(Rule):
    name = "thread-ownership"
    severity = "advisory"
    description = (
        "ctrl/monitor-reachable methods of @owned_by classes must not "
        "mutate owned state without a lock or a '# analysis: shared' "
        "handover (shared methods must be synchronous)"
    )

    def run(self, ctx: AnalysisContext):
        surface = external_surface(ctx)
        if not surface:
            return  # no ctrl server in scope; nothing is reachable
        for sf in ctx.files:
            for cls in walk_nodes(sf.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                owner = _decorator_owner(cls)
                if owner is None:
                    continue
                yield from self._check_class(sf, cls, owner, surface)

    def _check_class(self, sf, cls, owner, surface):
        methods: Dict[str, ast.AST] = {
            n.name: n for n in cls.body if isinstance(n, _FuncDef)
        }
        shared_attrs = _shared_attrs(sf, cls)
        queue_attrs = _queue_attrs(sf, cls)
        # the monitor aggregates module.counters / module.histograms by
        # reference: rebinding either outside __init__ swaps the object
        # under the aggregator — flag it from ANY method of an owned class
        for name, fn in methods.items():
            if name == "__init__":
                continue
            for node, _ in _walk_with_stack(fn):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and t.attr in MONITOR_READ_ATTRS
                        ):
                            yield self.finding(
                                "monitor-rebind",
                                sf,
                                node.lineno,
                                f"{cls.name}.{name} rebinds "
                                f"self.{t.attr}: the monitor holds the "
                                f"old dict by reference — mutate in "
                                f"place instead",
                            )
        for name, fn in methods.items():
            if name not in surface or name.startswith("__"):
                continue
            if _method_is_shared(sf, fn):
                # declared handover — but it only holds for synchronous
                # methods (loop-serialized with the owner's callbacks)
                if isinstance(fn, ast.AsyncFunctionDef):
                    yield self.finding(
                        "async-shared",
                        sf,
                        fn.lineno,
                        f"{cls.name}.{name} is declared shared but is "
                        f"async: it can interleave with the "
                        f"'{owner}' owner at every await",
                    )
                continue
            # close reachability over same-class self.method() calls,
            # stopping at declared-shared methods (already vetted)
            seen: Set[str] = set()
            queue = [name]
            while queue:
                cur = queue.pop()
                if cur in seen:
                    continue
                seen.add(cur)
                cur_fn = methods.get(cur)
                if cur_fn is None:
                    continue
                if cur != name and _method_is_shared(sf, cur_fn):
                    continue
                entry_async = isinstance(fn, ast.AsyncFunctionDef)
                for line, attr, what in _mutations(cur_fn):
                    if attr in shared_attrs:
                        continue
                    via = "" if cur == name else f" (via {cls.name}.{cur})"
                    if attr in queue_attrs:
                        # subscriber-queue handover: publisher-side
                        # enqueue from a SYNC ctrl-facing method is the
                        # sanctioned seam (docs/Streaming.md); an async
                        # entry can interleave mid-enqueue and is not
                        if entry_async:
                            yield self.finding(
                                "async-enqueue",
                                sf,
                                line,
                                f"{cls.name}.{name} is async but "
                                f"mutates subscriber-queue attribute "
                                f"self.{attr} ({what}){via}: the "
                                f"'# analysis: queue' handover only "
                                f"sanctions synchronous enqueue — make "
                                f"the entry method sync or take a lock",
                            )
                        continue
                    yield self.finding(
                        "unowned-mutation",
                        sf,
                        line,
                        f"{cls.name}.{name} is reachable from the ctrl "
                        f"server but mutates '{owner}'-owned state: "
                        f"{what}{via} — mark the method "
                        f"'# analysis: shared' (sync only), take a "
                        f"lock, mark the attribute shared in __init__, "
                        f"or declare a subscriber-queue handover "
                        f"('# analysis: queue')",
                    )
                # alias-engine pass: mutations through local aliases of
                # owned state, and owned state escaping the loop
                tracker = AliasTracker(cur_fn).run()
                spans = _lock_spans(cur_fn)
                via = "" if cur == name else f" (via {cls.name}.{cur})"
                for m in tracker.mutations:
                    if m.direct:
                        continue  # the attribute walk above covers these
                    if m.alias.tag[1] in shared_attrs:
                        continue
                    if m.alias.tag[1] in queue_attrs and not entry_async:
                        continue  # sanctioned enqueue seam, via alias
                    if _in_spans(m.line, spans):
                        continue
                    yield self.finding(
                        "aliased-mutation",
                        sf,
                        m.line,
                        f"{cls.name}.{name} is reachable from the ctrl "
                        f"server but mutates '{owner}'-owned state "
                        f"through an alias: {m.desc} mutates "
                        f"{alias_chain_text(m.alias)}{via} — mutate "
                        f"through self so a handover can be declared, "
                        f"or mark the method '# analysis: shared'",
                    )
                for esc in tracker.escapes:
                    if esc.sink == "the return value":
                        continue  # sync reads off the loop are the API
                    if esc.alias.tag[1] in shared_attrs:
                        continue
                    if _in_spans(esc.line, spans):
                        continue
                    yield self.finding(
                        "escaped-state",
                        sf,
                        esc.line,
                        f"{cls.name}.{name} is reachable from the ctrl "
                        f"server and hands '{owner}'-owned state "
                        f"({alias_chain_text(esc.alias)}) to "
                        f"{esc.sink}{via} — once it crosses the "
                        f"ownership boundary the loop serializes "
                        f"nothing; pass a copy instead",
                    )
                for node, _ in _walk_with_stack(cur_fn):
                    if (
                        isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in methods
                    ):
                        queue.append(node.func.attr)
