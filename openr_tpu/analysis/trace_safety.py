"""trace-safety: no host syncs or Python control flow on traced values.

The solver hot path (ops/spf.py, solver/tpu.py, parallel/mesh.py) lives
inside `jax.jit`; the paper's wins die the moment a traced function forces
an implicit host transfer (the tensorized Floyd–Warshall lesson, PAPERS.md).
This rule finds the functions that trace — decorated with `jax.jit`, passed
to a `jax.jit(...)`/`shard_map(...)` call or a transform that traces its
operand (`grad(...)`/`value_and_grad(...)`/`vmap(...)` — the differentiable
TE core in openr_tpu/te/ reaches its objective exclusively through
`jax.value_and_grad`), nested inside a traced function, or called from one
— transitively ACROSS MODULE BOUNDARIES: since 2.0 the reachability
closure runs on the whole-package call graph (analysis/callgraph.py), so
a jitted step in `parallel/mesh.py` that calls a helper imported from
`ops/spf.py` drags that helper (and everything it calls, wherever it
lives) into the traced set. Same-module calls resolve by simple name (the
per-file behavior, collisions unioned); cross-module calls resolve only
through an explicit `from X import f` / `import X as y` link, so
same-named helpers in unrelated modules never alias. Factory seeds cross
modules too: `jax.jit(factory(...), ...)` traces the nested function the
factory returns. Flagged inside traced functions:

  - `python-branch`: an `if`/`while`/conditional-expression test that
    contains a jnp/jax call (tracer-valued: `if jnp.any(...)` forces a
    concretization error or a silent host sync) or, in *directly* jitted
    functions where every parameter is a tracer, a bare parameter used in
    the test. Static introspection (`x.ndim`, `x.shape`, `x.dtype`,
    `len(x)`, `isinstance(...)`) is exempt — branching on trace-time
    constants is the shape-bucketing idiom this codebase is built on.
  - `host-sync`: `.item()` / `.tolist()` calls, `float()/int()/bool()` of
    a tracer-valued expression, and any `np.*` call — numpy round-trips
    device data through the host mid-trace.
  - `nonstatic-carry`: a Python `list`/`dict`/`set` literal (or
    constructor call) as the carry/init operand of
    `lax.while_loop`/`fori_loop`/`scan` — non-static containers in carry
    state retrace per call and defeat executable reuse.

Indirectly traced functions skip the bare-parameter branch check: their
parameters can be trace-time statics threaded from the shape key
(`zero_end`, `starts`, `shapes` in ops/spf.py), and flagging those would
bury the real signal. Precision over recall; the jnp-call and host-sync
checks still apply everywhere.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from openr_tpu.analysis.callgraph import (
    FunctionInfo,
    build_callgraph,
    returned_local_defs,
)
from openr_tpu.analysis.core import (
    AnalysisContext,
    Finding,
    Rule,
    SourceFile,
    call_name,
    dotted_name,
    register,
    walk_nodes,
)

_STATIC_ATTRS = {"ndim", "shape", "dtype", "size"}
_STATIC_CALLS = {"len", "isinstance", "range", "enumerate", "zip"}
_HOST_SYNC_METHODS = {"item", "tolist"}
_CAST_CALLS = {"float", "int", "bool"}
# carry/init argument position per structured-control-flow primitive
_CARRY_ARG = {"while_loop": 2, "fori_loop": 3, "scan": 1}
_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


def _jax_numpy_aliases(tree: ast.AST) -> Set[str]:
    """Module aliases whose calls are tracer-valued (jax.numpy, jax, lax)."""
    aliases: Set[str] = set()
    for node in walk_nodes(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name in ("jax", "jax.numpy", "jax.lax"):
                    aliases.add(a.asname or a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom):
            if node.module in ("jax", "jax.numpy", "jax.lax"):
                for a in node.names:
                    if a.name in ("numpy", "lax"):
                        aliases.add(a.asname or a.name)
    return aliases


def _numpy_aliases(tree: ast.AST) -> Set[str]:
    aliases: Set[str] = set()
    for node in walk_nodes(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "numpy":
                    aliases.add(a.asname or "numpy")
    return aliases


# calls whose function-valued arguments trace: jit/shard_map compile,
# grad/value_and_grad/vmap trace their operand on every (re)trace
_TRACE_ENTRY_CALLS = ("jit", "shard_map", "grad", "value_and_grad", "vmap")


def _is_jit_entry(call: ast.Call) -> bool:
    """jax.jit(...) / shard_map(...) / grad(...) / vmap(...) call."""
    name = call_name(call)
    return name in _TRACE_ENTRY_CALLS


def _jit_decorated(fn) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        base = dotted_name(target) or ""
        if base.split(".")[-1] in _TRACE_ENTRY_CALLS:
            return True
        if isinstance(dec, ast.Call):
            # functools.partial(jax.jit, ...) and friends
            for arg in dec.args:
                nm = dotted_name(arg) or ""
                if nm.split(".")[-1] in _TRACE_ENTRY_CALLS:
                    return True
    return False


def _collect_defs(tree: ast.AST) -> List:
    return [n for n in walk_nodes(tree) if isinstance(n, _FuncDef)]


def _traced_functions(tree: ast.AST) -> Tuple[Set, Set]:
    """(traced defs, directly-jitted defs) for one module.

    Direct seeds: decorated with jit/shard_map, or their bare name is
    passed as an argument to a jit/shard_map call anywhere in the module
    (the `jax.jit(solve, in_shardings=...)` factory idiom). Traced then
    closes over lexical nesting and same-module calls by simple name.
    """
    defs = _collect_defs(tree)
    by_name: Dict[str, List] = {}
    for fn in defs:
        by_name.setdefault(fn.name, []).append(fn)

    jit_arg_names: Set[str] = set()
    for node in walk_nodes(tree):
        if isinstance(node, ast.Call) and _is_jit_entry(node):
            for arg in node.args:
                if isinstance(arg, ast.Name):
                    jit_arg_names.add(arg.id)

    direct = {
        fn
        for fn in defs
        if _jit_decorated(fn) or fn.name in jit_arg_names
    }
    traced = set(direct)
    changed = True
    while changed:
        changed = False
        for fn in list(traced):
            for node in walk_nodes(fn):
                if node is fn:
                    continue
                if isinstance(node, _FuncDef) and node not in traced:
                    traced.add(node)
                    changed = True
                elif (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in by_name
                ):
                    for target in by_name[node.func.id]:
                        if target not in traced:
                            traced.add(target)
                            changed = True
    return traced, direct


class _TestScanner:
    """Why a branch test is trace-unsafe, or None."""

    def __init__(self, hot_params: Set[str], jnp_aliases: Set[str]):
        self.hot_params = hot_params
        self.jnp = jnp_aliases

    def scan(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return None  # x.ndim / x.shape[...] are trace-time statics
            return self.scan(node.value)
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in _STATIC_CALLS:
                return None
            root = dotted_name(node.func)
            if root and root.split(".")[0] in self.jnp:
                return f"call to tracer-valued {root}(...)"
            for child in list(node.args) + [
                kw.value for kw in node.keywords
            ]:
                reason = self.scan(child)
                if reason:
                    return reason
            return self.scan(node.func) if isinstance(
                node.func, ast.Attribute
            ) else None
        if isinstance(node, ast.Name):
            if node.id in self.hot_params:
                return f"traced parameter '{node.id}'"
            return None
        for child in ast.iter_child_nodes(node):
            reason = self.scan(child)
            if reason:
                return reason
        return None


def _walk_shallow(fn):
    """Walk a function body without descending into nested defs (they are
    analyzed as traced functions in their own right)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, _FuncDef):
            stack.extend(ast.iter_child_nodes(node))


def traced_function_infos(ctx: AnalysisContext):
    """(traced, direct) FunctionInfo sets for the WHOLE scanned set.

    Seeds are the per-module `_traced_functions` result plus cross-module
    jit seeds (an imported function passed to a trace-entry call, and the
    `jax.jit(factory(...), ...)` idiom where the factory's returned nested
    def is the thing that traces). The closure then follows lexical
    nesting and call edges through the package call graph, so reachability
    no longer stops at the file boundary (the ROADMAP analysis-depth gap).
    Cached on the context: every rule in a run shares one traced set."""
    cached = getattr(ctx, "_traced_infos", None)
    if cached is not None:
        return cached
    cg = build_callgraph(ctx)
    traced = set()
    direct = set()
    for sf in ctx.files:
        t, d = _traced_functions(sf.tree)
        for fn in t:
            fi = cg.info(fn)
            if fi is not None:
                traced.add(fi)
        for fn in d:
            fi = cg.info(fn)
            if fi is not None:
                direct.add(fi)
    # cross-module seeds: jit entries fed imported names or factory calls
    for mod in cg.modules.values():
        for node in walk_nodes(mod.sf.tree):
            if not (isinstance(node, ast.Call) and _is_jit_entry(node)):
                continue
            for arg in node.args:
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    for fi in cg.resolve_call_defs(
                        mod, ast.Call(func=arg, args=[], keywords=[])
                    ):
                        if fi.module != mod.name:
                            traced.add(fi)
                            direct.add(fi)
                elif isinstance(arg, ast.Call):
                    for fi in cg.resolve_call_defs(mod, arg):
                        for ret in returned_local_defs(fi.node):
                            ri = cg.info(ret)
                            if ri is not None:
                                traced.add(ri)
    # closure over nesting + resolved call edges, package-wide
    queue = list(traced)
    while queue:
        fi = queue.pop()
        mod = cg.modules.get(fi.module)
        if mod is None:
            continue
        for node in walk_nodes(fi.node):
            if node is fi.node:
                continue
            if isinstance(node, _FuncDef):
                ni = cg.info(node)
                if ni is not None and ni not in traced:
                    traced.add(ni)
                    queue.append(ni)
            elif isinstance(node, ast.Call):
                for target in cg.resolve_call_defs(mod, node):
                    if target is not None and target not in traced:
                        traced.add(target)
                        queue.append(target)
    ctx._traced_infos = (traced, direct)
    return traced, direct


@register
class TraceSafetyRule(Rule):
    name = "trace-safety"
    severity = "error"
    description = (
        "no Python branches on traced values, host syncs (.item/float/np.*)"
        " or non-static carry containers inside jax.jit-reachable functions"
    )

    def run(self, ctx: AnalysisContext):
        traced, direct = traced_function_infos(ctx)
        alias_cache: Dict[int, Tuple[Set[str], Set[str]]] = {}
        for fi in sorted(traced, key=lambda f: (f.sf.rel, f.node.lineno)):
            sf = fi.sf
            cached = alias_cache.get(id(sf))
            if cached is None:
                cached = (
                    _jax_numpy_aliases(sf.tree),
                    _numpy_aliases(sf.tree),
                )
                alias_cache[id(sf)] = cached
            jnp, np_aliases = cached
            fn = fi.node
            hot = (
                {
                    a.arg
                    for a in (
                        fn.args.posonlyargs
                        + fn.args.args
                        + fn.args.kwonlyargs
                    )
                    if a.arg != "self"
                }
                if fi in direct
                else set()
            )
            scanner = _TestScanner(hot, jnp)
            for node in _walk_shallow(fn):
                yield from self._check_node(
                    sf, fn, node, scanner, np_aliases, jnp
                )

    def _check_node(self, sf, fn, node, scanner, np_aliases, jnp):
        if isinstance(node, (ast.If, ast.While, ast.IfExp)):
            reason = scanner.scan(node.test)
            if reason:
                kind = "while" if isinstance(node, ast.While) else "if"
                yield self.finding(
                    "python-branch",
                    sf,
                    node.lineno,
                    f"traced function '{fn.name}': Python {kind} on a "
                    f"traced value ({reason}) — use jnp.where / "
                    f"lax.cond / lax.while_loop",
                )
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if (
                isinstance(node.func, ast.Attribute)
                and name in _HOST_SYNC_METHODS
            ):
                yield self.finding(
                    "host-sync",
                    sf,
                    node.lineno,
                    f"traced function '{fn.name}': .{name}() forces a "
                    f"device->host sync mid-trace",
                )
            elif isinstance(node.func, ast.Name) and name in _CAST_CALLS:
                reason = scanner.scan(
                    node.args[0]
                ) if node.args else None
                if reason:
                    yield self.finding(
                        "host-sync",
                        sf,
                        node.lineno,
                        f"traced function '{fn.name}': {name}() of a "
                        f"traced value ({reason}) concretizes on host",
                    )
            else:
                root = dotted_name(node.func)
                if root and root.split(".")[0] in np_aliases:
                    yield self.finding(
                        "host-sync",
                        sf,
                        node.lineno,
                        f"traced function '{fn.name}': numpy call "
                        f"{root}(...) round-trips device data through "
                        f"the host — use jnp",
                    )
                elif name in _CARRY_ARG and root and (
                    root.split(".")[0] in jnp or "lax" in root.split(".")
                ):
                    pos = _CARRY_ARG[name]
                    if len(node.args) > pos:
                        carry = node.args[pos]
                        bad = isinstance(
                            carry, (ast.List, ast.Dict, ast.Set)
                        ) or (
                            isinstance(carry, ast.Call)
                            and call_name(carry)
                            in ("list", "dict", "set")
                        )
                        if bad:
                            yield self.finding(
                                "nonstatic-carry",
                                sf,
                                carry.lineno,
                                f"traced function '{fn.name}': "
                                f"{name} carry state is a Python "
                                f"container — use a tuple/array pytree",
                            )
