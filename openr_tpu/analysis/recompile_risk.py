"""recompile-risk: static jit arguments must be bounded (bucketed).

Every distinct value of a `static_argnames`/`static_argnums` parameter is
a fresh trace + XLA compile. The codebase's discipline is the
`_PATCH_SLOTS` / `_next_bucket` idiom: any per-event size that reaches a
static argument is first rounded up to a power-of-two bucket, so a
handful of executables serve every event (docs/Decision.md,
`ops/spf.py:_delta_extract`'s `cap`). Feeding a raw `len(...)` — or
arithmetic derived from one — recompiles per event size: the classic
silent TPU-stack performance bug this rule exists to catch.

Mechanics: call sites of jit bindings with statically-known
static_argnames/static_argnums (resolved through the package call graph,
imports included) have each static operand classified as *bounded* or
*unbounded*:

  bounded    constants; bucketing calls (`_next_bucket`, `*_pad`, names
             containing 'bucket'); clamps (min/max/clip); attribute loads
             (config knobs, shape-key fields — already bucketed by the
             compile_graph padding discipline); `int()` of a bounded
             value; locals whose every assignment is bounded; bare
             parameters (the caller's responsibility, checked at ITS call
             sites)
  unbounded  `len(...)`, `sum(...)`, subscripts of data, arithmetic with
             an unbounded operand — anything that varies per call with
             the workload

Advisory severity: boundedness is a heuristic classification; `--strict`
(the tier-1 gate) promotes it.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from openr_tpu.analysis.callgraph import build_callgraph
from openr_tpu.analysis.core import (
    AnalysisContext,
    Rule,
    call_name,
    register,
    walk_nodes,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

_CLAMP_CALLS = {"min", "max", "clip"}
_UNBOUNDED_CALLS = {"len", "sum", "count_nonzero"}


def _is_bucketing_call(name: Optional[str]) -> bool:
    if not name:
        return False
    return "bucket" in name or name.endswith("_pad")


class _Boundedness:
    """Classify expressions inside one enclosing function."""

    def __init__(self, enclosing) -> None:
        self.assignments: Dict[str, List[ast.AST]] = {}
        if enclosing is not None:
            for node in walk_nodes(enclosing):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.assignments.setdefault(t.id, []).append(
                                node.value
                            )
                elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name
                ):
                    if node.value is not None:
                        self.assignments.setdefault(
                            node.target.id, []
                        ).append(node.value)

    def bounded(self, node: ast.AST, depth: int = 0) -> bool:
        if depth > 8:
            return True  # resolution fuel exhausted: trust it
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            # cfg.steps / key[0] / x.shape[1]: config knobs and shape-key
            # fields are bounded by the padding discipline
            return True
        if isinstance(node, ast.Call):
            name = call_name(node)
            if _is_bucketing_call(name) or name in _CLAMP_CALLS:
                return True
            if name in _UNBOUNDED_CALLS:
                return False
            if name in ("int", "abs", "round") and node.args:
                return self.bounded(node.args[0], depth + 1)
            return True  # unknown call: trust it (precision over recall)
        if isinstance(node, ast.Name):
            exprs = self.assignments.get(node.id)
            if not exprs:
                return True  # a parameter or outer binding: trusted
            return all(self.bounded(e, depth + 1) for e in exprs)
        if isinstance(node, ast.BinOp):
            return self.bounded(node.left, depth + 1) and self.bounded(
                node.right, depth + 1
            )
        if isinstance(node, ast.UnaryOp):
            return self.bounded(node.operand, depth + 1)
        if isinstance(node, ast.IfExp):
            return self.bounded(node.body, depth + 1) and self.bounded(
                node.orelse, depth + 1
            )
        return True


@register
class RecompileRiskRule(Rule):
    name = "recompile-risk"
    severity = "advisory"
    description = (
        "static jit arguments (static_argnames/static_argnums) must be "
        "bounded — bucketed via the _next_bucket/_PATCH_SLOTS idiom, "
        "clamped, or configuration — never a raw per-call len()/size"
    )

    def run(self, ctx: AnalysisContext):
        cg = build_callgraph(ctx)
        for mod in cg.modules.values():
            # enclosing-function map for local-assignment resolution
            enclosing_of: Dict[int, ast.AST] = {}
            for fn in walk_nodes(mod.sf.tree):
                if isinstance(fn, _FuncDef):
                    for sub in walk_nodes(fn):
                        if isinstance(sub, ast.Call):
                            enclosing_of.setdefault(id(sub), fn)
            for node in walk_nodes(mod.sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                callee = None
                if isinstance(node.func, ast.Name):
                    callee = node.func.id
                if callee is None:
                    continue
                resolved = cg.resolve_static_argnames(mod, callee)
                if resolved is None:
                    continue
                core, static_names, static_nums = resolved
                if not static_names and not static_nums:
                    continue
                params = [
                    a.arg
                    for a in (
                        core.node.args.posonlyargs + core.node.args.args
                    )
                ]
                checker = _Boundedness(enclosing_of.get(id(node)))
                # keyword statics
                for kw in node.keywords:
                    if kw.arg in static_names and not checker.bounded(
                        kw.value
                    ):
                        yield self.finding(
                            "unbucketed-static",
                            mod.sf,
                            node.lineno,
                            f"call to jitted '{callee}': static argument "
                            f"'{kw.arg}' varies per call (unbounded "
                            f"expression) — bucket it with _next_bucket "
                            f"or clamp it, or every event size compiles "
                            f"a fresh executable",
                        )
                # positional statics (by name position or static_argnums)
                for i, arg in enumerate(node.args):
                    pname = params[i] if i < len(params) else None
                    if (
                        i in static_nums or pname in static_names
                    ) and not checker.bounded(arg):
                        yield self.finding(
                            "unbucketed-static",
                            mod.sf,
                            node.lineno,
                            f"call to jitted '{callee}': static argument "
                            f"#{i} ('{pname or '?'}') varies per call "
                            f"(unbounded expression) — bucket it with "
                            f"_next_bucket or clamp it, or every event "
                            f"size compiles a fresh executable",
                        )
