"""Project-specific static analysis suite (docs/Analysis.md).

Twelve rule families encode this repo's invariants, sharing two pieces of
interprocedural infrastructure (v2.0 — "DeepFlow"): a whole-package call
graph (analysis/callgraph.py) and a light intraprocedural alias/escape
dataflow engine (analysis/dataflow.py) — plus, since v3.0, the ShapeFlow
abstract interpreter (analysis/shapeflow.py) that propagates symbolic
shapes, dtypes, and the INF-sentinel lattice through the traced kernel
set, seeded from @shape_contract annotations (utils/shape_contract.py).

  - trace-safety:    no host syncs / Python branches on traced values in
                     jax.jit-reachable code — reachability crosses module
                     boundaries via the call graph
  - thread-ownership: ctrl/monitor-reachable methods must not mutate
                     @owned_by module state without a declared handover —
                     alias-aware (`d = self.x; d[k] = v`) and
                     escape-aware (queue/thread handoffs)
  - device-transfer: no unsanctioned host syncs on values flowing out of
                     solver/jit dispatches (accounted *d2h* seams pass)
  - recompile-risk:  static jit arguments must be bucketed/bounded, never
                     a raw per-call len()
  - shard-spec:      in/out sharding-spec arity matches the wrapped
                     function; mesh axis names match the solver_mesh
                     vocabulary
  - blocking-call:   no synchronous blocking inside event-loop bodies
  - registry-drift:  counters/histograms, fault points, LogSample events,
                     DecisionConfigSection knobs AND the docs/Analysis.md
                     rule table match their code registries
  - shape-mismatch:  provable broadcast/rank conflicts, shape-contract
                     violations at call/return seams, unguarded tile
                     splits, unreserved frontier padding slots
  - sentinel-overflow: int32 adds of two maybe-INF values with no
                     dominating jnp.minimum(..., INF) clamp — the (min,+)
                     kernel hazard class
  - dtype-promotion: silent int->float promotion, bool masks in
                     arithmetic, int true division, float64 in traced code
  - resident-accounting: device-tagged self.* stores in the solver/apsp/
                     te packages must meet a device-memory ledger seam
                     in the same body — residency the observatory can't
                     see is invisible to watermarks and admission
  - collective-conformance: lax.ppermute/psum axis names checked against
                     the mesh axis vocabulary; ppermute perms must be
                     well-formed permutations

Run it:  python -m openr_tpu.analysis [paths] [--strict] [--json|--sarif]
         python -m openr_tpu.analysis --changed   (diff-scoped fast path)
         python -m openr_tpu.analysis --update-baseline
Tier-1:  tests/test_analysis.py self-runs the suite over openr_tpu/.
"""

from openr_tpu.analysis.core import (  # noqa: F401
    ANALYSIS_VERSION,
    LAST_RUN_STATS,
    AnalysisContext,
    Finding,
    RULES,
    Rule,
    build_context,
    render_json,
    render_sarif,
    render_text,
    rule_catalog,
    run_analysis,
    run_rules,
)

# importing the rule modules registers them in RULES
from openr_tpu.analysis import (  # noqa: F401  (registration side effect)
    blocking_calls,
    device_transfer,
    recompile_risk,
    registry_drift,
    resident_accounting,
    shard_spec,
    shapeflow,
    thread_ownership,
    trace_safety,
)


def rule_names():
    return [r["name"] for r in rule_catalog()]


def get_analysis_info() -> dict:
    """Metadata surfaced through utils/build_info.get_build_info and
    `breeze openr version`: deployed binaries report which invariants
    they were linted against, and — when an analysis ran in this process
    (the tier-1 self-run, a --changed pre-commit pass) — what it cost:
    per-rule finding counts and wall time, observable like every other
    cost in this codebase. When the run included the ShapeFlow pass, its
    contract/function counts ride along as analysis_contracts."""
    info = {
        "analysis_version": ANALYSIS_VERSION,
        "analysis_rules": rule_names(),
    }
    if LAST_RUN_STATS:
        info["analysis_wall_ms"] = LAST_RUN_STATS["wall_ms"]
        info["analysis_files"] = LAST_RUN_STATS["files"]
        info["analysis_rule_stats"] = {
            name: dict(stats)
            for name, stats in LAST_RUN_STATS["per_rule"].items()
        }
        if "shapeflow" in LAST_RUN_STATS:
            info["analysis_contracts"] = dict(LAST_RUN_STATS["shapeflow"])
    return info
