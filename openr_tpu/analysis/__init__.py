"""Project-specific static analysis suite (docs/Analysis.md).

Four rule families encode this repo's invariants:

  - trace-safety:    no host syncs / Python branches on traced values in
                     jax.jit-reachable solver code
  - thread-ownership: ctrl/monitor-reachable methods must not mutate
                     @owned_by module state without a declared handover
  - blocking-call:   no synchronous blocking inside event-loop bodies
  - registry-drift:  counters/histograms, fault points and
                     DecisionConfigSection knobs match their docs tables

Run it:  python -m openr_tpu.analysis [paths] [--strict] [--json]
Tier-1:  tests/test_analysis.py self-runs the suite over openr_tpu/.
"""

from openr_tpu.analysis.core import (  # noqa: F401
    ANALYSIS_VERSION,
    AnalysisContext,
    Finding,
    RULES,
    Rule,
    build_context,
    render_json,
    render_text,
    rule_catalog,
    run_analysis,
    run_rules,
)

# importing the rule modules registers them in RULES
from openr_tpu.analysis import (  # noqa: F401  (registration side effect)
    blocking_calls,
    registry_drift,
    thread_ownership,
    trace_safety,
)


def rule_names():
    return [r["name"] for r in rule_catalog()]


def get_analysis_info() -> dict:
    """Metadata surfaced through utils/build_info.get_build_info and
    `breeze openr version`: deployed binaries report which invariants
    they were linted against."""
    return {
        "analysis_version": ANALYSIS_VERSION,
        "analysis_rules": rule_names(),
    }
