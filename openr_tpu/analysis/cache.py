"""Persistent import-graph cache: `--changed` without a full re-parse.

A diff-scoped analysis run (`python -m openr_tpu.analysis --changed`) only
needs the package's *module dependency edges* to close over the touched
modules' dependents — but computing them used to read and parse every file
in the package on every invocation. This module persists exactly that
import surface, keyed by file content hash: per file, its sha256, dotted
module name, and the modules its import statements bind
(callgraph.scan_imports — the same edge definition
CallGraph.module_dependents walks, so the cached closure and the live one
cannot diverge). An unchanged file is a cache hit (one hash, zero parses);
an edited file re-parses and overwrites its entry. The cache file lives at
`<repo>/.analysis-cache.json` (gitignored), versioned so schema changes
invalidate it wholesale, and written best-effort — a read-only checkout
just re-parses.

Hit/miss counts surface in the `--changed` stderr note and, under
`--json`, as the `callgraph_cache` footer of the report.

The same file also persists ShapeFlow's inferred per-function summaries
(which parameters live in the sentinel domain), keyed per file-sha under a
`shapeflow` section. Both sections invalidate wholesale when either the
cache schema (CACHE_VERSION) or the analysis semantics (ANALYSIS_VERSION)
change; the shapeflow section additionally invalidates when any
@shape_contract annotation in the analyzed set is edited (contracts are
summary inputs — a changed contract changes every inference downstream of
the annotated callee).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

CACHE_VERSION = 1
CACHE_NAME = ".analysis-cache.json"


def _read_payload(cache_path: Optional[Path]) -> Dict:
    """The whole cache payload, or {} when missing, corrupt, or stale.
    Staleness covers both the cache schema (CACHE_VERSION) and the
    analysis semantics (ANALYSIS_VERSION): a rule-semantics bump must not
    serve summaries computed under the old semantics."""
    from openr_tpu.analysis.core import ANALYSIS_VERSION

    if cache_path is None or not cache_path.exists():
        return {}
    try:
        payload = json.loads(cache_path.read_text())
    except (OSError, ValueError):
        return {}
    if not isinstance(payload, dict):
        return {}
    if payload.get("version") != CACHE_VERSION:
        return {}
    if payload.get("analysis_version") != ANALYSIS_VERSION:
        return {}
    return payload


def _write_payload(cache_path: Path, payload: Dict) -> None:
    from openr_tpu.analysis.core import ANALYSIS_VERSION

    payload = dict(payload)
    payload["version"] = CACHE_VERSION
    payload["analysis_version"] = ANALYSIS_VERSION
    tmp = cache_path.with_name(cache_path.name + ".tmp")
    try:
        tmp.write_text(json.dumps(payload, sort_keys=True))
        os.replace(tmp, cache_path)
    except OSError:
        pass  # read-only checkout: next run re-parses, nothing breaks


def _module_name_of(rel: str) -> str:
    """Dotted module name of a package-relative posix path:
    openr_tpu/ops/spf.py -> openr_tpu.ops.spf; __init__.py collapses onto
    its package (same convention as callgraph.module_name)."""
    if rel.endswith(".py"):
        rel = rel[: -len(".py")]
    parts = rel.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _module_deps(tree: ast.AST) -> List[str]:
    """Modules this tree's import statements bind — the dependency edges
    module_dependents traverses (from-import source modules plus plain
    import aliases)."""
    from openr_tpu.analysis.callgraph import scan_imports

    from_imports, module_aliases = scan_imports(tree)
    deps: Set[str] = {mod for mod, _ in from_imports.values()}
    deps.update(module_aliases.values())
    return sorted(deps)


def load_import_graph(
    package: Path, cache_path: Optional[Path]
) -> Tuple[Dict[str, Dict], Dict[str, int]]:
    """The package's module dependency graph, served from the content-hash
    cache where possible. Returns ({module: {"path", "deps"}}, stats) with
    stats = {"hits", "misses", "files"}; when cache_path is set the cache
    file is rewritten with the refreshed entries (best-effort)."""
    payload = _read_payload(cache_path)
    entries: Dict[str, Dict] = payload.get("files", {})
    graph: Dict[str, Dict] = {}
    new_entries: Dict[str, Dict] = {}
    hits = misses = 0
    # module names must match the call graph's (rel-to-analysis-root), or
    # the cached closure and CallGraph.module_dependents would diverge
    from openr_tpu.analysis.core import _find_root

    root = _find_root([package])
    for path in sorted(package.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        try:
            data = path.read_bytes()
        except OSError:
            continue
        digest = hashlib.sha256(data).hexdigest()
        ent = entries.get(rel)
        if ent is not None and ent.get("hash") == digest:
            hits += 1
            module, deps = ent["module"], list(ent["deps"])
        else:
            misses += 1
            try:
                tree = ast.parse(data)
            except SyntaxError:
                continue  # core.build_context will report it; no edges
            module = _module_name_of(rel)
            deps = _module_deps(tree)
        new_entries[rel] = {"hash": digest, "module": module, "deps": deps}
        graph[module] = {"path": path, "rel": rel, "deps": deps}
    if cache_path is not None:
        payload["files"] = new_entries  # other sections ride along
        _write_payload(cache_path, payload)
    return graph, {"hits": hits, "misses": misses, "files": hits + misses}


def load_shapeflow_summaries(
    cache_path: Optional[Path],
    analysis_version: str,
    contracts_fp: str,
) -> Dict[str, Dict]:
    """Cached shapeflow inference summaries ({rel: {"hash", "functions"}}),
    valid only when the cache carries the current ANALYSIS_VERSION and the
    current contracts fingerprint — an edit to any @shape_contract
    invalidates every inferred summary."""
    payload = _read_payload(cache_path)
    if payload.get("analysis_version") not in (None, analysis_version):
        return {}
    section = payload.get("shapeflow")
    if not isinstance(section, dict):
        return {}
    if section.get("contracts_fp") != contracts_fp:
        return {}
    files = section.get("files")
    return files if isinstance(files, dict) else {}


def store_shapeflow_summaries(
    cache_path: Optional[Path],
    analysis_version: str,
    contracts_fp: str,
    summaries: Dict[str, Dict],
) -> None:
    """Merge this run's summaries into the cache (best-effort). Entries
    from a still-valid prior section are kept — a subset run must not
    evict summaries for files it did not analyze."""
    if cache_path is None:
        return
    payload = _read_payload(cache_path)
    merged: Dict[str, Dict] = {}
    prior = payload.get("shapeflow")
    if (
        isinstance(prior, dict)
        and prior.get("contracts_fp") == contracts_fp
        and isinstance(prior.get("files"), dict)
    ):
        merged.update(prior["files"])
    merged.update(summaries)
    payload["shapeflow"] = {"contracts_fp": contracts_fp, "files": merged}
    _write_payload(cache_path, payload)


def dependents_closure(
    graph: Dict[str, Dict], changed: Iterable[str]
) -> Set[str]:
    """Transitive closure of modules importing any of `changed` — the same
    traversal as CallGraph.module_dependents, on the cached edges."""
    importers: Dict[str, Set[str]] = {m: set() for m in graph}
    for mod, info in graph.items():
        for dep in info["deps"]:
            if dep in importers:
                importers[dep].add(mod)
    out: Set[str] = set()
    queue = [m for m in changed if m in graph]
    while queue:
        cur = queue.pop()
        if cur in out:
            continue
        out.add(cur)
        queue.extend(importers.get(cur, ()))
    return out


def changed_closure_cached(
    package: Path,
    changed_files: List[str],
    repo_root: Path,
    cache_path: Optional[Path] = None,
) -> Tuple[List[Path], Dict[str, int]]:
    """The `--changed` analysis set (touched package modules plus their
    call-graph dependents), computed from the persistent import-graph
    cache. Returns (paths sorted by module name, cache stats)."""
    if cache_path is None:
        cache_path = repo_root / CACHE_NAME
    graph, stats = load_import_graph(package, cache_path)
    by_path = {info["path"].resolve(): mod for mod, info in graph.items()}
    changed_modules = []
    for f in changed_files:
        mod = by_path.get((repo_root / f).resolve())
        if mod is not None:
            changed_modules.append(mod)
    if not changed_modules:
        return [], stats
    selected = dependents_closure(graph, changed_modules)
    paths = [
        graph[mod]["path"] for mod in sorted(graph) if mod in selected
    ]
    return paths, stats
