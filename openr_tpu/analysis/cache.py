"""Persistent import-graph cache: `--changed` without a full re-parse.

A diff-scoped analysis run (`python -m openr_tpu.analysis --changed`) only
needs the package's *module dependency edges* to close over the touched
modules' dependents — but computing them used to read and parse every file
in the package on every invocation. This module persists exactly that
import surface, keyed by file content hash: per file, its sha256, dotted
module name, and the modules its import statements bind
(callgraph.scan_imports — the same edge definition
CallGraph.module_dependents walks, so the cached closure and the live one
cannot diverge). An unchanged file is a cache hit (one hash, zero parses);
an edited file re-parses and overwrites its entry. The cache file lives at
`<repo>/.analysis-cache.json` (gitignored), versioned so schema changes
invalidate it wholesale, and written best-effort — a read-only checkout
just re-parses.

Hit/miss counts surface in the `--changed` stderr note and, under
`--json`, as the `callgraph_cache` footer of the report.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

CACHE_VERSION = 1
CACHE_NAME = ".analysis-cache.json"


def _module_name_of(rel: str) -> str:
    """Dotted module name of a package-relative posix path:
    openr_tpu/ops/spf.py -> openr_tpu.ops.spf; __init__.py collapses onto
    its package (same convention as callgraph.module_name)."""
    if rel.endswith(".py"):
        rel = rel[: -len(".py")]
    parts = rel.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _module_deps(tree: ast.AST) -> List[str]:
    """Modules this tree's import statements bind — the dependency edges
    module_dependents traverses (from-import source modules plus plain
    import aliases)."""
    from openr_tpu.analysis.callgraph import scan_imports

    from_imports, module_aliases = scan_imports(tree)
    deps: Set[str] = {mod for mod, _ in from_imports.values()}
    deps.update(module_aliases.values())
    return sorted(deps)


def load_import_graph(
    package: Path, cache_path: Optional[Path]
) -> Tuple[Dict[str, Dict], Dict[str, int]]:
    """The package's module dependency graph, served from the content-hash
    cache where possible. Returns ({module: {"path", "deps"}}, stats) with
    stats = {"hits", "misses", "files"}; when cache_path is set the cache
    file is rewritten with the refreshed entries (best-effort)."""
    entries: Dict[str, Dict] = {}
    if cache_path is not None and cache_path.exists():
        try:
            cached = json.loads(cache_path.read_text())
            if cached.get("version") == CACHE_VERSION:
                entries = cached.get("files", {})
        except (OSError, ValueError):
            entries = {}
    graph: Dict[str, Dict] = {}
    new_entries: Dict[str, Dict] = {}
    hits = misses = 0
    # module names must match the call graph's (rel-to-analysis-root), or
    # the cached closure and CallGraph.module_dependents would diverge
    from openr_tpu.analysis.core import _find_root

    root = _find_root([package])
    for path in sorted(package.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        try:
            data = path.read_bytes()
        except OSError:
            continue
        digest = hashlib.sha256(data).hexdigest()
        ent = entries.get(rel)
        if ent is not None and ent.get("hash") == digest:
            hits += 1
            module, deps = ent["module"], list(ent["deps"])
        else:
            misses += 1
            try:
                tree = ast.parse(data)
            except SyntaxError:
                continue  # core.build_context will report it; no edges
            module = _module_name_of(rel)
            deps = _module_deps(tree)
        new_entries[rel] = {"hash": digest, "module": module, "deps": deps}
        graph[module] = {"path": path, "rel": rel, "deps": deps}
    if cache_path is not None:
        _write_cache(cache_path, new_entries)
    return graph, {"hits": hits, "misses": misses, "files": hits + misses}


def _write_cache(cache_path: Path, entries: Dict[str, Dict]) -> None:
    payload = json.dumps(
        {"version": CACHE_VERSION, "files": entries}, sort_keys=True
    )
    tmp = cache_path.with_name(cache_path.name + ".tmp")
    try:
        tmp.write_text(payload)
        os.replace(tmp, cache_path)
    except OSError:
        pass  # read-only checkout: next run re-parses, nothing breaks


def dependents_closure(
    graph: Dict[str, Dict], changed: Iterable[str]
) -> Set[str]:
    """Transitive closure of modules importing any of `changed` — the same
    traversal as CallGraph.module_dependents, on the cached edges."""
    importers: Dict[str, Set[str]] = {m: set() for m in graph}
    for mod, info in graph.items():
        for dep in info["deps"]:
            if dep in importers:
                importers[dep].add(mod)
    out: Set[str] = set()
    queue = [m for m in changed if m in graph]
    while queue:
        cur = queue.pop()
        if cur in out:
            continue
        out.add(cur)
        queue.extend(importers.get(cur, ()))
    return out


def changed_closure_cached(
    package: Path,
    changed_files: List[str],
    repo_root: Path,
    cache_path: Optional[Path] = None,
) -> Tuple[List[Path], Dict[str, int]]:
    """The `--changed` analysis set (touched package modules plus their
    call-graph dependents), computed from the persistent import-graph
    cache. Returns (paths sorted by module name, cache stats)."""
    if cache_path is None:
        cache_path = repo_root / CACHE_NAME
    graph, stats = load_import_graph(package, cache_path)
    by_path = {info["path"].resolve(): mod for mod, info in graph.items()}
    changed_modules = []
    for f in changed_files:
        mod = by_path.get((repo_root / f).resolve())
        if mod is not None:
            changed_modules.append(mod)
    if not changed_modules:
        return [], stats
    selected = dependents_closure(graph, changed_modules)
    paths = [
        graph[mod]["path"] for mod in sorted(graph) if mod in selected
    ]
    return paths, stats
