"""Core of the project static-analysis suite.

A small AST-based lint framework encoding this repo's invariants — the
generalization of tests/test_counter_naming.py into a real analysis layer:

  - rules register themselves in RULES (one Rule per invariant family);
  - every rule sees the whole parsed file set (AnalysisContext), so
    project-wide rules (registry drift, ctrl-reachability) are as natural
    as per-file ones;
  - findings carry (rule, check, path, line, message, severity);
  - per-line suppression comments, a checked-in baseline file for waived
    legacy findings, and text/JSON reporters;
  - `ANALYSIS_STRICT=1` (or --strict) promotes advisory rules to errors.

Suppression syntax (docs/Analysis.md):
  # analysis: ignore               suppress every rule on this line
  # analysis: ignore[rule-name]    suppress one rule (comma-list allowed)
  # analysis: skip-file            near the top of a file: skip it entirely
The comment may sit on the flagged line or on the line directly above it.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# 2.0: interprocedural dataflow — whole-package call graph (cross-module
# trace-safety reachability), alias/escape-aware thread-ownership, and the
# device-transfer / recompile-risk / shard-spec rule families
# 3.0: ShapeFlow abstract interpretation — symbolic shape/dtype/sentinel
# propagation over the traced kernel set, @shape_contract seeding, and the
# shape-mismatch / sentinel-overflow / dtype-promotion /
# collective-conformance rule families
ANALYSIS_VERSION = "3.0.0"

# per-rule finding counts + wall time of the most recent run_analysis in
# this process — surfaced through utils/build_info.get_build_info so
# analysis cost rides ctrl getBuildInfo / `breeze openr version` like
# every other cost in this codebase
LAST_RUN_STATS: Dict = {}

_IGNORE_RE = re.compile(r"#\s*analysis:\s*ignore(?:\[([a-z0-9_,\- ]+)\])?")
_SKIP_FILE_RE = re.compile(r"#\s*analysis:\s*skip-file")
_SKIP_FILE_SCAN_LINES = 5  # skip-file must appear near the top


@dataclass
class Finding:
    rule: str  # rule family (registry name)
    check: str  # sub-check id within the family
    path: str  # path relative to the analysis root
    line: int
    message: str
    severity: str = "error"  # 'error' | 'advisory'

    def key(self) -> str:
        """Baseline identity: line numbers drift, messages are stable."""
        return f"{self.rule}\t{self.path}\t{self.message}"

    def to_dict(self) -> Dict:
        return {
            "rule": self.rule,
            "check": self.check,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "severity": self.severity,
        }


def walk_nodes(tree: ast.AST) -> tuple:
    """`tuple(ast.walk(tree))`, memoized on the tree object itself.

    Every rule sweeps the same 130 parsed modules; re-walking each tree
    per rule is the single largest cost of a full-package run. Rules
    never mutate trees, so the flat node tuple (same BFS order as
    ast.walk) is safe to share — it lives exactly as long as the tree.
    """
    cached = getattr(tree, "_openr_all_nodes", None)
    if cached is None:
        cached = tuple(ast.walk(tree))
        try:
            tree._openr_all_nodes = cached  # type: ignore[attr-defined]
        except AttributeError:
            pass  # slotted node type: fall through uncached
    return cached


@dataclass
class SourceFile:
    path: Path  # absolute
    rel: str  # root-relative, '/'-separated
    source: str
    tree: ast.AST
    lines: List[str]


@dataclass
class AnalysisContext:
    """Everything a rule may look at: the parsed file set plus the repo
    layout (docs for the registry-drift cross-checks)."""

    root: Path
    files: List[SourceFile] = field(default_factory=list)
    docs_dir: Optional[Path] = None
    # True when the scan covers the whole package; doc-completeness checks
    # (e.g. "documented counter is never emitted") only make sense then —
    # a single-file scan must not report the rest of the package as ghosts
    full_package: bool = False

    def file(self, rel_suffix: str) -> Optional[SourceFile]:
        for sf in self.files:
            if sf.rel.endswith(rel_suffix):
                return sf
        return None


class Rule:
    """One invariant family. Subclasses set name/description/severity and
    implement run(ctx) -> Iterable[Finding]."""

    name: str = ""
    description: str = ""
    severity: str = "error"  # default severity of this family's findings

    def run(self, ctx: AnalysisContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(
        self, check: str, sf: SourceFile, line: int, message: str
    ) -> Finding:
        return Finding(
            rule=self.name,
            check=check,
            path=sf.rel,
            line=line,
            message=message,
            severity=self.severity,
        )


RULES: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate + register a Rule."""
    rule = rule_cls()
    assert rule.name and rule.name not in RULES, rule.name
    RULES[rule.name] = rule
    return rule_cls


def rule_catalog() -> List[Dict[str, str]]:
    return [
        {
            "name": rule.name,
            "severity": rule.severity,
            "description": rule.description,
        }
        for rule in sorted(RULES.values(), key=lambda r: r.name)
    ]


# ---------------------------------------------------------------------------
# file collection
# ---------------------------------------------------------------------------


def _collect_py_files(paths: Sequence[Path]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            out.append(p)
    # dedupe, stable order
    seen = set()
    uniq = []
    for p in out:
        rp = p.resolve()
        if rp not in seen:
            seen.add(rp)
            uniq.append(rp)
    return uniq


def _find_root(paths: Sequence[Path]) -> Path:
    """The analysis root: the parent of the `openr_tpu` package when the
    scanned paths live inside one (so docs/ and the baseline resolve), else
    the common parent of the inputs."""
    for p in paths:
        q = p.resolve()
        for anc in [q] + list(q.parents):
            if anc.name == "openr_tpu" and (anc / "__init__.py").exists():
                return anc.parent
    first = paths[0].resolve()
    return first if first.is_dir() else first.parent


def build_context(
    paths: Sequence[Path], root: Optional[Path] = None
) -> AnalysisContext:
    files = _collect_py_files(paths)
    root = (root or _find_root(paths)).resolve()
    ctx = AnalysisContext(root=root)
    docs = root / "docs"
    if docs.is_dir():
        ctx.docs_dir = docs
    for path in files:
        try:
            source = path.read_text()
            tree = ast.parse(source, filename=str(path))
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue  # unparseable files are not this suite's business
        try:
            rel = path.relative_to(root).as_posix()
        except ValueError:
            rel = path.as_posix()
        ctx.files.append(
            SourceFile(
                path=path,
                rel=rel,
                source=source,
                tree=tree,
                lines=source.splitlines(),
            )
        )
    # whole-package scans carry the monitor module; doc-completeness
    # cross-checks key off it (see AnalysisContext.full_package)
    ctx.full_package = any(
        sf.rel.endswith("monitor/monitor.py") for sf in ctx.files
    )
    return ctx


# ---------------------------------------------------------------------------
# suppression + baseline
# ---------------------------------------------------------------------------


def _line_suppresses(line: str, rule: str) -> bool:
    m = _IGNORE_RE.search(line)
    if not m:
        return False
    if m.group(1) is None:
        return True
    rules = {r.strip() for r in m.group(1).split(",")}
    return rule in rules


def is_suppressed(sf: SourceFile, finding: Finding) -> bool:
    if any(
        _SKIP_FILE_RE.search(line)
        for line in sf.lines[:_SKIP_FILE_SCAN_LINES]
    ):
        return True
    idx = finding.line - 1
    for i in (idx, idx - 1):
        if 0 <= i < len(sf.lines) and _line_suppresses(
            sf.lines[i], finding.rule
        ):
            return True
    return False


def load_baseline(path: Optional[Path]) -> set:
    """Waived finding keys, one per line (tab-separated rule/path/message);
    '#' comments and blank lines ignored. The shipped baseline is empty —
    new waivers need a comment explaining why (docs/Analysis.md)."""
    if path is None or not path.exists():
        return set()
    keys = set()
    for line in path.read_text().splitlines():
        line = line.rstrip("\n")
        if not line.strip() or line.lstrip().startswith("#"):
            continue
        keys.add(line)
    return keys


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def run_rules(
    ctx: AnalysisContext,
    strict: bool = False,
    timings: Optional[Dict[str, float]] = None,
) -> Tuple[List[Finding], int]:
    """(kept findings, suppressed count). Suppressions apply per line;
    strict promotes advisory findings to errors. Pass a dict as `timings`
    to collect per-rule wall milliseconds (rule generators are drained
    inside the timed section)."""
    import time

    by_rel = {sf.rel: sf for sf in ctx.files}
    kept: List[Finding] = []
    suppressed = 0
    for rule in RULES.values():
        t0 = time.perf_counter()
        produced = list(rule.run(ctx))
        if timings is not None:
            timings[rule.name] = (time.perf_counter() - t0) * 1e3
        for finding in produced:
            sf = by_rel.get(finding.path)
            if sf is not None and is_suppressed(sf, finding):
                suppressed += 1
                continue
            if strict and finding.severity == "advisory":
                finding.severity = "error"
            kept.append(finding)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return kept, suppressed


def run_analysis(
    paths: Sequence[Path],
    root: Optional[Path] = None,
    strict: bool = False,
    baseline_path: Optional[Path] = None,
) -> Dict:
    """End-to-end run: returns a result dict (findings, counts, exit code).

    Exit semantics: non-zero iff any non-baselined error-severity finding
    remains, or (on full-package scans) the baseline carries a STALE entry
    — a waived key no rule produces anymore. A stale waiver means the debt
    it marked was paid (or the message drifted): the baseline must be
    regenerated (`--update-baseline`) so it never shadows a future
    regression with the same key. Advisory findings are reported but do
    not fail the run unless strict mode promoted them.
    """
    import time

    t_start = time.perf_counter()
    ctx = build_context(paths, root=root)
    timings: Dict[str, float] = {}
    findings, suppressed = run_rules(ctx, strict=strict, timings=timings)
    baseline = load_baseline(baseline_path)
    baselined = [f for f in findings if f.key() in baseline]
    active = [f for f in findings if f.key() not in baseline]
    # stale-waiver check: only meaningful when the scan could have
    # reproduced every waived finding, i.e. the whole package is in scope
    if ctx.full_package:
        produced_keys = {f.key() for f in findings}
        for key in sorted(baseline - produced_keys):
            rule = key.split("\t", 1)[0]
            active.append(
                Finding(
                    rule="baseline",
                    check="stale-entry",
                    path=(
                        baseline_path.name
                        if baseline_path is not None
                        else "analysis-baseline.txt"
                    ),
                    line=1,
                    message=(
                        f"stale baseline entry (no '{rule}' finding "
                        f"produces this key anymore): {key!r} — "
                        f"regenerate with --update-baseline"
                    ),
                )
            )
    errors = [f for f in active if f.severity == "error"]
    per_rule: Dict[str, Dict] = {}
    for name in sorted(RULES):
        per_rule[name] = {
            "findings": sum(1 for f in active if f.rule == name),
            "ms": round(timings.get(name, 0.0), 3),
        }
    wall_ms = (time.perf_counter() - t_start) * 1e3
    result = {
        "version": ANALYSIS_VERSION,
        "rules": [r["name"] for r in rule_catalog()],
        "files": len(ctx.files),
        "findings": active,
        "errors": len(errors),
        "advisories": len(active) - len(errors),
        "suppressed": suppressed,
        "baselined": len(baselined),
        "per_rule": per_rule,
        "wall_ms": round(wall_ms, 3),
        "full_package": ctx.full_package,
        "exit_code": 1 if errors else 0,
    }
    LAST_RUN_STATS.clear()
    LAST_RUN_STATS.update(
        {
            "wall_ms": result["wall_ms"],
            "files": result["files"],
            "per_rule": per_rule,
        }
    )
    try:  # shapeflow pass stats (contract/function counts) ride along
        from openr_tpu.analysis.shapeflow import LAST_SHAPEFLOW_STATS

        if LAST_SHAPEFLOW_STATS:
            LAST_RUN_STATS["shapeflow"] = dict(LAST_SHAPEFLOW_STATS)
    except ImportError:  # pragma: no cover - shapeflow always ships
        pass
    return result


# ---------------------------------------------------------------------------
# reporters
# ---------------------------------------------------------------------------


def render_text(result: Dict) -> str:
    out = []
    for f in result["findings"]:
        out.append(
            f"{f.path}:{f.line}: [{f.rule}/{f.check}] "
            f"{f.severity}: {f.message}"
        )
    out.append(
        f"analysis v{result['version']}: {result['files']} files, "
        f"{result['errors']} error(s), {result['advisories']} advisory, "
        f"{result['suppressed']} suppressed, "
        f"{result['baselined']} baselined"
        + (
            f", {result['wall_ms']:.0f} ms"
            if "wall_ms" in result
            else ""
        )
    )
    return "\n".join(out)


def render_json(result: Dict) -> str:
    payload = dict(result)
    payload["findings"] = [f.to_dict() for f in result["findings"]]
    return json.dumps(payload, indent=2, sort_keys=True)


def render_sarif(result: Dict) -> str:
    """SARIF 2.1.0 rendering of a run, so findings annotate diffs in CI.

    Only the reporting format changes: the finding set, severities, and
    the exit-code contract are exactly those of --json / text output.
    Advisory findings map to SARIF "warning", errors to "error"."""
    rules = [
        {
            "id": r["name"],
            "shortDescription": {"text": r["description"]},
            "defaultConfiguration": {
                "level": "error" if r["severity"] == "error" else "warning",
            },
        }
        for r in rule_catalog()
    ]
    results = [
        {
            "ruleId": f.rule,
            "level": "error" if f.severity == "error" else "warning",
            "message": {"text": f"[{f.check}] {f.message}"},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {"startLine": max(f.line, 1)},
                    }
                }
            ],
        }
        for f in result["findings"]
    ]
    payload = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
            "master/Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "openr-tpu-analysis",
                        "version": ANALYSIS_VERSION,
                        "informationUri": "docs/Analysis.md",
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(payload, indent=2, sort_keys=True)


# ---------------------------------------------------------------------------
# shared AST helpers (used by several rules)
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains (Call links descend through the
    callee, so `self.kvstore.db(area).set_key_vals` roots at self)."""
    parts: List[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        else:
            return None


def call_name(node: ast.Call) -> Optional[str]:
    """Bare or attribute call name: f(...) -> 'f', a.b.f(...) -> 'f'."""
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None
