"""device-transfer: no unsanctioned host syncs on device-resident arrays.

Trace-safety polices code *inside* jit; this rule polices the host side of
the seam. The solver stack's whole performance story is that distance
state stays device-resident between events (docs/Decision.md DeltaPath):
an `np.asarray(...)` / `.item()` / `float(...)` / Python iteration over a
value that flowed out of a solver dispatch is a synchronous device->host
copy on the hot path — the exact bug class the [S, n_pad] mirror fetch
was redesigned to avoid.

Mechanics (callgraph + dataflow):
  - *producers* are resolved through the package call graph
    (analysis/callgraph.py): module-level jit bindings (`@jax.jit` defs,
    `X = jax.jit(f, ...)`), solver factories (functions returning a jit
    callable — `fn = _sell_solver(key); d = fn(...)`), and functions whose
    return value flows out of one of those (`batched_spf`).
  - the producer set crosses **method boundaries inside a class**: a
    per-class fixpoint learns (a) *device attributes* — `self._d_dev =
    fn(...)`-style stores of device-tagged values (tuple unpacking
    included), after which every `self._d_dev` load is device-tagged in
    every method — and (b) *device-returning methods* — `return` of a
    device-tagged value, after which `self._solve_resident(...)` call
    sites are producers too. This closes the ROADMAP carry-over where
    `self._d_dev` was only covered because its consumers happened to
    account bytes.
  - the alias tracker (analysis/dataflow.py) follows the producer's value
    through local bindings, tuple unpacking (`d, rounds = fn(...)`), and
    sub-object loads, then reports host syncs with the flow chain in the
    message.
  - traced functions are excluded — host syncs inside them are
    trace-safety findings, not transfer findings.

Sanctioned seams — "whitelisted by construction": a function that
accounts its copy-back into a `*d2h*` transfer counter in the same body
(`self.d2h_bytes += xfer`, the DeltaPath compacted-extraction contract) is
a deliberate, *measured* seam and is skipped whole. The rule therefore
enforces a real invariant: every host sync on solver output is either
accounted where it happens or explicitly waived with a comment.

Note `int(...)` is deliberately NOT a sync trigger: the 4-byte scalar
reads the warm path is designed around (`int(num_changed)`,
`int(rounds)`) are the sanctioned way to size a compacted fetch.
"""

from __future__ import annotations

import ast
from typing import Optional, Set, Tuple

from openr_tpu.analysis.callgraph import build_callgraph
from openr_tpu.analysis.core import (
    AnalysisContext,
    Rule,
    call_name,
    dotted_name,
    register,
    walk_nodes,
)
from openr_tpu.analysis.dataflow import AliasTracker, alias_chain_text
from openr_tpu.analysis.trace_safety import (
    _numpy_aliases,
    traced_function_infos,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


def _accounts_transfer(fn) -> bool:
    """True when the function accounts device->host bytes in its own body:
    an (aug-)assignment to an attribute or local whose name mentions d2h
    (`self.d2h_bytes += xfer` — the 'sanctioned seam' contract the
    DeltaPath extraction established; free functions hand a `d2h_bytes`
    local to their caller's counters instead)."""
    for node in walk_nodes(fn):
        target = None
        if isinstance(node, ast.AugAssign):
            target = node.target
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        if isinstance(target, ast.Attribute) and "d2h" in target.attr.lower():
            return True
        if isinstance(target, ast.Name) and "d2h" in target.id.lower():
            return True
    return False


class _ClassDeviceEnv:
    """Per-class device-producer facts learned by fixpoint."""

    __slots__ = ("device_attrs", "device_methods")

    def __init__(self) -> None:
        self.device_attrs: Set[str] = set()
        self.device_methods: Set[str] = set()


def _attr_classifier(env: Optional[_ClassDeviceEnv]):
    if env is None:
        return None

    def classify_attr(attr: str):
        if attr in env.device_attrs:
            return ("device", f"self.{attr}")
        return None

    return classify_attr


def _with_class_env(classify, env: Optional[_ClassDeviceEnv]):
    """Extend the module-level producer classifier with the class's
    device-returning methods: `self._solve_resident(...)` is a producer
    once the fixpoint saw the method return a device value."""
    if env is None:
        return classify

    def combined(call: ast.Call):
        base = classify(call)
        if base is not None:
            return base
        if isinstance(call.func, ast.Attribute):
            chain = dotted_name(call.func)
            if chain and chain.startswith("self."):
                name = chain[len("self."):]
                if name in env.device_methods:
                    return ("device", f"{chain}(...)")
        return None

    return combined


def _class_device_env(
    cls: ast.ClassDef, classify, np_aliases
) -> _ClassDeviceEnv:
    """Fixpoint over the class's methods: an attribute stored from a
    device-tagged value becomes a device attribute (its loads are then
    device-tagged everywhere in the class); a method returning a
    device-tagged value becomes a device producer (its `self.` call
    sites then tag their results). Iterates until neither set grows —
    bounded by #attrs + #methods."""
    env = _ClassDeviceEnv()
    methods = [n for n in cls.body if isinstance(n, _FuncDef)]
    changed = True
    while changed:
        changed = False
        for fn in methods:
            tracker = AliasTracker(
                fn,
                classify_call=_with_class_env(classify, env),
                np_aliases=np_aliases,
                classify_attr=_attr_classifier(env),
            ).run()
            for _, attr, tags in tracker.attr_stores:
                if attr not in env.device_attrs and any(
                    t.tag[0] == "device" for t in tags
                ):
                    env.device_attrs.add(attr)
                    changed = True
            if fn.name not in env.device_methods and any(
                any(t.tag[0] == "device" for t in tags)
                for _, tags in tracker.returns
            ):
                env.device_methods.add(fn.name)
                changed = True
    return env


@register
class DeviceTransferRule(Rule):
    name = "device-transfer"
    severity = "error"
    description = (
        "host syncs (np.asarray/.item()/float()/iteration) on values that "
        "flow from solver/jit outputs must happen in sanctioned seams "
        "(functions accounting *d2h* transfer bytes) or carry a waiver"
    )

    def run(self, ctx: AnalysisContext):
        cg = build_callgraph(ctx)
        traced, _ = traced_function_infos(ctx)
        traced_nodes = {id(fi.node) for fi in traced}
        for mod in cg.modules.values():
            np_aliases = _numpy_aliases(mod.sf.tree)

            def classify(call: ast.Call) -> Optional[Tuple[str, str]]:
                func = call.func
                if isinstance(func, ast.Name):
                    kind = cg.resolve_producer(mod, func.id)
                    if kind in ("jit", "device"):
                        return ("device", f"{func.id}(...)")
                    if kind == "factory":
                        return ("jit", func.id)
                elif isinstance(func, ast.Attribute):
                    chain = dotted_name(func)
                    if chain and not chain.startswith("self."):
                        kind = cg.resolve_producer_chain(mod, chain)
                        if kind in ("jit", "device"):
                            return ("device", f"{chain}(...)")
                        if kind == "factory":
                            return ("jit", chain)
                elif isinstance(func, ast.Call):
                    inner = call_name(func)
                    if (
                        inner
                        and cg.resolve_producer(mod, inner) == "factory"
                    ):
                        return ("device", f"{inner}(...)(...)")
                return None

            # per-class producer fixpoint: device attributes + methods
            # whose returns carry device values (the past-function-
            # boundary extension); methods map onto their class env
            method_env: dict = {}
            for cls in walk_nodes(mod.sf.tree):
                if isinstance(cls, ast.ClassDef):
                    env = _class_device_env(cls, classify, np_aliases)
                    for node in cls.body:
                        if isinstance(node, _FuncDef):
                            method_env[id(node)] = env

            for infos in mod.by_name.values():
                for fi in infos:
                    if id(fi.node) in traced_nodes:
                        continue  # trace-safety's jurisdiction
                    if fi.parent is not None and id(
                        fi.parent.node
                    ) in traced_nodes:
                        continue
                    if _accounts_transfer(fi.node):
                        continue  # sanctioned seam, by construction
                    env = method_env.get(id(fi.node))
                    tracker = AliasTracker(
                        fi.node,
                        classify_call=_with_class_env(classify, env),
                        np_aliases=np_aliases,
                        classify_attr=_attr_classifier(env),
                    ).run()
                    for sync in tracker.syncs:
                        check = (
                            "device-iteration"
                            if "iteration" in sync.desc
                            else "host-sync"
                        )
                        flow = alias_chain_text(sync.alias)
                        yield self.finding(
                            check,
                            mod.sf,
                            sync.line,
                            f"'{fi.name}': {sync.desc} forces a "
                            f"device->host copy of a solver output "
                            f"({flow}) outside a sanctioned seam — "
                            f"account the bytes into a *d2h* counter, "
                            f"move it behind an accounted fetch, or "
                            f"waive with a comment",
                        )
