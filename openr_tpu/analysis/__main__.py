"""CLI driver: python -m openr_tpu.analysis [paths ...]

Exit code 0 when no (non-baselined) error-severity findings remain, 1
otherwise. With no paths, analyzes the installed openr_tpu package —
`python -m openr_tpu.analysis` from a checkout is the pre-PR gate
(docs/DeveloperGuide.md). `ANALYSIS_STRICT=1` (or --strict) promotes
advisory rules (thread-ownership) to errors for local runs.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

from openr_tpu.analysis import (
    ANALYSIS_VERSION,
    render_json,
    render_text,
    rule_catalog,
    run_analysis,
)

BASELINE_NAME = "analysis-baseline.txt"


def _default_package() -> Path:
    return Path(__file__).resolve().parent.parent  # the openr_tpu package


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m openr_tpu.analysis",
        description="openr-tpu project static analysis suite",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        type=Path,
        help="files/directories to analyze (default: the openr_tpu "
        "package)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable report"
    )
    parser.add_argument(
        "--strict",
        action="store_true",
        help="promote advisory rules to errors (also: ANALYSIS_STRICT=1)",
    )
    parser.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help=f"waived-findings file (default: <repo>/{BASELINE_NAME})",
    )
    parser.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline file",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--version", action="store_true", help="print the suite version"
    )
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.version:
        print(f"openr-tpu analysis v{ANALYSIS_VERSION}")
        return 0
    if args.list_rules:
        for rule in rule_catalog():
            print(
                f"{rule['name']:<18} [{rule['severity']}] "
                f"{rule['description']}"
            )
        return 0
    paths = args.paths or [_default_package()]
    strict = args.strict or os.environ.get("ANALYSIS_STRICT", "") == "1"
    baseline = args.baseline
    if baseline is None and not args.no_baseline:
        # resolve <repo>/analysis-baseline.txt next to the package
        from openr_tpu.analysis.core import _find_root

        candidate = _find_root(paths) / BASELINE_NAME
        if candidate.exists():
            baseline = candidate
    if args.no_baseline:
        baseline = None
    result = run_analysis(paths, strict=strict, baseline_path=baseline)
    print(render_json(result) if args.json else render_text(result))
    return result["exit_code"]


if __name__ == "__main__":
    sys.exit(main())
