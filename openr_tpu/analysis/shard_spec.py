"""shard-spec: sharding specs must match the functions they wrap.

Two drift modes bite multi-chip code and surface only at trace time (or
worse, as silent resharding):

  - arity drift: `jax.jit(solve, in_shardings=(row, repl, repl))` where
    `solve` takes four arguments — adding a solver operand without
    extending the spec tuple raises deep inside pjit with a message that
    names neither the function nor the missing leaf. Same for
    `out_shardings` vs. the returned tuple, and `shard_map`'s
    in_specs/out_specs.
  - axis-vocabulary drift: every PartitionSpec axis name and
    `mesh.shape["..."]` lookup must come from the solver-mesh axis
    vocabulary (`make_mesh`'s axis_names — 'batch'/'graph' in this repo).
    A typo'd axis name (`P('batchs')`) resolves to nothing until a run on
    a real multi-chip mesh dies.

Resolution: the wrapped function is found through the package call graph
(local defs — nearest preceding def for same-name shadowing — imported
names, and `factory(...)` operands via the factory's returned nested
def). Only literal tuple/list specs are checked; computed specs
(`shardings + (extra,)`) are skipped — precision over recall. The axis
vocabulary is read from the scanned set itself (the `axis_names` default
of a `make_mesh` def, literal `Mesh(..., ('batch', 'graph'))`
constructions, and literal `axis_names=` kwargs); when no vocabulary is
in scope (single-file scans of consumer modules) the axis check disarms.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from openr_tpu.analysis.callgraph import (
    build_callgraph,
    returned_local_defs,
)
from openr_tpu.analysis.core import (
    AnalysisContext,
    Rule,
    call_name,
    dotted_name,
    register,
    walk_nodes,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

_IN_SPEC_KWARGS = ("in_shardings", "in_specs")
_OUT_SPEC_KWARGS = ("out_shardings", "out_specs")
_WRAPPER_CALLS = ("jit", "shard_map")


def _partition_spec_aliases(tree: ast.AST) -> Set[str]:
    """Local names bound to jax.sharding.PartitionSpec ('P' by idiom)."""
    aliases: Set[str] = set()
    for node in walk_nodes(tree):
        if isinstance(node, ast.ImportFrom) and node.module and (
            node.module.endswith("sharding") or node.module == "jax"
        ):
            for a in node.names:
                if a.name == "PartitionSpec":
                    aliases.add(a.asname or a.name)
    return aliases


def mesh_axis_vocabulary(ctx: AnalysisContext) -> Set[str]:
    """Axis names the scanned set itself declares: make_mesh's axis_names
    default, literal Mesh(..., (names)) constructions, and literal
    axis_names= kwargs anywhere."""
    vocab: Set[str] = set()
    for sf in ctx.files:
        for node in walk_nodes(sf.tree):
            if isinstance(node, _FuncDef) and node.name == "make_mesh":
                args = node.args
                names = args.posonlyargs + args.args + args.kwonlyargs
                defaults = list(args.defaults)
                kw_defaults = list(args.kw_defaults)
                pos = args.posonlyargs + args.args
                for a, d in zip(pos[len(pos) - len(defaults):], defaults):
                    if a.arg == "axis_names":
                        vocab.update(_const_strs(d))
                for a, d in zip(args.kwonlyargs, kw_defaults):
                    if a.arg == "axis_names" and d is not None:
                        vocab.update(_const_strs(d))
            elif isinstance(node, ast.Call):
                name = call_name(node)
                if name == "Mesh" and len(node.args) >= 2:
                    vocab.update(_const_strs(node.args[1]))
                for kw in node.keywords:
                    if kw.arg == "axis_names":
                        vocab.update(_const_strs(kw.value))
    return vocab


def _const_strs(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        return [
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        ]
    return []


def _spec_len(node: ast.AST) -> Optional[int]:
    """Length of a literal tuple/list spec; None when computed."""
    if isinstance(node, (ast.Tuple, ast.List)):
        return len(node.elts)
    return None


def _positional_arity(fn) -> Optional[range]:
    """Acceptable in-spec arities: a range covering optional defaults;
    None when *args makes the arity open."""
    args = fn.args
    if args.vararg is not None:
        return None
    names = [a.arg for a in args.posonlyargs + args.args if a.arg != "self"]
    n = len(names)
    ndefault = len(args.defaults)
    return range(n - ndefault, n + 1)


def _return_arity(fn) -> Optional[int]:
    """Consistent tuple-return length of a def; None when mixed/opaque."""
    lengths: Set[int] = set()
    for node in walk_nodes(fn):
        if isinstance(node, _FuncDef) and node is not fn:
            continue
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Tuple):
                lengths.add(len(node.value.elts))
            else:
                return None
    # only descend this function's own returns (walk enters nested defs;
    # redo shallowly)
    lengths = set()
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, _FuncDef):
            continue
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Tuple):
                lengths.add(len(node.value.elts))
            else:
                return None
        stack.extend(
            c
            for c in ast.iter_child_nodes(node)
            if not isinstance(c, _FuncDef)
        )
    return lengths.pop() if len(lengths) == 1 else None


@register
class ShardSpecRule(Rule):
    name = "shard-spec"
    severity = "error"
    description = (
        "in_shardings/in_specs arity must match the wrapped function's "
        "signature (out specs vs. returned tuple), and PartitionSpec/"
        "mesh.shape axis names must come from the solver_mesh vocabulary"
    )

    def run(self, ctx: AnalysisContext):
        cg = build_callgraph(ctx)
        vocab = mesh_axis_vocabulary(ctx)
        for mod in cg.modules.values():
            sf = mod.sf
            p_aliases = _partition_spec_aliases(sf.tree)
            for node in walk_nodes(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                yield from self._check_axis_names(
                    sf, node, p_aliases, vocab
                )
                if call_name(node) in _WRAPPER_CALLS:
                    yield from self._check_arity(cg, mod, node)
            if vocab:
                for axis, line in mesh_shape_subscripts(sf.tree):
                    if axis not in vocab:
                        yield self.finding(
                            "unknown-mesh-axis",
                            sf,
                            line,
                            f"mesh.shape['{axis}'] is not in the "
                            f"solver_mesh axis vocabulary "
                            f"({', '.join(sorted(vocab))})",
                        )

    # -- axis vocabulary -------------------------------------------------

    def _check_axis_names(self, sf, node: ast.Call, p_aliases, vocab):
        if not vocab:
            return  # no declaration in scope: cannot judge
        name = call_name(node)
        root = dotted_name(node.func) or ""
        if name in p_aliases or root in p_aliases or (
            name == "PartitionSpec"
        ):
            for arg in node.args:
                for axis in _const_strs(arg):
                    if axis not in vocab:
                        yield self.finding(
                            "unknown-mesh-axis",
                            sf,
                            node.lineno,
                            f"PartitionSpec axis '{axis}' is not in the "
                            f"solver_mesh axis vocabulary "
                            f"({', '.join(sorted(vocab))})",
                        )

    # -- arity -----------------------------------------------------------

    def _check_arity(self, cg, mod, call: ast.Call):
        target = self._resolve_wrapped(cg, mod, call)
        if target is None:
            return
        for kw in call.keywords:
            if kw.arg in _IN_SPEC_KWARGS:
                got = _spec_len(kw.value)
                if got is None:
                    continue
                want = _positional_arity(target)
                if want is not None and got not in want:
                    yield self.finding(
                        "spec-arity",
                        mod.sf,
                        call.lineno,
                        f"{kw.arg} has {got} entries but "
                        f"'{target.name}' takes "
                        f"{want.start if len(want) == 1 else f'{want.start}..{want.stop - 1}'} "
                        f"positional argument(s)",
                    )
            elif kw.arg in _OUT_SPEC_KWARGS:
                got = _spec_len(kw.value)
                if got is None:
                    continue
                ret = _return_arity(target)
                if ret is not None and got != ret:
                    yield self.finding(
                        "spec-arity",
                        mod.sf,
                        call.lineno,
                        f"{kw.arg} has {got} entries but "
                        f"'{target.name}' returns a {ret}-tuple",
                    )

    def _resolve_wrapped(self, cg, mod, call: ast.Call):
        """The wrapped def of a jit/shard_map call, or None. Name operands
        prefer the nearest preceding same-file def (shadowing-safe), then
        imports; Call operands resolve through factory returns."""
        if not call.args:
            return None
        op = call.args[0]
        if isinstance(op, ast.Name):
            local = mod.by_name.get(op.id, [])
            preceding = [
                fi for fi in local if fi.node.lineno < call.lineno
            ]
            if preceding:
                return max(preceding, key=lambda fi: fi.node.lineno).node
            if local:
                return None  # only defs after the call: ambiguous
            for fi in cg.resolve_call_defs(
                mod, ast.Call(func=op, args=[], keywords=[])
            ):
                return fi.node
            return None
        if isinstance(op, ast.Call):
            for fi in cg.resolve_call_defs(mod, op):
                rets = returned_local_defs(fi.node)
                if len(rets) == 1:
                    return rets[0]
            return None
        if isinstance(op, ast.Attribute):
            chain = dotted_name(op)
            if chain:
                for fi in cg.resolve_call_defs(
                    mod, ast.Call(func=op, args=[], keywords=[])
                ):
                    return fi.node
        return None


def mesh_shape_subscripts(tree: ast.AST):
    """(axis, line) of every mesh.shape['axis'] lookup in a module."""
    for node in walk_nodes(tree):
        if (
            isinstance(node, ast.Subscript)
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "shape"
            and "mesh" in (dotted_name(node.value) or "").lower()
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)
        ):
            yield node.slice.value, node.lineno
