"""Whole-package call graph: the shared skeleton of the deep rules.

Every interprocedural rule in this suite (cross-module trace-safety
reachability, device-transfer flow classification, recompile-risk static
argument resolution, `--changed` dependent selection) needs the same three
facts about the scanned file set:

  1. which function definitions exist, per module (including nested and
     method defs, with their qualified names);
  2. how a *name used in a call* resolves to those definitions — same-module
     simple names (the existing per-file behavior), `from X import f`
     bindings, and `import X as y; y.f(...)` attribute chains;
  3. which module-level names are *jit artifacts*: defs decorated with a
     trace-entry transform, `X = jax.jit(f, ...)` bindings, factory
     functions whose return value is a jit callable
     (`def _sell_solver(key): return jax.jit(solve)`), and functions whose
     return value is a device array because it flows out of one of the
     above (`def batched_spf(...): return sell_fixpoint(...)`).

Resolution is name-based and import-directed: a cross-module edge exists
only when an import statement links the caller's name to the callee's
module, so unrelated same-named helpers in different modules never alias
each other (the precision lesson from the per-file rule generation).
Everything here is an over-approximation in the direction each rule wants:
trace-safety wants "possibly traced" (union over candidates), the transfer
rules want "definitely a device producer" (resolution misses degrade to
silence, not noise).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from openr_tpu.analysis.core import (
    AnalysisContext,
    SourceFile,
    call_name,
    dotted_name,
    walk_nodes,
)

_FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

# calls whose function-valued operand traces: jit/shard_map compile their
# operand; grad/value_and_grad/vmap trace theirs on every (re)trace
TRACE_ENTRY_CALLS = ("jit", "shard_map", "grad", "value_and_grad", "vmap")
# the subset that returns a *compiled callable* (a jit artifact a factory
# can hand back to its caller)
_JIT_WRAPPER_CALLS = ("jit", "shard_map")


def module_name(sf: SourceFile) -> str:
    """Dotted module name of a SourceFile: openr_tpu/ops/spf.py ->
    openr_tpu.ops.spf; package __init__.py collapses onto the package."""
    rel = sf.rel
    if rel.endswith(".py"):
        rel = rel[: -len(".py")]
    parts = rel.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclass
class FunctionInfo:
    """One function definition, located in the package."""

    qname: str  # '<module>::Outer.inner' dotted nesting path
    name: str  # simple name
    module: str
    sf: SourceFile
    node: ast.AST  # the FunctionDef / AsyncFunctionDef
    in_class: bool = False  # lexically inside a ClassDef (a method)
    parent: Optional["FunctionInfo"] = None  # lexically enclosing function

    def __hash__(self):  # identity hashing: defs are unique AST nodes
        return id(self.node)

    def __eq__(self, other):
        return isinstance(other, FunctionInfo) and other.node is self.node


@dataclass
class ModuleInfo:
    name: str
    sf: SourceFile
    # local binding -> (source module, source name) from `from X import a`
    from_imports: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    # local alias -> module dotted name from `import X [as y]`
    module_aliases: Dict[str, str] = field(default_factory=dict)
    # simple name -> defs carrying it anywhere in the module (collisions
    # kept: per-file trace reachability intentionally unions over them)
    by_name: Dict[str, List[FunctionInfo]] = field(default_factory=dict)
    # module-level (top-of-module) defs by name — the importable surface
    top_level: Dict[str, FunctionInfo] = field(default_factory=dict)
    # module-level names bound to jit callables: decorated defs and
    # `X = jax.jit(f, ...)` assignments
    jit_bindings: Set[str] = field(default_factory=set)
    # module-level defs that RETURN a jit callable (solver factories)
    factories: Set[str] = field(default_factory=set)
    # module-level defs that return a device value (flow fixpoint)
    device_fns: Set[str] = field(default_factory=set)


def _is_trace_entry_call(call: ast.Call, names=TRACE_ENTRY_CALLS) -> bool:
    return call_name(call) in names


def _jit_decorated(fn) -> bool:
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        base = dotted_name(target) or ""
        if base.split(".")[-1] in TRACE_ENTRY_CALLS:
            return True
        if isinstance(dec, ast.Call):
            # functools.partial(jax.jit, ...) and friends
            for arg in dec.args:
                nm = dotted_name(arg) or ""
                if nm.split(".")[-1] in TRACE_ENTRY_CALLS:
                    return True
    return False


def returned_local_defs(fn: ast.AST) -> List[ast.AST]:
    """Nested defs this function returns by bare name — the factory shape
    `def factory(key): def solve(...): ...; return solve` (ops/spf.py's
    `_sell_solver_raw`). Used to seed tracing through
    `jax.jit(factory(...), ...)` call sites."""
    nested = {
        n.name: n
        for n in walk_nodes(fn)
        if isinstance(n, _FuncDef) and n is not fn
    }
    out: List[ast.AST] = []
    for node in walk_nodes(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Name):
            target = nested.get(node.value.id)
            if target is not None:
                out.append(target)
    return out


def scan_imports(
    tree: ast.AST,
) -> Tuple[Dict[str, Tuple[str, str]], Dict[str, str]]:
    """(from_imports, module_aliases) of one module tree — the import
    surface both the call graph and the persistent `--changed` cache
    (openr_tpu/analysis/cache.py) key their dependency edges on."""
    from_imports: Dict[str, Tuple[str, str]] = {}
    module_aliases: Dict[str, str] = {}
    for node in walk_nodes(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.level == 0:
                for a in node.names:
                    from_imports[a.asname or a.name] = (
                        node.module,
                        a.name,
                    )
        elif isinstance(node, ast.Import):
            for a in node.names:
                alias = a.asname or a.name.split(".")[0]
                # `import a.b.c` binds `a`; `import a.b.c as x` binds
                # x -> a.b.c. Attribute-chain resolution re-joins the
                # full path either way.
                module_aliases[alias] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
    return from_imports, module_aliases


class CallGraph:
    """Package-wide function index + import-directed call resolution."""

    def __init__(self, ctx: AnalysisContext):
        self.ctx = ctx
        self.modules: Dict[str, ModuleInfo] = {}
        self._fn_by_node: Dict[int, FunctionInfo] = {}
        for sf in ctx.files:
            self._index_module(sf)
        self._classify_jit_artifacts()

    # -- indexing --------------------------------------------------------

    def _index_module(self, sf: SourceFile) -> None:
        mod = ModuleInfo(name=module_name(sf), sf=sf)
        self.modules[mod.name] = mod
        mod.from_imports, mod.module_aliases = scan_imports(sf.tree)

        def index_defs(parent: ast.AST, prefix: str, in_class: bool,
                       enclosing: Optional[FunctionInfo]) -> None:
            for child in ast.iter_child_nodes(parent):
                if isinstance(child, _FuncDef):
                    qname = f"{mod.name}::{prefix}{child.name}"
                    info = FunctionInfo(
                        qname=qname,
                        name=child.name,
                        module=mod.name,
                        sf=sf,
                        node=child,
                        in_class=in_class,
                        parent=enclosing,
                    )
                    mod.by_name.setdefault(child.name, []).append(info)
                    self._fn_by_node[id(child)] = info
                    if parent is sf.tree:
                        mod.top_level[child.name] = info
                    index_defs(
                        child, f"{prefix}{child.name}.", False, info
                    )
                elif isinstance(child, ast.ClassDef):
                    index_defs(
                        child, f"{prefix}{child.name}.", True, enclosing
                    )
                else:
                    index_defs(child, prefix, in_class, enclosing)

        index_defs(sf.tree, "", False, None)

    def info(self, fn_node: ast.AST) -> Optional[FunctionInfo]:
        return self._fn_by_node.get(id(fn_node))

    def functions(self) -> Iterable[FunctionInfo]:
        """Every indexed function definition across the analyzed set
        (shapeflow scans these for @shape_contract annotations)."""
        return self._fn_by_node.values()

    # -- jit-artifact classification -------------------------------------

    def _classify_jit_artifacts(self) -> None:
        for mod in self.modules.values():
            for name, fi in mod.top_level.items():
                if _jit_decorated(fi.node):
                    mod.jit_bindings.add(name)
            for node in mod.sf.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and _is_trace_entry_call(
                        node.value, _JIT_WRAPPER_CALLS
                    )
                ):
                    mod.jit_bindings.add(node.targets[0].id)
            for name, fi in mod.top_level.items():
                if self._returns_jit_callable(fi.node):
                    mod.factories.add(name)
        # device-returning functions: fixpoint over return-expression flow
        changed = True
        while changed:
            changed = False
            for mod in self.modules.values():
                for name, fi in mod.top_level.items():
                    if name in mod.device_fns or fi.in_class:
                        continue
                    if self._returns_device(mod, fi.node):
                        mod.device_fns.add(name)
                        changed = True

    def _returns_jit_callable(self, fn) -> bool:
        for node in walk_nodes(fn):
            if (
                isinstance(node, ast.Return)
                and isinstance(node.value, ast.Call)
                and _is_trace_entry_call(node.value, _JIT_WRAPPER_CALLS)
            ):
                return True
        return False

    def _returns_device(self, mod: ModuleInfo, fn) -> bool:
        """Does some return expression flow out of a jit dispatch? Tracks
        jit-callable locals (`fn = _sell_solver(key); return fn(...)`) and
        device locals (`d = batched_spf(...); return d`)."""
        jit_locals: Set[str] = set()
        dev_locals: Set[str] = set()

        def call_is_device(call: ast.Call) -> bool:
            func = call.func
            if isinstance(func, ast.Name):
                if func.id in jit_locals:
                    return True
                kind = self.resolve_producer(mod, func.id)
                return kind in ("jit", "device")
            if isinstance(func, ast.Attribute):
                chain = dotted_name(func)
                if chain:
                    kind = self.resolve_producer_chain(mod, chain)
                    return kind in ("jit", "device")
            if isinstance(func, ast.Call):
                # factory call called immediately: _bf_vw_solver(mesh)(...)
                inner = call_name(func)
                if inner and self.resolve_producer(mod, inner) == "factory":
                    return True
            return False

        for node in walk_nodes(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                fname = (
                    node.value.func.id
                    if isinstance(node.value.func, ast.Name)
                    else None
                )
                is_factory = (
                    fname is not None
                    and self.resolve_producer(mod, fname) == "factory"
                ) or _is_trace_entry_call(node.value, _JIT_WRAPPER_CALLS)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        if is_factory:
                            jit_locals.add(t.id)
                        elif call_is_device(node.value):
                            dev_locals.add(t.id)
        for node in walk_nodes(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                v = node.value
                if isinstance(v, ast.Call) and call_is_device(v):
                    return True
                if isinstance(v, ast.Name) and v.id in dev_locals:
                    return True
        return False

    # -- resolution ------------------------------------------------------

    def _imported(
        self, mod: ModuleInfo, local: str
    ) -> Optional[Tuple[ModuleInfo, str]]:
        """(source module, source name) of a from-import binding, when the
        source module is in the scanned set (re-export chains followed one
        hop through package __init__ files)."""
        seen = 0
        cur: Optional[Tuple[str, str]] = mod.from_imports.get(local)
        while cur is not None and seen < 4:
            src_mod = self.modules.get(cur[0])
            if src_mod is None:
                return None
            if cur[1] in src_mod.top_level or cur[1] in src_mod.jit_bindings:
                return src_mod, cur[1]
            cur = src_mod.from_imports.get(cur[1])
            seen += 1
        return None

    def resolve_call_defs(
        self, mod: ModuleInfo, call: ast.Call
    ) -> List[FunctionInfo]:
        """Candidate definitions a call site may invoke: same-module simple
        names (union over collisions, matching the per-file rule), from-
        import bindings, and module-alias attribute chains."""
        func = call.func
        if isinstance(func, ast.Name):
            name = func.id
            local = mod.by_name.get(name)
            if local:
                return list(local)
            imp = self._imported(mod, name)
            if imp is not None:
                src_mod, src_name = imp
                target = src_mod.top_level.get(src_name)
                return [target] if target is not None else []
            return []
        if isinstance(func, ast.Attribute):
            chain = dotted_name(func)
            if not chain or "." not in chain:
                return []
            head, _, attr_path = chain.partition(".")
            if head in ("self", "cls"):
                return []
            base = mod.module_aliases.get(head)
            if base is None:
                return []
            # re-join `import a.b.c` chains: a.b.c.f -> module a.b.c, f
            full = chain
            mod_path, _, fn_name = full.rpartition(".")
            src_mod = self.modules.get(mod_path)
            if src_mod is None and mod_path == head:
                src_mod = self.modules.get(base)
            if src_mod is None:
                return []
            target = src_mod.top_level.get(fn_name)
            return [target] if target is not None else []
        return []

    def resolve_producer(self, mod: ModuleInfo, name: str) -> Optional[str]:
        """'jit' | 'factory' | 'device' | None for a bare name in mod —
        following from-imports to the defining module."""
        if name in mod.jit_bindings:
            return "jit"
        if name in mod.factories:
            return "factory"
        if name in mod.device_fns:
            return "device"
        if name in mod.top_level:
            return None  # defined here, classified as none of the above
        imp = self._imported(mod, name)
        if imp is not None:
            src_mod, src_name = imp
            if src_name in src_mod.jit_bindings:
                return "jit"
            if src_name in src_mod.factories:
                return "factory"
            if src_name in src_mod.device_fns:
                return "device"
        return None

    def resolve_producer_chain(
        self, mod: ModuleInfo, chain: str
    ) -> Optional[str]:
        """resolve_producer for dotted `alias.f` module-attribute calls."""
        mod_path, _, fn_name = chain.rpartition(".")
        if not mod_path:
            return self.resolve_producer(mod, chain)
        head = mod_path.split(".")[0]
        if head not in mod.module_aliases:
            return None
        src_mod = self.modules.get(mod_path) or self.modules.get(
            mod.module_aliases[head]
        )
        if src_mod is None:
            return None
        if fn_name in src_mod.jit_bindings:
            return "jit"
        if fn_name in src_mod.factories:
            return "factory"
        if fn_name in src_mod.device_fns:
            return "device"
        return None

    def resolve_static_argnames(
        self, mod: ModuleInfo, name: str
    ) -> Optional[Tuple[FunctionInfo, Tuple, Tuple]]:
        """(wrapped def, static_argnames, static_argnums) for a module-level
        jit binding `X = jax.jit(core, static_argnames=(...))` or a
        @functools.partial(jax.jit, static_argnames=...)-decorated def —
        following from-imports. None when the name is not such a binding."""
        target_mod = mod
        target_name = name
        if name not in mod.jit_bindings:
            imp = self._imported(mod, name)
            if imp is None:
                return None
            target_mod, target_name = imp
        if target_name not in target_mod.jit_bindings:
            return None
        # `X = jax.jit(core, static_arg...=...)` module-level assignment
        for node in target_mod.sf.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == target_name
                and isinstance(node.value, ast.Call)
                and _is_trace_entry_call(node.value, _JIT_WRAPPER_CALLS)
            ):
                names, nums = _static_kwargs(node.value)
                core = None
                if node.value.args and isinstance(
                    node.value.args[0], ast.Name
                ):
                    core = target_mod.top_level.get(node.value.args[0].id)
                if core is not None:
                    return core, names, nums
        # decorated def: @functools.partial(jax.jit, static_argnames=...)
        fi = target_mod.top_level.get(target_name)
        if fi is not None:
            for dec in fi.node.decorator_list:
                if isinstance(dec, ast.Call):
                    args_all = [dotted_name(a) or "" for a in dec.args]
                    if any(
                        a.split(".")[-1] in _JIT_WRAPPER_CALLS
                        for a in args_all
                    ) or (dotted_name(dec.func) or "").split(".")[
                        -1
                    ] in _JIT_WRAPPER_CALLS:
                        names, nums = _static_kwargs(dec)
                        return fi, names, nums
        return None

    # -- dependency closure (for `--changed`) ----------------------------

    def module_dependents(self, changed: Iterable[str]) -> Set[str]:
        """Transitive closure of modules importing any of `changed` —
        the scan set a diff-scoped run must cover (a cross-module rule's
        finding can live in a dependent of the edited module)."""
        importers: Dict[str, Set[str]] = {m: set() for m in self.modules}
        for mod in self.modules.values():
            deps: Set[str] = set()
            for src_module, _ in mod.from_imports.values():
                deps.add(src_module)
            deps.update(mod.module_aliases.values())
            for dep in deps:
                # an import of a package lands on its __init__ module
                for candidate in (dep,):
                    if candidate in importers:
                        importers[candidate].add(mod.name)
        out: Set[str] = set()
        queue = [m for m in changed if m in importers]
        while queue:
            cur = queue.pop()
            if cur in out:
                continue
            out.add(cur)
            queue.extend(importers.get(cur, ()))
        return out


def build_callgraph(ctx: AnalysisContext) -> CallGraph:
    """Context-cached accessor: every rule in a run shares one graph."""
    cached = getattr(ctx, "_callgraph", None)
    if cached is None:
        cached = CallGraph(ctx)
        ctx._callgraph = cached
    return cached


def _static_kwargs(call: ast.Call) -> Tuple[Tuple, Tuple]:
    """(static_argnames, static_argnums) literal values of a jit call."""
    names: Tuple = ()
    nums: Tuple = ()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names = _const_tuple(kw.value)
        elif kw.arg == "static_argnums":
            nums = _const_tuple(kw.value)
    return names, nums


def _const_tuple(node: ast.AST) -> Tuple:
    if isinstance(node, ast.Constant):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts if isinstance(e, ast.Constant)
        )
    return ()
