"""blocking-call: no synchronous blocking inside event-loop code.

Every module (Decision's kvstore consumer, KvStore's flood/full-sync
tasks, Fib's programming/keepalive loops, the ctrl server's connection
handlers) shares one asyncio loop: a single synchronous `time.sleep`,
blocking socket op, or un-deadlined `Future.result()` stalls *all* of
them — convergence, flooding and the ctrl API freeze together, and the
Watchdog eventually aborts the process. Flagged inside any `async def`
(including sync closures defined there, which run as loop callbacks):

  - `time-sleep`: `time.sleep(...)` — use `asyncio.sleep`.
  - `undeadlined-result`: `<future>.result()` with neither a positional
    timeout nor a `timeout=` kwarg — an unbounded cross-thread wait.
  - `blocking-socket`: non-awaited `.recv/.recvfrom/.accept/.sendall/
    .makefile` calls and `socket.create_connection` /
    `socket.gethostbyname` / `socket.getaddrinfo` / `select.select` —
    use the loop's transports (`loop.sock_*`, streams) instead.
  - `blocking-subprocess`: `subprocess.run/check_output/check_call/call`
    and `os.system` — use `asyncio.create_subprocess_*`.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from openr_tpu.analysis.core import (
    AnalysisContext,
    Rule,
    call_name,
    dotted_name,
    register,
    walk_nodes,
)

_SOCKET_METHODS = {"recv", "recvfrom", "accept", "sendall", "makefile"}
_BLOCKING_MODULE_CALLS = {
    "time.sleep": ("time-sleep", "use asyncio.sleep"),
    "socket.create_connection": (
        "blocking-socket",
        "use asyncio.open_connection",
    ),
    "socket.gethostbyname": (
        "blocking-socket",
        "use loop.getaddrinfo",
    ),
    "socket.getaddrinfo": ("blocking-socket", "use loop.getaddrinfo"),
    "select.select": ("blocking-socket", "use loop readers/writers"),
    "subprocess.run": (
        "blocking-subprocess",
        "use asyncio.create_subprocess_exec",
    ),
    "subprocess.check_output": (
        "blocking-subprocess",
        "use asyncio.create_subprocess_exec",
    ),
    "subprocess.check_call": (
        "blocking-subprocess",
        "use asyncio.create_subprocess_exec",
    ),
    "subprocess.call": (
        "blocking-subprocess",
        "use asyncio.create_subprocess_exec",
    ),
    "os.system": (
        "blocking-subprocess",
        "use asyncio.create_subprocess_shell",
    ),
}


def _async_defs(tree: ast.AST) -> Iterable[ast.AsyncFunctionDef]:
    for node in walk_nodes(tree):
        if isinstance(node, ast.AsyncFunctionDef):
            yield node


def _awaited_calls(fn) -> Set[int]:
    """id()s of Call nodes that are directly awaited (await x.recv())."""
    out: Set[int] = set()
    for node in walk_nodes(fn):
        if isinstance(node, ast.Await) and isinstance(
            node.value, ast.Call
        ):
            out.add(id(node.value))
    return out


@register
class BlockingCallRule(Rule):
    name = "blocking-call"
    severity = "error"
    description = (
        "no time.sleep, blocking socket ops, or un-deadlined .result() "
        "inside async event-loop bodies"
    )

    def run(self, ctx: AnalysisContext):
        for sf in ctx.files:
            for fn in _async_defs(sf.tree):
                awaited = _awaited_calls(fn)
                for node in walk_nodes(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    yield from self._check_call(sf, fn, node, awaited)

    def _check_call(self, sf, fn, node, awaited):
        chain = dotted_name(node.func)
        if chain in _BLOCKING_MODULE_CALLS:
            check, fix = _BLOCKING_MODULE_CALLS[chain]
            yield self.finding(
                check,
                sf,
                node.lineno,
                f"async '{fn.name}': blocking {chain}(...) stalls the "
                f"whole event loop — {fix}",
            )
            return
        name = call_name(node)
        if (
            name == "result"
            and isinstance(node.func, ast.Attribute)
            and not node.args
            and not any(kw.arg == "timeout" for kw in node.keywords)
        ):
            yield self.finding(
                "undeadlined-result",
                sf,
                node.lineno,
                f"async '{fn.name}': .result() without a timeout is an "
                f"unbounded blocking wait — pass timeout= or await the "
                f"future",
            )
        elif (
            name in _SOCKET_METHODS
            and isinstance(node.func, ast.Attribute)
            and id(node) not in awaited
        ):
            receiver = dotted_name(node.func.value) or ""
            if "sock" in receiver.lower() or "conn" in receiver.lower():
                yield self.finding(
                    "blocking-socket",
                    sf,
                    node.lineno,
                    f"async '{fn.name}': blocking socket op "
                    f"{receiver}.{name}(...) — use loop.sock_{name} or "
                    f"streams",
                )
