"""registry-drift: hand-maintained registries must match the code.

Three registries drift silently as the codebase grows; this rule pins each
to its source of truth (it is the generalization of the old
tests/test_counter_naming.py lint into the analysis layer — that test now
delegates here):

  1. Counter/histogram names. Every name emitted through
     CountersMixin/HistogramsMixin (`self._bump("...")`,
     `self._observe("...")`, `self._timer("...")`, literal subscripts on
     `counters`/`histograms`/`_ensure_counters()`/`_ensure_histograms()`)
     must follow `<module>.<name>` with a registered module prefix
     (docs/Monitoring.md); `_observe`/`_timer` names must carry a unit
     suffix (`*_ms`/`*_bytes`). On full-package scans the naming tables in
     docs/Monitoring.md are cross-checked: every documented name must
     exist in code (no ghost rows), and every emitted histogram must be
     documented (the histogram table is exhaustive by contract; the
     counter table is explicitly exemplary).
  2. Fault points. `fault_point("...")` names in code vs. the catalog
     table in docs/Robustness.md — both directions.
  3. Decision config knobs. Every `DecisionConfigSection` field must be
     mentioned in docs/ (bare, or as the `--decision_<name>` flag), and
     every `solver_*`-style knob the docs name must exist as a field.
  4. LogSample event names. Every event name stamped onto a LogSample —
     `sample.add_string("event", <literal or module constant>)` and
     `self._emit_sample("NAME", ...)` — must appear in the event-catalog
     table of docs/Monitoring.md, and every cataloged event must be
     emitted (CONVERGENCE_TRACE, FLOOD_TRACE, SOLVER_BREAKER_*,
     WARM_STATE_AUDIT_MISMATCH, ... — both directions).

Doc-name shorthand understood when parsing tables: `{a,b}` brace
alternation, `*` suffix wildcards, and `x_sent/recv` slash alternation on
the final `_`-separated token. Event-catalog rows are ALL_CAPS tokens and
support the same braces and `*` suffix wildcards.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from openr_tpu.analysis.core import (
    RULES,
    AnalysisContext,
    Rule,
    SourceFile,
    register,
    walk_nodes,
)

MIXINS = {"CountersMixin", "HistogramsMixin"}

# module prefixes registered with the Monitor (openr.py) plus the
# cross-module end-to-end namespaces and process-level stats; "ctrl"
# covers the streaming control plane's fan-out + admission layers
# (ctrl.stream.* / ctrl.admission.*, docs/Streaming.md); "restart" is
# the whole-node warm-boot span (restart.e2e_ms, closed by Fib like
# convergence.e2e_ms — docs/Robustness.md "Graceful restart & warm boot");
# "fleet" is the fleet observer's own telemetry (openr_tpu/fleet — a
# Monitor-registrable module even though it usually runs out-of-daemon,
# docs/Monitoring.md "Fleet observer & SLO watchdog")
ALLOWED_PREFIXES = {
    "decision",
    "kvstore",
    "fib",
    "spark",
    "link_monitor",
    "prefix_manager",
    "convergence",
    "restart",
    "process",
    "monitor",
    "ctrl",
    "fleet",
    # the state journal (openr_tpu/journal — docs/Journal.md): recorder,
    # durable log and replay engine telemetry (docs/Monitoring.md
    # "State journal")
    "journal",
}

# <module>.<name>[.<name>...], lowercase snake segments
NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
_DOC_TOKEN_RE = re.compile(r"`([a-z0-9_.{},*/]+)`")

# LogSample event names: SCREAMING_SNAKE (CONVERGENCE_TRACE, FLOOD_TRACE)
EVENT_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*$")
_EVENT_DOC_TOKEN_RE = re.compile(r"`([A-Z0-9_{},*]+)`")

_EMIT_CALLS = {"_bump", "_observe", "_timer"}
_HIST_CALLS = {"_observe", "_timer"}
_DICT_ATTRS = {"counters", "histograms"}
_ENSURE_CALLS = {"_ensure_counters", "_ensure_histograms"}


# ---------------------------------------------------------------------------
# emission collection (the old test_counter_naming walk, context-based)
# ---------------------------------------------------------------------------


def _base_names(node: ast.ClassDef):
    for base in node.bases:
        if isinstance(base, ast.Name):
            yield base.id
        elif isinstance(base, ast.Attribute):
            yield base.attr


def _mixin_classes(ctx: AnalysisContext) -> Set[str]:
    """Names of classes inheriting a mixin, transitively by simple name."""
    bases: Dict[str, Set[str]] = {}
    for sf in ctx.files:
        for node in walk_nodes(sf.tree):
            if isinstance(node, ast.ClassDef):
                bases[node.name] = set(_base_names(node))
    users = set(MIXINS)
    changed = True
    while changed:
        changed = False
        for name, bs in bases.items():
            if name not in users and bs & users:
                users.add(name)
                changed = True
    return users - MIXINS


def _is_dict_ref(node) -> bool:
    """`self.counters` / `x.histograms` / `self._ensure_counters()` or a
    local alias of one (`counters = self._ensure_counters()`)."""
    if isinstance(node, ast.Attribute) and node.attr in _DICT_ATTRS:
        return True
    if isinstance(node, ast.Name) and node.id in _DICT_ATTRS:
        return True
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in _ENSURE_CALLS
    )


def collect_emitted_names(
    ctx: AnalysisContext,
) -> List[Tuple[str, SourceFile, int]]:
    """(name, file, line) for every mixin-user emission site in scope."""
    mixin_users = _mixin_classes(ctx)
    found: List[Tuple[str, SourceFile, int]] = []
    for sf in ctx.files:
        for cls in walk_nodes(sf.tree):
            if not (
                isinstance(cls, ast.ClassDef) and cls.name in mixin_users
            ):
                continue
            for node in walk_nodes(cls):
                name = None
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _EMIT_CALLS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    name = node.args[0].value
                elif (
                    isinstance(node, ast.Subscript)
                    and _is_dict_ref(node.value)
                    and isinstance(node.slice, ast.Constant)
                    and isinstance(node.slice.value, str)
                ):
                    name = node.slice.value
                if name is not None:
                    found.append((name, sf, node.lineno))
    return found


def collect_histogram_names(
    ctx: AnalysisContext,
) -> List[Tuple[str, SourceFile, int]]:
    """Literal first args of _observe/_timer anywhere in scope."""
    found = []
    for sf in ctx.files:
        for node in walk_nodes(sf.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _HIST_CALLS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                found.append((node.args[0].value, sf, node.lineno))
    return found


def _string_universe(ctx: AnalysisContext) -> Tuple[Set[str], Set[str]]:
    """(exact names, f-string prefixes) of dotted-name-shaped string
    constants anywhere in the scanned code — the existence oracle for the
    doc-direction checks (f-strings like
    f"decision.spf.solver_failures.{kind}" contribute their literal
    prefix)."""
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    for sf in ctx.files:
        for node in walk_nodes(sf.tree):
            if isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                if NAME_RE.match(node.value):
                    exact.add(node.value)
            elif isinstance(node, ast.JoinedStr) and node.values:
                first = node.values[0]
                if isinstance(first, ast.Constant) and isinstance(
                    first.value, str
                ):
                    if "." in first.value:
                        prefixes.add(first.value)
    return exact, prefixes


# ---------------------------------------------------------------------------
# doc parsing
# ---------------------------------------------------------------------------


def _expand_doc_token(token: str) -> List[str]:
    """Expand one backticked doc token into candidate names/wildcards."""
    # {a,b} alternation (possibly with a suffix after the brace)
    m = re.match(r"^(.*)\{([^}]*)\}(.*)$", token)
    if m:
        out: List[str] = []
        for alt in m.group(2).split(","):
            out.extend(_expand_doc_token(m.group(1) + alt + m.group(3)))
        return out
    # x_sent/recv slash alternation on the final token
    if "/" in token:
        head, _, tail = token.rpartition("/")
        if "." in tail or "/" in head and "." in head.rsplit("/", 1)[1]:
            return []  # a path like fib/fib.py, not a name
        if not head or "." not in head:
            return []
        base = head
        cut = base.rfind("_")
        if cut < 0:
            return []
        second = base[: cut + 1] + tail.lstrip("_")
        return _expand_doc_token(head) + _expand_doc_token(second)
    if token.endswith("*"):
        stem = token.rstrip("*")
        return [stem + "*"] if "." in stem else []
    return [token] if NAME_RE.match(token) else []


def _table_names(text: str, header_hint: Optional[str] = None) -> Set[str]:
    """Backticked names from markdown table rows. With header_hint, only
    tables whose header row mentions it are read."""
    names: Set[str] = set()
    in_table = header_hint is None
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped.startswith("|"):
            if header_hint is not None:
                in_table = False
            continue
        if header_hint is not None and header_hint in stripped.lower():
            in_table = True
            continue
        if not in_table:
            continue
        for token in _DOC_TOKEN_RE.findall(stripped):
            names.update(_expand_doc_token(token))
    return names


def _exists_in_code(
    name: str, exact: Set[str], prefixes: Set[str]
) -> bool:
    if name.endswith("*"):
        stem = name[:-1]
        return any(e.startswith(stem) for e in exact) or any(
            p.startswith(stem) or stem.startswith(p) for p in prefixes
        )
    return name in exact or any(name.startswith(p) for p in prefixes)


# ---------------------------------------------------------------------------
# LogSample event names
# ---------------------------------------------------------------------------


def collect_log_events(
    ctx: AnalysisContext,
) -> List[Tuple[str, SourceFile, int]]:
    """(event-name, file, line) for every LogSample event emission:
    `*.add_string("event", X)` where X is a string literal or a
    module-level string constant, and literal first args of
    `self._emit_sample("NAME", ...)` helpers."""
    found: List[Tuple[str, SourceFile, int]] = []
    for sf in ctx.files:
        consts: Dict[str, str] = {}
        for node in sf.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                consts[node.targets[0].id] = node.value.value
        for node in walk_nodes(sf.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
            ):
                continue
            name: Optional[str] = None
            if (
                node.func.attr == "add_string"
                and len(node.args) >= 2
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "event"
            ):
                arg = node.args[1]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    name = arg.value
                elif isinstance(arg, ast.Name):
                    name = consts.get(arg.id)
            elif (
                node.func.attr == "_emit_sample"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                name = node.args[0].value
            if name is not None and EVENT_NAME_RE.match(name):
                found.append((name, sf, node.lineno))
    return found


def _expand_event_token(token: str) -> List[str]:
    m = re.match(r"^(.*)\{([^}]*)\}(.*)$", token)
    if m:
        out: List[str] = []
        for alt in m.group(2).split(","):
            out.extend(_expand_event_token(m.group(1) + alt + m.group(3)))
        return out
    if token.endswith("*"):
        stem = token.rstrip("*")
        return [stem + "*"] if EVENT_NAME_RE.match(stem) else []
    return [token] if EVENT_NAME_RE.match(token) else []


def _event_table_names(text: str) -> Set[str]:
    """ALL_CAPS backticked tokens from the event-catalog table (rows of
    markdown tables whose header mentions 'event')."""
    names: Set[str] = set()
    in_table = False
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped.startswith("|"):
            in_table = False
            continue
        if "event" in stripped.lower() and "---" not in stripped:
            header_tokens = _EVENT_DOC_TOKEN_RE.findall(stripped)
            if not header_tokens:
                in_table = True
                continue
        if not in_table:
            continue
        for token in _EVENT_DOC_TOKEN_RE.findall(stripped):
            names.update(_expand_event_token(token))
    return names


def _event_documented(name: str, documented: Set[str]) -> bool:
    if name in documented:
        return True
    return any(
        name.startswith(d[:-1]) for d in documented if d.endswith("*")
    )


# ---------------------------------------------------------------------------
# fault points + config knobs
# ---------------------------------------------------------------------------


def collect_fault_points(
    ctx: AnalysisContext,
) -> List[Tuple[str, SourceFile, int]]:
    """Literal first args of fault_point(...) declarations in scope."""
    found = []
    for sf in ctx.files:
        if sf.rel.endswith("testing/faults.py"):
            continue  # the harness itself, not a declaration site
        for node in walk_nodes(sf.tree):
            if (
                isinstance(node, ast.Call)
                and (
                    (
                        isinstance(node.func, ast.Name)
                        and node.func.id == "fault_point"
                    )
                    or (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "fault_point"
                    )
                )
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                found.append((node.args[0].value, sf, node.lineno))
    return found


def _decision_config_fields(
    ctx: AnalysisContext,
) -> List[Tuple[str, SourceFile, int]]:
    fields = []
    for sf in ctx.files:
        for node in walk_nodes(sf.tree):
            if (
                isinstance(node, ast.ClassDef)
                and node.name == "DecisionConfigSection"
            ):
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and isinstance(
                        stmt.target, ast.Name
                    ):
                        fields.append((stmt.target.id, sf, stmt.lineno))
    return fields


@register
class RegistryDriftRule(Rule):
    name = "registry-drift"
    severity = "error"
    description = (
        "counter/histogram names, fault points, LogSample event names and "
        "DecisionConfigSection knobs must match their docs registries "
        "(Monitoring.md / Robustness.md)"
    )

    def run(self, ctx: AnalysisContext) -> Iterable:
        yield from self._check_naming(ctx)
        if ctx.docs_dir is None or not ctx.full_package:
            # doc cross-checks need the whole package in scope: a
            # single-file scan must not report the rest as ghosts
            return
        yield from self._check_monitoring_docs(ctx)
        yield from self._check_exporter_metrics(ctx)
        yield from self._check_event_catalog(ctx)
        yield from self._check_fault_catalog(ctx)
        yield from self._check_config_knobs(ctx)
        yield from self._check_rule_table(ctx)

    # -- naming convention (always on) ----------------------------------

    def _check_naming(self, ctx: AnalysisContext):
        for name, sf, line in collect_emitted_names(ctx):
            if (
                not NAME_RE.match(name)
                or name.split(".", 1)[0] not in ALLOWED_PREFIXES
            ):
                yield self.finding(
                    "counter-name",
                    sf,
                    line,
                    f"counter/histogram name '{name}' violates the "
                    f"<module>.<name> convention "
                    f"(allowed prefixes: docs/Monitoring.md)",
                )
        for name, sf, line in collect_histogram_names(ctx):
            if not name.endswith(("_ms", "_bytes")):
                yield self.finding(
                    "histogram-unit",
                    sf,
                    line,
                    f"histogram name '{name}' lacks a unit suffix "
                    f"(*_ms or *_bytes)",
                )

    # -- docs/Monitoring.md cross-check ---------------------------------

    def _check_monitoring_docs(self, ctx: AnalysisContext):
        doc = ctx.docs_dir / "Monitoring.md"
        if not doc.exists():
            return
        sf_doc = _doc_source(ctx, doc)
        text = doc.read_text()
        exact, prefixes = _string_universe(ctx)
        for name in sorted(_table_names(text)):
            if not _exists_in_code(name, exact, prefixes):
                yield self.finding(
                    "doc-ghost",
                    sf_doc,
                    _doc_line(text, name),
                    f"docs/Monitoring.md documents '{name}' but no code "
                    f"in the package emits it",
                )
        documented = _table_names(text)
        doc_exact = {n for n in documented if not n.endswith("*")}
        doc_stems = {n[:-1] for n in documented if n.endswith("*")}
        for name, sf, line in collect_histogram_names(ctx):
            if name in doc_exact or any(
                name.startswith(s) for s in doc_stems
            ):
                continue
            yield self.finding(
                "undocumented-histogram",
                sf,
                line,
                f"histogram '{name}' is emitted but missing from the "
                f"docs/Monitoring.md histogram table",
            )

    # -- docs/Monitoring.md exporter-metric table -----------------------

    def _check_exporter_metrics(self, ctx: AnalysisContext):
        """The exporter's own telemetry namespace (`monitor.*` — the
        scrape/push/rollup overhead metrics riding every exposition) is
        pinned to its docs/Monitoring.md table BOTH ways, exhaustively:
        an emitted `monitor.*` name missing a table row is an
        undocumented-metric, a row no code emits is a ghost-metric. The
        general counter table is exemplary by contract; this table is
        not — the exporter serves it to external scrapers, so drift here
        is operator-visible dashboard breakage."""
        doc = ctx.docs_dir / "Monitoring.md"
        if not doc.exists():
            return
        sf_doc = _doc_source(ctx, doc)
        text = doc.read_text()
        documented = _table_names(text, header_hint="exporter metric")
        doc_exact = {n for n in documented if not n.endswith("*")}
        doc_stems = {n[:-1] for n in documented if n.endswith("*")}
        emissions = {
            (name, sf.rel, line): (name, sf, line)
            for name, sf, line in (
                collect_emitted_names(ctx) + collect_histogram_names(ctx)
            )
            if name.startswith("monitor.")
        }
        emitted: Set[str] = set()
        for name, sf, line in emissions.values():
            emitted.add(name)
            if name in doc_exact or any(
                name.startswith(s) for s in doc_stems
            ):
                continue
            yield self.finding(
                "undocumented-metric",
                sf,
                line,
                f"exporter metric '{name}' is emitted but missing from "
                f"the docs/Monitoring.md exporter-metric table",
            )
        for name in sorted(documented):
            if name.endswith("*"):
                if any(e.startswith(name[:-1]) for e in emitted):
                    continue
            elif name in emitted:
                continue
            yield self.finding(
                "ghost-metric",
                sf_doc,
                _doc_line(text, name),
                f"docs/Monitoring.md exporter-metric table documents "
                f"'{name}' but no code emits it",
            )

    # -- docs/Monitoring.md LogSample event catalog ---------------------

    def _check_event_catalog(self, ctx: AnalysisContext):
        doc = ctx.docs_dir / "Monitoring.md"
        if not doc.exists():
            return
        sf_doc = _doc_source(ctx, doc)
        text = doc.read_text()
        documented = _event_table_names(text)
        code_events = collect_log_events(ctx)
        emitted = {name for name, _, _ in code_events}
        for name, sf, line in code_events:
            if not _event_documented(name, documented):
                yield self.finding(
                    "undocumented-event",
                    sf,
                    line,
                    f"LogSample event '{name}' is emitted but missing "
                    f"from the docs/Monitoring.md event catalog",
                )
        for name in sorted(documented):
            if name.endswith("*"):
                stem = name[:-1]
                if any(e.startswith(stem) for e in emitted):
                    continue
            elif name in emitted:
                continue
            yield self.finding(
                "ghost-event",
                sf_doc,
                _doc_line(text, name.rstrip("*")),
                f"docs/Monitoring.md catalogs LogSample event '{name}' "
                f"but no code emits it",
            )

    # -- docs/Robustness.md fault-point catalog -------------------------

    def _check_fault_catalog(self, ctx: AnalysisContext):
        doc = ctx.docs_dir / "Robustness.md"
        if not doc.exists():
            return
        sf_doc = _doc_source(ctx, doc)
        text = doc.read_text()
        doc_points = _table_names(text, header_hint="fault point")
        code_points = collect_fault_points(ctx)
        code_set = {name for name, _, _ in code_points}
        for name, sf, line in code_points:
            if name not in doc_points:
                yield self.finding(
                    "undocumented-fault-point",
                    sf,
                    line,
                    f"fault point '{name}' is declared in code but "
                    f"missing from the docs/Robustness.md catalog",
                )
        for name in sorted(doc_points - code_set):
            yield self.finding(
                "ghost-fault-point",
                sf_doc,
                _doc_line(text, name),
                f"docs/Robustness.md catalogs fault point '{name}' but "
                f"no fault_point(...) declares it",
            )

    # -- docs/Analysis.md rule catalog ----------------------------------

    def _check_rule_table(self, ctx: AnalysisContext):
        """The analysis suite's own registry: the docs/Analysis.md rule
        table and the RULES registry (= `--list-rules` output, which is
        generated from it) must match both ways — a rule family without a
        documented invariant is unreviewable, a documented family that no
        longer registers is a ghost."""
        doc = ctx.docs_dir / "Analysis.md"
        if not doc.exists():
            return
        sf_doc = _doc_source(ctx, doc)
        text = doc.read_text()
        documented: Set[str] = set()
        in_table = False
        for line in text.splitlines():
            s = line.strip()
            if not s.startswith("|"):
                in_table = False
                continue
            cells = [c.strip() for c in s.strip("|").split("|")]
            if not cells:
                continue
            low = cells[0].lower()
            if low == "rule":
                in_table = True
                continue
            if not in_table or set(cells[0]) <= {"-", " "}:
                continue
            m = re.match(r"^`([a-z][a-z0-9-]*)`$", cells[0])
            if m:
                documented.add(m.group(1))
        registered = set(RULES)
        for name in sorted(registered - documented):
            yield self.finding(
                "undocumented-rule",
                sf_doc,
                _doc_line(text, name),
                f"analysis rule '{name}' is registered but missing from "
                f"the docs/Analysis.md rule table",
            )
        for name in sorted(documented - registered):
            yield self.finding(
                "ghost-rule",
                sf_doc,
                _doc_line(text, name),
                f"docs/Analysis.md documents analysis rule '{name}' but "
                f"no such rule registers (see --list-rules)",
            )

    # -- DecisionConfigSection knobs ------------------------------------

    def _check_config_knobs(self, ctx: AnalysisContext):
        fields = _decision_config_fields(ctx)
        if not fields or ctx.docs_dir is None:
            return
        doc_text = "\n".join(
            p.read_text() for p in sorted(ctx.docs_dir.glob("*.md"))
        )
        for name, sf, line in fields:
            # documented bare, or via the --decision_<name> flag spelling
            pat = re.compile(
                r"(?<![A-Za-z0-9_])(?:decision_)?"
                + re.escape(name)
                + r"(?![A-Za-z0-9_])"
            )
            if not pat.search(doc_text):
                yield self.finding(
                    "undocumented-config-knob",
                    sf,
                    line,
                    f"DecisionConfigSection.{name} is not documented "
                    f"anywhere under docs/ (document the knob or the "
                    f"--decision_{name} flag)",
                )


def _doc_source(ctx: AnalysisContext, doc: Path) -> SourceFile:
    """A pseudo SourceFile for doc-anchored findings (suppression comments
    do not apply to docs; baseline entries do)."""
    try:
        rel = doc.relative_to(ctx.root).as_posix()
    except ValueError:
        rel = doc.as_posix()
    return SourceFile(
        path=doc, rel=rel, source="", tree=ast.parse(""), lines=[]
    )


def _doc_line(text: str, name: str) -> int:
    stem = name.rstrip("*")
    for i, line in enumerate(text.splitlines(), 1):
        if stem in line:
            return i
    return 1
