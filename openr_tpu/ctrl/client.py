"""Control-plane client.

Equivalent of openr/py/openr/clients/openr_client.py:25-47 (the thrift
client factory breeze uses): a thin request/response + streaming client for
the CtrlServer's newline-JSON protocol. Both async (tests, tooling) and
blocking (CLI) call styles are provided.
"""

from __future__ import annotations

import asyncio
import base64
import json
import socket
from typing import Any, Dict, Iterator, Optional

from openr_tpu.utils import serializer


def decode_obj(blob: Optional[str]):
    """Decode a b64 serializer blob returned by the server."""
    if blob is None:
        return None
    return serializer.loads(base64.b64decode(blob))


def encode_obj(obj) -> str:
    return base64.b64encode(serializer.dumps(obj)).decode()


class CtrlError(RuntimeError):
    """Server-reported error. Typed rejections (admission control,
    subscriber limits) carry `kind` ("server_busy") and a
    `retry_after_ms` backoff hint (docs/Streaming.md)."""

    def __init__(
        self,
        message: str,
        kind: Optional[str] = None,
        retry_after_ms: Optional[int] = None,
    ) -> None:
        super().__init__(message)
        self.kind = kind
        self.retry_after_ms = retry_after_ms

    @property
    def server_busy(self) -> bool:
        return self.kind == "server_busy"


def _raise_ctrl_error(resp: Dict) -> None:
    raise CtrlError(
        resp["error"],
        kind=resp.get("error_kind"),
        retry_after_ms=resp.get("retry_after_ms"),
    )


# one response/frame is one newline-JSON line: a full-fleet KvStore
# snapshot (subscribeKvStore's initial frame on a hundreds-of-nodes
# LSDB) far exceeds asyncio's default 64 KiB StreamReader limit, and
# readline() would fail with "chunk is longer than limit" on every
# fleet-scale subscription — size the reader for the protocol
_LINE_LIMIT = 64 * 1024 * 1024


class CtrlClient:
    """Async client: one connection, sequential request/response."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 2018,
        ssl_context=None,
        limit: int = _LINE_LIMIT,
    ) -> None:
        self.host = host
        self.port = port
        self._ssl_context = ssl_context
        self._limit = limit
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._next_id = 0

    async def connect(self) -> "CtrlClient":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, ssl=self._ssl_context, limit=self._limit
        )
        return self

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            self._reader = None

    async def __aenter__(self) -> "CtrlClient":
        return await self.connect()

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def call(self, method: str, **params) -> Any:
        assert self._writer is not None, "not connected"
        self._next_id += 1
        req = {"id": self._next_id, "method": method, "params": params}
        self._writer.write(json.dumps(req).encode() + b"\n")
        await self._writer.drain()
        line = await self._reader.readline()
        if not line:
            raise CtrlError("connection closed")
        resp = json.loads(line)
        if "error" in resp:
            _raise_ctrl_error(resp)
        return resp.get("result")

    async def subscribe(self, method: str, **params):
        """Async iterator over stream frames (subscribeKvStoreFilter)."""
        assert self._writer is not None, "not connected"
        self._next_id += 1
        req = {"id": self._next_id, "method": method, "params": params}
        self._writer.write(json.dumps(req).encode() + b"\n")
        await self._writer.drain()
        while True:
            line = await self._reader.readline()
            if not line:
                return
            frame = json.loads(line)
            if "error" in frame:
                _raise_ctrl_error(frame)
            if frame.get("done"):
                return
            yield frame["stream"]


class BlockingCtrlClient:
    """Synchronous client for CLI usage."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 2018,
        timeout: float = 30.0,
        ssl_context=None,
    ) -> None:
        # kept so callers fanning out to more nodes (breeze perf report
        # --hosts) can open peer connections with the same TLS settings
        self.ssl_context = ssl_context
        self._sock = socket.create_connection((host, port), timeout=timeout)
        if ssl_context is not None:
            self._sock = ssl_context.wrap_socket(self._sock)
        self._file = self._sock.makefile("rwb")
        self._next_id = 0

    def close(self) -> None:
        self._file.close()
        self._sock.close()

    def __enter__(self) -> "BlockingCtrlClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def call(self, method: str, **params) -> Any:
        self._next_id += 1
        req = {"id": self._next_id, "method": method, "params": params}
        self._file.write(json.dumps(req).encode() + b"\n")
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise CtrlError("connection closed")
        resp = json.loads(line)
        if "error" in resp:
            _raise_ctrl_error(resp)
        return resp.get("result")

    def subscribe(self, method: str, **params) -> Iterator[Dict]:
        self._next_id += 1
        req = {"id": self._next_id, "method": method, "params": params}
        self._file.write(json.dumps(req).encode() + b"\n")
        self._file.flush()
        while True:
            line = self._file.readline()
            if not line:
                return
            frame = json.loads(line)
            if "error" in frame:
                _raise_ctrl_error(frame)
            if frame.get("done"):
                return
            yield frame["stream"]
