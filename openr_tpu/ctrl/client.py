"""Control-plane client.

Equivalent of openr/py/openr/clients/openr_client.py:25-47 (the thrift
client factory breeze uses): a thin request/response + streaming client for
the CtrlServer's newline-JSON protocol. Both async (tests, tooling) and
blocking (CLI) call styles are provided.
"""

from __future__ import annotations

import asyncio
import base64
import json
import socket
from typing import Any, Dict, Iterator, Optional

from openr_tpu.utils import serializer


def decode_obj(blob: Optional[str]):
    """Decode a b64 serializer blob returned by the server."""
    if blob is None:
        return None
    return serializer.loads(base64.b64decode(blob))


def encode_obj(obj) -> str:
    return base64.b64encode(serializer.dumps(obj)).decode()


class CtrlError(RuntimeError):
    """Server-reported error. Typed rejections (admission control,
    subscriber limits) carry `kind` ("server_busy") and a
    `retry_after_ms` backoff hint (docs/Streaming.md)."""

    def __init__(
        self,
        message: str,
        kind: Optional[str] = None,
        retry_after_ms: Optional[int] = None,
    ) -> None:
        super().__init__(message)
        self.kind = kind
        self.retry_after_ms = retry_after_ms

    @property
    def server_busy(self) -> bool:
        return self.kind == "server_busy"


def _raise_ctrl_error(resp: Dict) -> None:
    raise CtrlError(
        resp["error"],
        kind=resp.get("error_kind"),
        retry_after_ms=resp.get("retry_after_ms"),
    )


# one response/frame is one newline-JSON line: a full-fleet KvStore
# snapshot (subscribeKvStore's initial frame on a hundreds-of-nodes
# LSDB) far exceeds asyncio's default 64 KiB StreamReader limit, and
# readline() would fail with "chunk is longer than limit" on every
# fleet-scale subscription — size the reader for the protocol
_LINE_LIMIT = 64 * 1024 * 1024


class CtrlClient:
    """Async client: one connection, sequential request/response."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 2018,
        ssl_context=None,
        limit: int = _LINE_LIMIT,
    ) -> None:
        self.host = host
        self.port = port
        self._ssl_context = ssl_context
        self._limit = limit
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._next_id = 0

    async def connect(self) -> "CtrlClient":
        self._reader, self._writer = await asyncio.open_connection(
            self.host, self.port, ssl=self._ssl_context, limit=self._limit
        )
        return self

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            self._reader = None

    async def __aenter__(self) -> "CtrlClient":
        return await self.connect()

    async def __aexit__(self, *exc) -> None:
        await self.close()

    async def call(self, method: str, **params) -> Any:
        assert self._writer is not None, "not connected"
        self._next_id += 1
        req = {"id": self._next_id, "method": method, "params": params}
        self._writer.write(json.dumps(req).encode() + b"\n")
        await self._writer.drain()
        line = await self._reader.readline()
        if not line:
            raise CtrlError("connection closed")
        resp = json.loads(line)
        if "error" in resp:
            _raise_ctrl_error(resp)
        return resp.get("result")

    async def subscribe(self, method: str, decode: bool = True, **params):
        """Async iterator over stream frames (subscribeKvStoreFilter).

        Pass ``codec="binary"`` to request the length-prefixed binary
        frame codec (docs/Streaming.md "Codec negotiation"): the server
        acks with one ``{"id": N, "codec": "binary"}`` line before
        switching framing. A server that predates the codec ignores the
        param and streams newline-JSON — the absent ack IS the graceful
        fallback, so consumers see identical payload dicts either way.

        ``decode=False`` is the fast-consumer mode for meters and
        benchmark watchers: every frame is still read off the socket in
        full, but the payload is not parsed — frames yield just
        ``{"type": kind}`` (plus ``seq`` on binary streams), read from
        the frame header / envelope prefix. The first JSON line is
        always fully parsed so codec negotiation and typed errors
        behave identically."""
        assert self._writer is not None, "not connected"
        self._next_id += 1
        want_binary = params.get("codec") == "binary"
        req = {"id": self._next_id, "method": method, "params": params}
        self._writer.write(json.dumps(req).encode() + b"\n")
        await self._writer.drain()
        binary = False
        first = True
        while True:
            if binary:
                payload = await self._read_binary_frame(method, decode)
                if payload is None:
                    return
                yield payload
                continue
            line = await self._reader.readline()
            if not line:
                return
            if not decode and not first:
                # the envelope prefix is pinned byte-identical to
                # json.dumps (streaming/codec.py), so the frame type
                # sits at a fixed early offset — sniff it instead of
                # parsing the whole line; anything unexpected (done,
                # error) falls through to the full parse below
                i = line.find(b'"type": "', 0, 96)
                if i >= 0:
                    j = i + 9
                    yield {"type": line[j : line.index(b'"', j)].decode()}
                    continue
            frame = json.loads(line)
            if "error" in frame:
                _raise_ctrl_error(frame)
            if first and want_binary and frame.get("codec") == "binary":
                binary = True
                first = False
                continue
            first = False
            if frame.get("done"):
                return
            yield frame["stream"]

    async def _read_binary_frame(self, method: str, decode: bool = True):
        from openr_tpu.streaming import codec as stream_codec

        try:
            header = await self._reader.readexactly(4)
            length, _ = stream_codec.frame_header_info(header)
            payload = await self._reader.readexactly(length)
        except asyncio.IncompleteReadError:
            return None  # connection closed mid-frame: end of stream
        if not decode:
            kind, seq = stream_codec.frame_kind_seq(payload)
            return {"type": kind, "seq": seq}
        stream = "routes" if "Route" in method else "kv"
        return stream_codec.decode_binary_frame(payload, stream)


class BlockingCtrlClient:
    """Synchronous client for CLI usage."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 2018,
        timeout: float = 30.0,
        ssl_context=None,
    ) -> None:
        # kept so callers fanning out to more nodes (breeze perf report
        # --hosts) can open peer connections with the same TLS settings
        self.ssl_context = ssl_context
        self._sock = socket.create_connection((host, port), timeout=timeout)
        if ssl_context is not None:
            self._sock = ssl_context.wrap_socket(self._sock)
        self._file = self._sock.makefile("rwb")
        self._next_id = 0

    def close(self) -> None:
        self._file.close()
        self._sock.close()

    def __enter__(self) -> "BlockingCtrlClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def call(self, method: str, **params) -> Any:
        self._next_id += 1
        req = {"id": self._next_id, "method": method, "params": params}
        self._file.write(json.dumps(req).encode() + b"\n")
        self._file.flush()
        line = self._file.readline()
        if not line:
            raise CtrlError("connection closed")
        resp = json.loads(line)
        if "error" in resp:
            _raise_ctrl_error(resp)
        return resp.get("result")

    def subscribe(self, method: str, **params) -> Iterator[Dict]:
        """Sync stream iterator; ``codec="binary"`` negotiates the
        binary framing exactly as CtrlClient.subscribe does, with the
        same graceful JSON fallback against old servers."""
        self._next_id += 1
        want_binary = params.get("codec") == "binary"
        req = {"id": self._next_id, "method": method, "params": params}
        self._file.write(json.dumps(req).encode() + b"\n")
        self._file.flush()
        binary = False
        first = True
        while True:
            if binary:
                payload = self._read_binary_frame(method)
                if payload is None:
                    return
                yield payload
                continue
            line = self._file.readline()
            if not line:
                return
            frame = json.loads(line)
            if "error" in frame:
                _raise_ctrl_error(frame)
            if first and want_binary and frame.get("codec") == "binary":
                binary = True
                first = False
                continue
            first = False
            if frame.get("done"):
                return
            yield frame["stream"]

    def _read_binary_frame(self, method: str):
        from openr_tpu.streaming import codec as stream_codec

        header = self._read_exact(4)
        if header is None:
            return None
        length, _ = stream_codec.frame_header_info(header)
        payload = self._read_exact(length)
        if payload is None:
            return None
        stream = "routes" if "Route" in method else "kv"
        return stream_codec.decode_binary_frame(payload, stream)

    def _read_exact(self, n: int) -> Optional[bytes]:
        chunks = []
        remaining = n
        while remaining:
            chunk = self._file.read(remaining)
            if not chunk:
                return None
            chunks.append(chunk)
            remaining -= len(chunk)
        return b"".join(chunks)
