"""Control-plane API server + client (OpenrCtrl equivalent)."""

from openr_tpu.ctrl.server import CtrlServer
from openr_tpu.ctrl.client import CtrlClient

__all__ = ["CtrlServer", "CtrlClient"]
