"""Control-plane API server.

Behavioral port of openr/ctrl-server/OpenrCtrlHandler.{h,cpp}: one server
holding references to every module, exposing the OpenrCtrl surface
(openr/if/OpenrCtrl.thrift:128-507) — route/adjacency/prefix reads, KvStore
get/set/dump, drain + metric-override controls, RibPolicy, config-store
keys, event logs, counters — plus the server-streaming KvStore subscription
(subscribeKvStoreFilter, OpenrCtrlHandler.h:207-211) and the adjacency
long-poll (longPollKvStoreAdj, OpenrCtrlLongPollTest.cpp semantics).

Transport is length-free newline-delimited JSON over TCP (the fbthrift
Rocket transport is Meta-stack-specific; a framed-JSON protocol keeps the
same request/response + streaming semantics with zero extra dependencies):
  request:   {"id": N, "method": "...", "params": {...}}
  response:  {"id": N, "result": ...} | {"id": N, "error": "..."}
  streaming: {"id": N, "stream": ...}* then {"id": N, "done": true}
  typed err: {"id": N, "error": "...", "error_kind": "server_busy",
              "retry_after_ms": M}

The streaming control plane (docs/Streaming.md) rides this transport:
`subscribeKvStore` / `subscribeRouteDb` stream typed frames ("snapshot",
then "delta"s, with marked "resync" snapshots after fan-out overflow)
through the daemon's StreamManager (bounded per-subscriber queues —
a stalled reader can never block publication or other subscribers), and
the expensive RPCs (`runTeOptimize`, `getRouteDbComputed`,
`getConvergenceReport`) pass through the AdmissionController's weighted
fair queue, rejecting with the typed server-busy error above when the
bounded wait expires.
"""

from __future__ import annotations

import asyncio
import base64
import json
import logging
import time
from typing import Any, Callable, Dict, List, Optional

from openr_tpu.kvstore import wire
from openr_tpu.messaging import QueueClosedError
from openr_tpu.testing.faults import fault_point
from openr_tpu.types import (
    ADJ_DB_MARKER,
    IpPrefix,
    KeyVals,
    Publication,
    Value,
)
from openr_tpu.utils import serializer

log = logging.getLogger(__name__)


def _b64(data: Optional[bytes]) -> Optional[str]:
    return None if data is None else base64.b64encode(data).decode()


def _unb64(text: Optional[str]) -> Optional[bytes]:
    return None if text is None else base64.b64decode(text)


# Value codecs are shared with the TCP peer protocol (kvstore/wire.py) so
# the ctrl API and peer wire format cannot drift apart
_value_to_json = wire.value_to_json
_value_from_json = wire.value_from_json


def _publication_to_json(pub: Publication) -> Dict[str, Any]:
    """Subscriber-facing publication: node_ids/tobe_updated_keys (peer-sync
    internals) are intentionally omitted."""
    return {
        "area": pub.area,
        "key_vals": wire.key_vals_to_json(pub.key_vals),
        "expired_keys": list(pub.expired_keys),
    }


def _encode_config(config) -> dict:
    """Serialize a Config's OpenrConfig dataclass tree to plain JSON."""
    import dataclasses

    def enc(obj):
        if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
            return {
                f.name: enc(getattr(obj, f.name))
                for f in dataclasses.fields(obj)
            }
        if isinstance(obj, (list, tuple)):
            return [enc(x) for x in obj]
        if hasattr(obj, "name") and hasattr(obj, "value"):
            return obj.name  # enum
        return obj

    return enc(config.config)


def _obj_to_json(obj: Any) -> Any:
    """Wire dataclasses ride the deterministic serializer as b64 blobs."""
    return _b64(serializer.dumps(obj))


class CtrlServer:
    def __init__(
        self,
        node_name: str,
        host: str = "127.0.0.1",
        port: int = 2018,
        *,
        kvstore=None,
        decision=None,
        fib=None,
        link_monitor=None,
        prefix_manager=None,
        monitor=None,
        exporter=None,
        config_store=None,
        config=None,
        stream_manager=None,
        admission=None,
        journal=None,
        route_updates=None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
        ssl_context=None,
        tls_acceptable_peers=None,
    ) -> None:
        self.node_name = node_name
        self.host = host
        self.port = port
        self._ssl_context = ssl_context
        self._tls_acceptable_peers = tls_acceptable_peers
        self.kvstore = kvstore
        self.decision = decision
        self.fib = fib
        self.link_monitor = link_monitor
        self.prefix_manager = prefix_manager
        self.monitor = monitor
        self.exporter = exporter
        self.config_store = config_store
        self.config = config
        # streaming control plane (docs/Streaming.md): in the daemon both
        # are built by openr.py and shared with the monitor; standalone
        # embeddings (tests, tools) get defaults built in start()
        self.stream_manager = stream_manager
        self.admission = admission
        self.journal = journal
        self._route_updates = route_updates
        self._own_stream_manager = False
        # on-demand jax profiling window (monitor/profiling.py), built
        # lazily by the first startProfile/getProfileStatus
        self._profile_controller = None
        self._loop = loop
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()
        self._methods: Dict[str, Callable] = {
            name[len("m_"):]: getattr(self, name)
            for name in dir(self)
            if name.startswith("m_")
        }

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self) -> int:
        if self.stream_manager is None and (
            self.kvstore is not None or self._route_updates is not None
        ):
            # standalone embedding: own a default-config fan-out layer
            from openr_tpu.streaming import StreamManager

            self.stream_manager = StreamManager(
                kvstore_updates=(
                    self.kvstore.updates_queue
                    if self.kvstore is not None
                    else None
                ),
                route_updates=self._route_updates,
                loop=self._loop,
            )
            self._own_stream_manager = True
        if self.stream_manager is not None and self._own_stream_manager:
            self.stream_manager.start()
        if self.admission is None:
            from openr_tpu.streaming import AdmissionController

            self.admission = AdmissionController()
        # request lines are one JSON document each; bulk writes (e.g. a
        # big setKvStoreKeyVals) overflow asyncio's default 64 KiB
        # readline limit — mirror the client's fleet-scale line limit
        from openr_tpu.ctrl.client import _LINE_LIMIT

        self._server = await asyncio.start_server(
            self._handle_conn,
            self.host,
            self.port,
            ssl=self._ssl_context,
            limit=_LINE_LIMIT,
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._profile_controller is not None:
            # a profiling window must not outlive the daemon it profiles
            self._profile_controller.stop()
        if self.stream_manager is not None and self._own_stream_manager:
            self.stream_manager.stop()
        if self._server is not None:
            self._server.close()
            # cancel in-flight handlers (streaming subscriptions block on
            # the kvstore updates reader and never see the socket close)
            for task in list(self._conn_tasks):
                task.cancel()
            await asyncio.gather(*self._conn_tasks, return_exceptions=True)
            self._conn_tasks.clear()
            await self._server.wait_closed()
            self._server = None

    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)
        if self._ssl_context is not None:
            from openr_tpu.utils.tls import enforce_acceptable_peer

            if not enforce_acceptable_peer(
                writer, self._tls_acceptable_peers, log, "ctrl"
            ):
                return
        try:
            while True:
                line = await reader.readline()
                if not line:
                    return
                if line.startswith((b"GET ", b"HEAD ")):
                    # plain HTTP-ish scrape handler: a stock Prometheus
                    # scraper (or curl) polling GET /metrics on the ctrl
                    # port gets a one-shot exposition response — no JSON
                    # request ever starts with an HTTP method line
                    await self._serve_http_scrape(line, reader, writer)
                    return
                try:
                    req = json.loads(line)
                    name = req.get("method", "")
                    method = self._methods.get(name)
                    if method is None:
                        resp = {
                            "id": req.get("id"),
                            "error": f"unknown method {name}",
                        }
                    else:
                        params = req.get("params") or {}
                        if self.admission is not None and (
                            self.admission.guards(name)
                        ):
                            # expensive RPC: weighted fair admission with
                            # bounded wait + typed server-busy rejection
                            # (docs/Streaming.md admission section)
                            result = await self.admission.run(
                                name,
                                self._client_id(writer, params),
                                lambda: method(params),
                            )
                        else:
                            result = method(params)
                        if asyncio.iscoroutine(result):
                            result = await result
                        if result is _STREAMING:
                            # streaming method wrote frames itself
                            continue
                        resp = {"id": req.get("id"), "result": result}
                except _Streaming as stream:
                    await stream.run(req.get("id"), writer)
                    continue
                except Exception as exc:  # per-request isolation
                    resp = {"id": req.get("id"), "error": str(exc)}
                    kind = getattr(exc, "error_kind", None)
                    if kind is not None:
                        # typed rejection (server_busy): clients back off
                        # on retry_after_ms instead of piling on
                        resp["error_kind"] = kind
                        retry = getattr(exc, "retry_after_ms", None)
                        if retry is not None:
                            resp["retry_after_ms"] = int(retry)
                    else:
                        log.exception("ctrl method failed")
                writer.write(json.dumps(resp).encode() + b"\n")
                await writer.drain()
        except (
            ConnectionResetError,
            BrokenPipeError,
            asyncio.CancelledError,
        ):
            pass  # client hung up (possibly mid-write): normal teardown
        finally:
            writer.close()

    # ------------------------------------------------------------------
    # identity / config
    # ------------------------------------------------------------------

    def m_getMyNodeName(self, params) -> str:
        return self.node_name

    def m_getBuildInfo(self, params) -> Dict[str, str]:
        """fb303 getBuildInfo equivalent (common/BuildInfo exportBuildInfo)."""
        from openr_tpu.utils.build_info import get_build_info

        return get_build_info()

    def m_getRunningConfig(self, params) -> Optional[dict]:
        if self.config is None:
            return None
        return _encode_config(self.config)

    def m_dryrunConfig(self, params) -> dict:
        """Validate a candidate config (JSON text) without applying it;
        returns the parsed config dict or raises
        (OpenrCtrl.thrift dryrunConfig)."""
        import json as _json

        from openr_tpu.config import Config

        text = params.get("file")
        if params.get("path"):
            with open(params["path"], "r") as fh:
                text = fh.read()
        return _encode_config(Config.from_dict(_json.loads(text)))

    def m_processKvStoreDualMessage(self, params) -> None:
        """Inject a DualMessages batch into the area's KvStore DUAL node
        (OpenrCtrl.thrift processKvStoreDualMessage)."""
        assert self.kvstore is not None
        from openr_tpu.dual import DualMessage, DualMessages, DualMessageType

        msgs = DualMessages(
            src_id=params["messages"]["src_id"],
            messages=[
                DualMessage(
                    dst_id=m["dst_id"],
                    distance=int(m["distance"]),
                    type=DualMessageType[m["type"]]
                    if isinstance(m["type"], str)
                    else DualMessageType(m["type"]),
                )
                for m in params["messages"]["messages"]
            ],
        )
        self.kvstore.handle_dual_messages(params.get("area", "0"), msgs)

    def m_getCounters(self, params) -> Dict[str, int]:
        if self.monitor is not None:
            return self.monitor.get_counters()
        counters: Dict[str, int] = {}
        for module in (self.decision, self.fib, self.link_monitor):
            if module is not None and hasattr(module, "counters"):
                counters.update(module.counters)
        return counters

    def m_getHistograms(self, params) -> Dict[str, Any]:
        """Merged latency histograms of every registered module
        (count/sum/avg/min/max + p50/p95/p99 per name) — the fb303
        exported-histogram surface next to getCounters. `reset: true`
        clears the sources after export (reset-on-read windowing, so
        dashboards can compute rates from consecutive snapshots)."""
        reset = bool(params.get("reset", False))
        if self.monitor is not None:
            return self.monitor.get_histograms(reset=reset)
        from openr_tpu.monitor import merge_module_histograms

        merged = merge_module_histograms(
            (
                m
                for m in (self.decision, self.fib, self.link_monitor)
                if m is not None
            ),
            reset=reset,
        )
        return {name: h.to_dict() for name, h in sorted(merged.items())}

    def m_getSolverHealth(self, params) -> Dict[str, Any]:
        """Solver fault-domain state: degraded flag, breaker state,
        probe/audit stats, last-solve timing gauges, flight-recorder ring
        + forensics state (docs/Robustness.md)."""
        assert self.decision is not None, "decision module not attached"
        return self.decision.get_solver_health()

    def m_getDeviceMemory(self, params) -> Dict[str, Any]:
        """Device-memory observatory read surface (docs/Monitoring.md
        "Device-memory observatory"): the resident-state ledger snapshot
        — per-structure live bytes, exact-accounting totals, watermark
        reconciliation, capacity verdict and last admission refusal.
        params: area (narrows the entry listing)."""
        assert self.decision is not None, "decision module not attached"
        return self.decision.get_device_memory(
            area=params.get("area") or None
        )

    def m_getSolveTraces(self, params) -> Dict[str, Any]:
        """Flight-recorder read surface (docs/Monitoring.md "Flight
        recorder & profiling"): per-area SolveTrace rings (event class,
        layout, warm/cold, per-phase ms on sampled solves), ring/eviction
        accounting, and the forensics-dump index. params: area (filter),
        last_n (most recent N)."""
        assert self.decision is not None, "decision module not attached"
        last_n = params.get("last_n")
        return self.decision.get_solve_traces(
            area=params.get("area") or None,
            last_n=int(last_n) if last_n is not None else None,
        )

    def _profiler(self):
        if getattr(self, "_profile_controller", None) is None:
            from openr_tpu.monitor.profiling import ProfileController

            self._profile_controller = ProfileController()
        return self._profile_controller

    def m_startProfile(self, params) -> Dict[str, Any]:
        """Open a bounded on-demand jax.profiler window writing a
        TensorBoard-compatible trace dir (`breeze decision profile`).
        Admission-controlled like the other expensive RPCs; degrade-safe:
        an unavailable profiler reports in-band, never raises. params:
        seconds (clamped to [0.1, 600]), out (directory; temp dir when
        omitted)."""
        controller = self._profiler()
        result = controller.start(
            out_dir=params.get("out") or params.get("out_dir"),
            seconds=float(params.get("seconds", 5.0)),
        )
        if result.get("started"):
            # arm the expiry on the daemon loop so the bound holds even
            # if no client ever polls getProfileStatus
            try:
                loop = self._loop or asyncio.get_event_loop()
                loop.call_later(
                    controller.seconds + 0.05, controller.maybe_expire
                )
            except RuntimeError:
                pass  # loop-less embedding: status()/start() still expire
        return result

    def m_getProfileStatus(self, params) -> Dict[str, Any]:
        """Live profiling-window state (active, out_dir, remaining_s,
        last_error)."""
        return self._profiler().status()

    def m_getConvergenceReport(self, params) -> Dict[str, Any]:
        """This node's convergence evidence — finished CONVERGENCE_TRACE
        spans, FLOOD_TRACE hop samples and kvstore flood stats — for the
        cross-node aggregation (`breeze perf report`,
        monitor/report.py:aggregate_convergence_reports)."""
        assert self.monitor is not None, "monitor module not attached"
        from openr_tpu.monitor.report import node_convergence_report

        return node_convergence_report(
            self.node_name, self.monitor, kvstore=self.kvstore
        )

    def m_getEventLogs(self, params) -> List[str]:
        if self.monitor is None:
            return []
        return [s.to_json() for s in self.monitor.get_event_logs()]

    def m_getMetricsText(self, params) -> str:
        """The full counter/histogram registry (plus the convergence
        rollup's cumulative-vs-windowed split) in Prometheus text
        exposition format — the `breeze monitor scrape` / GET /metrics
        surface (docs/Monitoring.md exporter section)."""
        return self._metrics_text()

    def _metrics_text(self) -> str:
        from openr_tpu.monitor import merge_module_histograms
        from openr_tpu.monitor.exporter import render_metrics_text

        if self.exporter is not None:
            return self.exporter.render()
        if self.monitor is not None:
            return render_metrics_text(
                self.monitor.get_counters(),
                self.monitor.get_cumulative_histograms(),
                node_name=self.node_name,
                rollup=getattr(self.monitor, "rollup", None),
            )
        # monitor-less fallback: render straight off the wired modules
        modules = [
            m
            for m in (self.decision, self.fib, self.link_monitor)
            if m is not None
        ]
        counters: Dict[str, int] = {}
        for module in modules:
            if hasattr(module, "counters"):
                counters.update(module.counters)
        return render_metrics_text(
            counters,
            merge_module_histograms(modules),
            node_name=self.node_name,
        )

    async def _serve_http_scrape(
        self,
        request_line: bytes,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
    ) -> None:
        """Minimal HTTP response for GET/HEAD /metrics on the ctrl port
        (one request per connection, then close — all a scraper needs)."""
        parts = request_line.decode(errors="replace").split()
        method = parts[0] if parts else "GET"
        path = parts[1] if len(parts) > 1 else "/"
        while True:  # drain request headers
            line = await reader.readline()
            if not line or line in (b"\r\n", b"\n"):
                break
        if path.split("?", 1)[0].rstrip("/") in ("", "/metrics"):
            try:
                body = self._metrics_text().encode()
                status = "200 OK"
            except Exception as exc:  # pragma: no cover - defensive
                log.exception("metrics render failed")
                body = f"metrics render failed: {exc}\n".encode()
                status = "500 Internal Server Error"
        else:
            body = b"only /metrics is served here\n"
            status = "404 Not Found"
        head = (
            f"HTTP/1.0 {status}\r\n"
            "Content-Type: text/plain; version=0.0.4; charset=utf-8\r\n"
            f"Content-Length: {len(body)}\r\n"
            "Connection: close\r\n\r\n"
        ).encode()
        writer.write(head if method == "HEAD" else head + body)
        await writer.drain()

    # ------------------------------------------------------------------
    # route APIs
    # ------------------------------------------------------------------

    def m_getRouteDb(self, params) -> Dict[str, Any]:
        assert self.fib is not None, "fib module not attached"
        db = self.fib.get_route_db()
        return {
            "this_node_name": db["this_node_name"],
            "unicast_routes": [_obj_to_json(r) for r in db["unicast_routes"]],
            "mpls_routes": [_obj_to_json(r) for r in db["mpls_routes"]],
        }

    def m_getRouteDbComputed(self, params) -> Dict[str, Any]:
        assert self.decision is not None, "decision module not attached"
        node = params.get("node") or None
        db = self.decision.get_decision_route_db(node)
        unicast = []
        mpls = []
        if db is not None:
            unicast = [
                _obj_to_json(e.to_unicast_route())
                for e in db.unicast_entries.values()
            ]
            mpls = [
                _obj_to_json(e.to_mpls_route())
                for e in db.mpls_entries.values()
            ]
        return {
            "this_node_name": node or self.node_name,
            "unicast_routes": unicast,
            "mpls_routes": mpls,
        }

    def m_getUnicastRoutesFiltered(self, params) -> List[Any]:
        assert self.fib is not None
        routes = self.fib.get_unicast_routes(params.get("prefixes"))
        return [_obj_to_json(r) for r in routes]

    def m_getUnicastRoutes(self, params) -> List[Any]:
        return self.m_getUnicastRoutesFiltered({})

    def m_getMplsRoutesFiltered(self, params) -> List[Any]:
        assert self.fib is not None
        routes = self.fib.get_mpls_routes(params.get("labels"))
        return [_obj_to_json(r) for r in routes]

    def m_getMplsRoutes(self, params) -> List[Any]:
        return self.m_getMplsRoutesFiltered({})

    def m_getPerfDb(self, params) -> List[Any]:
        assert self.fib is not None
        return [_obj_to_json(p) for p in self.fib.get_perf_db()]

    # ------------------------------------------------------------------
    # decision APIs
    # ------------------------------------------------------------------

    def m_getDecisionAdjacencyDbs(self, params) -> Dict[str, Any]:
        assert self.decision is not None
        return {
            node: _obj_to_json(db)
            for node, db in self.decision.get_adjacency_databases().items()
        }

    def m_getAllDecisionAdjacencyDbs(self, params) -> List[Any]:
        """Deprecated list form of getDecisionAdjacencyDbs
        (OpenrCtrl.thrift getAllDecisionAdjacencyDbs)."""
        assert self.decision is not None
        return [
            _obj_to_json(db)
            for _, db in sorted(
                self.decision.get_adjacency_databases().items()
            )
        ]

    def m_getDecisionPrefixDbs(self, params) -> Dict[str, Any]:
        assert self.decision is not None
        return {
            f"{node}:{area}": _obj_to_json(db)
            for (
                node,
                area,
            ), db in self.decision.get_prefix_databases().items()
        }

    def m_runTeOptimize(self, params) -> Dict[str, Any]:
        """What-if gradient-descent TE optimization over the live LSDB
        (docs/TrafficEngineering.md): proposes link-metric changes plus
        the predicted hard-SPF max-link-utilization delta; programs
        nothing. params: demands (spec dict), steps, scenarios, area,
        seed, plus optimizer knobs (lr, tau0, tau_min, ...)."""
        assert self.decision is not None, "decision module not attached"
        return self.decision.run_te_optimize(params or {})

    def m_setRibPolicy(self, params) -> None:
        assert self.decision is not None
        from openr_tpu.solver.rib_policy import RibPolicy

        policy = RibPolicy.from_dict(params["policy"])
        self.decision.set_rib_policy(policy)

    def m_getRibPolicy(self, params) -> Optional[dict]:
        assert self.decision is not None
        policy = self.decision.get_rib_policy()
        return None if policy is None else policy.to_dict()

    # ------------------------------------------------------------------
    # prefix manager APIs
    # ------------------------------------------------------------------

    def _parse_prefix_entries(self, blobs: List[str]):
        return [serializer.loads(_unb64(b)) for b in blobs]

    def m_advertisePrefixes(self, params) -> bool:
        assert self.prefix_manager is not None
        return self.prefix_manager.advertise_prefixes(
            self._parse_prefix_entries(params["prefixes"])
        )

    def m_withdrawPrefixes(self, params) -> bool:
        assert self.prefix_manager is not None
        return self.prefix_manager.withdraw_prefixes(
            self._parse_prefix_entries(params["prefixes"])
        )

    def m_withdrawPrefixesByType(self, params) -> bool:
        assert self.prefix_manager is not None
        from openr_tpu.types import PrefixType

        return self.prefix_manager.withdraw_prefixes_by_type(
            PrefixType(params["type"])
        )

    def m_syncPrefixesByType(self, params) -> bool:
        assert self.prefix_manager is not None
        from openr_tpu.types import PrefixType

        return self.prefix_manager.sync_prefixes_by_type(
            PrefixType(params["type"]),
            self._parse_prefix_entries(params["prefixes"]),
        )

    def m_getPrefixes(self, params) -> List[Any]:
        assert self.prefix_manager is not None
        return [_obj_to_json(e) for e in self.prefix_manager.get_prefixes()]

    def m_getPrefixesByType(self, params) -> List[Any]:
        assert self.prefix_manager is not None
        from openr_tpu.types import PrefixType

        return [
            _obj_to_json(e)
            for e in self.prefix_manager.get_prefixes_by_type(
                PrefixType(params["type"])
            )
        ]

    # ------------------------------------------------------------------
    # kvstore APIs
    # ------------------------------------------------------------------

    def m_getKvStoreKeyVals(self, params) -> Dict[str, Any]:
        assert self.kvstore is not None
        area = params.get("area", "0")
        keys = params.get("keys", [])
        pub = self.kvstore.db(area).get_key_vals(keys)
        return _publication_to_json(pub)

    def m_getKvStoreKeyValsFiltered(self, params) -> Dict[str, Any]:
        assert self.kvstore is not None
        from openr_tpu.kvstore import KvStoreFilters

        area = params.get("area", "0")
        filters = KvStoreFilters(
            key_prefixes=params.get("prefixes") or [],
            originator_ids=set(params.get("originators") or []),
        )
        pub = self.kvstore.dump_all(area=area, filters=filters)
        return _publication_to_json(pub)

    def m_getKvStoreHashFiltered(self, params) -> Dict[str, Any]:
        assert self.kvstore is not None
        from openr_tpu.kvstore import KvStoreFilters

        area = params.get("area", "0")
        filters = KvStoreFilters(
            key_prefixes=params.get("prefixes") or []
        )
        pub = self.kvstore.db(area).dump_hashes(filters)
        return _publication_to_json(pub)

    def m_setKvStoreKeyVals(self, params) -> None:
        assert self.kvstore is not None
        area = params.get("area", "0")
        key_vals: KeyVals = {
            k: _value_from_json(v)
            for k, v in params.get("key_vals", {}).items()
        }
        self.kvstore.db(area).set_key_vals(key_vals)

    def m_getKvStorePeers(self, params) -> Dict[str, Any]:
        assert self.kvstore is not None
        area = params.get("area", "0")
        return {
            name: {"peer_addr": spec.peer_addr}
            for name, spec in self.kvstore.db(area).get_peers().items()
        }

    def m_getKvStorePeerHealth(self, params) -> Dict[str, Any]:
        """Peer-health quarantine ladder snapshot (docs/Runbook.md:
        `breeze kvstore peer-health`)."""
        assert self.kvstore is not None
        area = params.get("area", "0")
        return self.kvstore.db(area).get_peer_health()

    def m_getAreasConfig(self, params) -> Dict[str, Any]:
        assert self.kvstore is not None
        return {"areas": sorted(self.kvstore.dbs.keys())}

    def m_getSpanningTreeInfos(self, params) -> Dict[str, Any]:
        """OpenrCtrl.thrift getSpanningTreeInfos:375 — DUAL SPT state."""
        assert self.kvstore is not None
        area = params.get("area", "0")
        return self.kvstore.db(area).get_spt_infos()

    def m_updateFloodTopologyChild(self, params) -> None:
        """OpenrCtrl.thrift updateFloodTopologyChild:367."""
        assert self.kvstore is not None
        area = params.get("area", "0")
        self.kvstore.db(area).handle_flood_topo_set(
            params["root_id"],
            params["src_id"],
            bool(params["set_child"]),
            bool(params.get("all_roots", False)),
        )

    def m_longPollKvStoreAdj(self, params):
        """Block until any adj: key differs from the client's snapshot
        (OpenrCtrl.thrift:353, OpenrCtrlLongPollTest)."""
        assert self.kvstore is not None
        area = params.get("area", "0")
        snapshot: Dict[str, int] = params.get("snapshot", {})
        timeout = float(params.get("timeout_s", 20.0))

        def adj_changed() -> bool:
            pub = self.kvstore.dump_all(area=area)
            current = {
                k: v.version
                for k, v in pub.key_vals.items()
                if k.startswith(ADJ_DB_MARKER)
            }
            for key, version in current.items():
                if snapshot.get(key, -1) < version:
                    return True
            return any(k not in current for k in snapshot)

        async def wait() -> bool:
            if adj_changed():
                return True
            reader = self.kvstore.updates_queue.get_reader()
            loop = asyncio.get_event_loop()
            deadline = loop.time() + timeout
            try:
                while loop.time() < deadline:
                    try:
                        pub = await asyncio.wait_for(
                            reader.get(), deadline - loop.time()
                        )
                    except (asyncio.TimeoutError, QueueClosedError):
                        return False
                    if pub.area != area:
                        continue
                    if any(
                        k.startswith(ADJ_DB_MARKER)
                        for k in list(pub.key_vals) + pub.expired_keys
                    ):
                        return True
                return False
            finally:
                reader.close()

        return wait()

    def m_subscribeKvStoreFilter(self, params):
        """Server-streaming KvStore subscription
        (OpenrCtrlHandler.h:207-211): initial full dump frame, then every
        matching publication as a stream frame. Legacy frame shape (bare
        publication JSON); rides the same bounded fan-out as
        subscribeKvStore — an overflow resync arrives as a full-dump
        publication, which per-key merge clients absorb unmarked."""
        assert self.kvstore is not None
        if self.stream_manager is not None:
            self.stream_manager.ensure_capacity()
        raise _Streaming(self._kvstore_stream_legacy, params)

    def m_subscribeKvStore(self, params):
        """Streaming KvStore delta subscription (docs/Streaming.md):
        typed frames {"type": "snapshot"|"delta"|"resync", "seq": N,
        "pub": {...}} — initial full-sync snapshot, then per-publication
        deltas (key-prefix/originator filtered), with marked
        snapshot-resyncs after bounded fan-out overflow.
        params: area, prefixes, originators, client (fairness label)."""
        assert self.kvstore is not None
        if self.stream_manager is not None:
            # typed server-busy BEFORE the stream starts: the rejection
            # rides the normal error response with retry_after_ms
            self.stream_manager.ensure_capacity()
        raise _Streaming(self._kvstore_stream, params)

    def m_subscribeRouteDb(self, params):
        """Streaming RIB subscription (docs/Streaming.md): initial
        computed-RIB snapshot, then every DecisionRouteUpdate the
        DeltaPath emits, with marked snapshot-resyncs after overflow.
        Frames: {"type": ..., "seq": N, "unicast_to_update": [b64...],
        "unicast_to_delete": [...], "mpls_to_update": [...],
        "mpls_to_delete": [...]}; snapshots/resyncs carry the full RIB
        in the *_to_update fields."""
        assert self.decision is not None
        if self.stream_manager is not None:
            self.stream_manager.ensure_capacity()
        raise _Streaming(self._route_stream, params)

    def m_getStreamStats(self, params) -> Dict[str, Any]:
        """Live fan-out + admission state (docs/Streaming.md)."""
        out: Dict[str, Any] = {}
        if self.stream_manager is not None:
            out["stream"] = self.stream_manager.stats()
        if self.admission is not None:
            out["admission"] = self.admission.stats()
        return out

    # -- state journal (docs/Journal.md) --------------------------------

    def _journal_or_error(self) -> Any:
        if self.journal is None or not self.journal.config.enabled:
            return None
        return self.journal

    def m_getJournalStats(self, params) -> Dict[str, Any]:
        """Journal ring/base/durable-log state + journal.* counters."""
        journal = self._journal_or_error()
        if journal is None:
            return {"enabled": False}
        return journal.stats()

    def m_getJournalTail(self, params) -> Dict[str, Any]:
        """Most recent journal records, raw (forensics attachment +
        `breeze` debugging). params: last_n."""
        journal = self._journal_or_error()
        if journal is None:
            return {"enabled": False, "records": []}
        return {
            "enabled": True,
            "records": journal.tail(int(params.get("last_n", 32))),
        }

    def m_getKvStoreKeyHistory(self, params) -> Dict[str, Any]:
        """Bounded publication history of one key (`breeze kvstore
        history <key>`). params: key (required), area (filter)."""
        journal = self._journal_or_error()
        if journal is None:
            return {"enabled": False, "history": []}
        key = params.get("key")
        assert key, "key is required"
        return {
            "enabled": True,
            "key": key,
            "history": journal.key_history(
                key, area=params.get("area") or None
            ),
        }

    def m_getRibDiff(self, params) -> Dict[str, Any]:
        """RIB delta between two replayed instants (`breeze decision
        rib-diff --from T1 --to T2`). params: from_ts / to_ts — unix
        seconds, negative = relative to now, absent = latest."""
        journal = self._journal_or_error()
        if journal is None:
            return {"enabled": False}
        from_ts = params.get("from_ts")
        to_ts = params.get("to_ts")
        out = journal.rib_diff(
            float(from_ts) if from_ts is not None else None,
            float(to_ts) if to_ts is not None else None,
        )
        out["enabled"] = True
        return out

    def m_verifyJournalReplay(self, params) -> Dict[str, Any]:
        """Standing correctness audit: replay(T) vs the CPU oracle over
        the reconstructed LSDB. params: at."""
        journal = self._journal_or_error()
        if journal is None:
            return {"enabled": False}
        at = params.get("at")
        out = journal.verify_replay(
            float(at) if at is not None else None
        )
        out["enabled"] = True
        return out

    def m_explainRoute(self, params) -> Dict[str, Any]:
        """Provenance chain: route → contributing prefix/adjacency keys →
        originating publication → (when sampled) the SolveTrace that
        computed it. params: prefix (required), at."""
        journal = self._journal_or_error()
        if journal is None:
            return {"enabled": False, "found": False}
        prefix = params.get("prefix")
        assert prefix, "prefix is required"
        at = params.get("at")
        out = journal.explain_route(
            prefix, float(at) if at is not None else None
        )
        out["enabled"] = True
        # link the nearest sampled SolveTrace at-or-before the replayed
        # instant (the flight recorder lives in Decision, not the journal)
        out["solve_trace"] = None
        if self.decision is not None and out.get("found"):
            at_ts = out.get("at_ts") or time.time()
            traces = self.decision.get_solve_traces().get("traces", [])
            best = None
            for trace in traces:
                ts = trace.get("ts")
                if ts is None or ts > at_ts:
                    continue
                if best is None or ts > best.get("ts", 0.0):
                    best = trace
            out["solve_trace"] = best
            if self.config is not None:
                out["rib_policy_active"] = bool(
                    self.config.config.enable_rib_policy
                )
        return out

    def _client_id(self, writer, params) -> str:
        """Admission fairness identity: the client-declared label when
        present (breeze --client), else the peer address."""
        label = params.get("client")
        if label:
            return str(label)
        peer = writer.get_extra_info("peername")
        return str(peer[0]) if peer else "unknown"

    def _kv_snapshot(self, area, prefixes, originators) -> Publication:
        from openr_tpu.kvstore import KvStoreFilters

        filters = None
        if prefixes or originators:
            filters = KvStoreFilters(
                key_prefixes=list(prefixes or []),
                originator_ids=set(originators or ()),
            )
        return self.kvstore.dump_all(area=area, filters=filters)

    def _encode_body(self, encode, *args) -> bytes:
        """One PRIVATE body serialization (snapshot, resync, or a
        coalesced per-subscriber frame) — metered as a real encode so
        `ctrl.stream.encode_*` stays the full serialization bill; the
        shared path meters its class encodes in `SharedFrame.body`."""
        t0 = time.perf_counter()
        body = encode(*args)
        if self.stream_manager is not None:
            self.stream_manager.note_encode(
                (time.perf_counter() - t0) * 1e3, len(body)
            )
        return body

    async def _write_frame(
        self, writer, segments, drain: bool = True, legacy_path: bool = False
    ) -> None:
        """Per-subscriber delivery: splice the envelope around the
        (possibly shared) body in ONE transport write — writev-style,
        `writelines` joins the segments once instead of issuing one
        socket send per segment. `ctrl.stream.deliver_*` meters exactly
        this work; the drain (socket backpressure, a slow client's
        stall) stays outside it. Callers delivering a burst pass
        `drain=False` while the subscriber queue still holds frames and
        drain once at burst end — the buffered bytes stay bounded by
        `subscriber_max_pending` frames, and a stalled client still
        blocks its own task at the burst-end drain, nobody else's.

        `legacy_path` (the `stream_config.shared_encode: false` A/B
        baseline / rollback, docs/Streaming.md) restores the
        pre-sharing delivery verbatim: one transport write per segment
        and an unconditional per-frame drain — so the before/after
        meters compare the old serving path against the new one, not a
        half-upgraded hybrid."""
        t0 = time.perf_counter()
        if legacy_path:
            total = 0
            for seg in segments:
                writer.write(seg)
                total += len(seg)
        else:
            writer.writelines(segments)
            total = sum(len(seg) for seg in segments)
        if self.stream_manager is not None:
            self.stream_manager.note_deliver(
                (time.perf_counter() - t0) * 1e3, total
            )
        if drain or legacy_path:
            await writer.drain()

    async def _ack_codec(self, writer, req_id, codec_name) -> None:
        """Codec negotiation (docs/Streaming.md): one JSON ack line, then
        every frame on this stream is length-prefixed binary. A server
        without binary support never sends the ack, so old clients and
        old servers both fall back to newline-JSON gracefully."""
        writer.write(
            json.dumps({"id": req_id, "codec": codec_name}).encode() + b"\n"
        )
        await writer.drain()

    async def _deliver_gate(self, sub) -> None:
        """Per-frame delivery seam: the `ctrl.stream.deliver` fault point
        (ctx=subscription) fires here — an armed exception tears the
        stream down (the client reconnects and resyncs), an armed action
        may set `sub.throttle_s` to emulate a slow client; the throttle
        is consumed one-shot per frame."""
        fault_point("ctrl.stream.deliver", sub)
        delay, sub.throttle_s = sub.throttle_s, 0.0
        if delay:
            await asyncio.sleep(delay)

    async def _kvstore_stream(
        self, req_id, writer, params, legacy: bool = False
    ) -> None:
        assert self.stream_manager is not None, "stream manager not wired"
        from openr_tpu.streaming import SharedFrame
        from openr_tpu.streaming import codec as stream_codec

        area = params.get("area", "0")
        prefixes = params.get("prefixes") or []
        originators = params.get("originators") or []
        # legacy streams stay newline-JSON (the debug/compat path);
        # unknown codec names degrade to JSON, never error
        codec_name = stream_codec.CODEC_JSON
        if not legacy:
            codec_name = stream_codec.normalize_codec(params.get("codec"))
        sub = self.stream_manager.add_kvstore_subscriber(
            area=area,
            prefixes=prefixes,
            originators=set(originators),
            label=str(params.get("client") or ""),
        )
        # shared_encode=false is the A/B baseline: serve exactly the way
        # the pre-sharing code did (see _write_frame)
        legacy_delivery = not self.stream_manager.config.shared_encode
        try:
            if codec_name == stream_codec.CODEC_BINARY:
                await self._ack_codec(writer, req_id, codec_name)
            # register-then-snapshot: a publication landing between the
            # two shows up in the snapshot AND as a delta — per-key
            # version merge makes the replay idempotent, nothing is lost
            snapshot = self._kv_snapshot(area, prefixes, originators)
            seq = 0
            body = self._encode_body(
                stream_codec.encode_kv_body, snapshot, codec_name
            )
            await self._write_frame(
                writer,
                stream_codec.kv_frame_segments(
                    codec_name, req_id, "snapshot", seq, area, body, legacy
                ),
            )
            while True:
                kind, frame, t_enq = await sub.next_frame()
                if kind == "closed":
                    return
                await self._deliver_gate(sub)
                seq += 1
                if kind == "resync":
                    # per-subscriber state: a fresh marked snapshot,
                    # encoded privately — it re-enters the shared path
                    # once the class re-converges on live deltas
                    pub = self._kv_snapshot(area, prefixes, originators)
                    body = self._encode_body(
                        stream_codec.encode_kv_body, pub, codec_name
                    )
                elif isinstance(frame, SharedFrame):
                    # the shared path: bytes encoded once per
                    # filter-equivalence class, reused here
                    body = frame.body(codec_name)
                else:
                    # coalesced merges (and the shared_encode=false
                    # path) are per-subscriber state: private encode
                    body = self._encode_body(
                        stream_codec.encode_kv_body, frame, codec_name
                    )
                # burst-drain: while the queue holds more frames, keep
                # splicing into the transport buffer and drain once at
                # burst end (bounded by subscriber_max_pending frames)
                await self._write_frame(
                    writer,
                    stream_codec.kv_frame_segments(
                        codec_name, req_id, kind, seq, area, body, legacy
                    ),
                    drain=not (sub._frames or sub._resync_at is not None),
                    legacy_path=legacy_delivery,
                )
                self.stream_manager.mark_delivered(sub, t_enq)
        # CancelledError must PROPAGATE: server shutdown cancels this
        # connection task mid-stream, and swallowing the cancel here sent
        # the task back into _handle_conn's readline — stop()'s gather
        # then waited forever on a subscriber that never hangs up
        except (
            QueueClosedError,
            ConnectionResetError,
            BrokenPipeError,
        ):
            pass
        finally:
            self.stream_manager.remove_subscriber(sub)

    async def _kvstore_stream_legacy(self, req_id, writer, params) -> None:
        await self._kvstore_stream(req_id, writer, params, legacy=True)

    def _route_db_fields(self) -> Dict[str, Any]:
        """Full computed RIB as the four route-list fields of a
        snapshot/resync frame body."""
        db = self.decision.get_decision_route_db(None)
        unicast = mpls = []
        if db is not None:
            unicast = [
                _obj_to_json(e.to_unicast_route())
                for e in db.unicast_entries.values()
            ]
            mpls = [
                _obj_to_json(e.to_mpls_route())
                for e in db.mpls_entries.values()
            ]
        return {
            "unicast_to_update": unicast,
            "unicast_to_delete": [],
            "mpls_to_update": mpls,
            "mpls_to_delete": [],
        }

    async def _route_stream(self, req_id, writer, params) -> None:
        assert self.stream_manager is not None, "stream manager not wired"
        from openr_tpu.streaming import SharedFrame
        from openr_tpu.streaming import codec as stream_codec

        codec_name = stream_codec.normalize_codec(params.get("codec"))
        sub = self.stream_manager.add_route_subscriber(
            label=str(params.get("client") or "")
        )
        legacy_delivery = not self.stream_manager.config.shared_encode
        try:
            if codec_name == stream_codec.CODEC_BINARY:
                await self._ack_codec(writer, req_id, codec_name)
            seq = 0
            body = self._encode_body(
                stream_codec.encode_route_body,
                self._route_db_fields(),
                codec_name,
            )
            await self._write_frame(
                writer,
                stream_codec.route_frame_segments(
                    codec_name, req_id, "snapshot", seq, body
                ),
            )
            while True:
                kind, frame, t_enq = await sub.next_frame()
                if kind == "closed":
                    return
                await self._deliver_gate(sub)
                seq += 1
                if kind == "resync":
                    body = self._encode_body(
                        stream_codec.encode_route_body,
                        self._route_db_fields(),
                        codec_name,
                    )
                elif isinstance(frame, SharedFrame):
                    body = frame.body(codec_name)
                else:
                    body = self._encode_body(
                        stream_codec.encode_route_body,
                        stream_codec.route_fields_from_update(frame),
                        codec_name,
                    )
                await self._write_frame(
                    writer,
                    stream_codec.route_frame_segments(
                        codec_name, req_id, kind, seq, body
                    ),
                    drain=not (sub._frames or sub._resync_at is not None),
                    legacy_path=legacy_delivery,
                )
                self.stream_manager.mark_delivered(sub, t_enq)
        # CancelledError must propagate (see _kvstore_stream)
        except (
            QueueClosedError,
            ConnectionResetError,
            BrokenPipeError,
        ):
            pass
        finally:
            self.stream_manager.remove_subscriber(sub)

    # ------------------------------------------------------------------
    # link monitor APIs (drain / metric overrides)
    # ------------------------------------------------------------------

    def m_setNodeOverload(self, params) -> None:
        assert self.link_monitor is not None
        self.link_monitor.set_node_overload(True)

    def m_unsetNodeOverload(self, params) -> None:
        assert self.link_monitor is not None
        self.link_monitor.set_node_overload(False)

    def m_setInterfaceOverload(self, params) -> None:
        assert self.link_monitor is not None
        self.link_monitor.set_link_overload(params["interface"], True)

    def m_unsetInterfaceOverload(self, params) -> None:
        assert self.link_monitor is not None
        self.link_monitor.set_link_overload(params["interface"], False)

    def m_setInterfaceMetric(self, params) -> None:
        assert self.link_monitor is not None
        self.link_monitor.set_link_metric(
            params["interface"], int(params["metric"])
        )

    def m_unsetInterfaceMetric(self, params) -> None:
        assert self.link_monitor is not None
        self.link_monitor.set_link_metric(params["interface"], None)

    def m_setAdjacencyMetric(self, params) -> None:
        assert self.link_monitor is not None
        self.link_monitor.set_adjacency_metric(
            params["interface"],
            params["adjNodeName"],
            int(params["metric"]),
        )

    def m_unsetAdjacencyMetric(self, params) -> None:
        assert self.link_monitor is not None
        self.link_monitor.set_adjacency_metric(
            params["interface"], params["adjNodeName"], None
        )

    def m_getInterfaces(self, params) -> Dict[str, Any]:
        assert self.link_monitor is not None
        return {
            name: {
                "is_up": e.is_up,
                "is_active": e.is_active(),
                "addresses": list(e.addresses),
            }
            for name, e in self.link_monitor.get_interfaces().items()
        }

    def m_getLinkMonitorAdjacencies(self, params) -> List[Any]:
        assert self.link_monitor is not None
        return [
            _obj_to_json(adj)
            for adj in self.link_monitor.get_adjacencies().values()
        ]

    # ------------------------------------------------------------------
    # config-store APIs
    # ------------------------------------------------------------------

    def m_setConfigKey(self, params) -> None:
        assert self.config_store is not None
        self.config_store.store(params["key"], _unb64(params["value"]))

    def m_eraseConfigKey(self, params) -> bool:
        assert self.config_store is not None
        return self.config_store.erase(params["key"])

    def m_getConfigKey(self, params) -> Optional[str]:
        assert self.config_store is not None
        return _b64(self.config_store.load(params["key"]))


class _Streaming(Exception):
    """Raised by streaming methods; _handle_conn runs the stream."""

    def __init__(self, fn, params) -> None:
        super().__init__("streaming")
        self.fn = fn
        self.params = params

    async def run(self, req_id, writer) -> None:
        await self.fn(req_id, writer, self.params)


_STREAMING = object()
