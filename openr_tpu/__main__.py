"""Daemon entrypoint: `python -m openr_tpu [flags | --config file]`.

The openr_bin equivalent (openr/Main.cpp:154): parses the legacy flag set
or a thrift-JSON config file (openr_tpu/config/flags.py), wires the real
transports — UDP multicast discovery for Spark, TCP peering for KvStore —
and runs the daemon until SIGINT/SIGTERM, shutting modules down in reverse
order (Main.cpp:597-654 semantics, OpenrDaemon.stop).
"""

from __future__ import annotations

import asyncio
import logging
import signal
import sys


def main(argv=None) -> int:
    from openr_tpu.config.flags import parse_flags
    from openr_tpu.openr import OpenrDaemon
    from openr_tpu.spark.io_provider import UdpIoProvider
    from openr_tpu.kvstore import TcpTransport
    from openr_tpu.utils.build_info import get_build_info

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s] %(message)s",
    )
    config, args = parse_flags(argv)
    info = get_build_info()
    logging.info(
        "starting %s %s node=%s",
        info["build_package_name"],
        info["build_package_version"],
        config.node_name,
    )

    async def run() -> int:
        c = config.config
        daemon = OpenrDaemon(
            config,
            io_provider=UdpIoProvider(
                port=c.spark_config.neighbor_discovery_port
            ),
            kv_transport=TcpTransport(),
            config_store_path=args.config_store_filepath,
            ctrl_port=c.openr_ctrl_port,
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop.set)
        await daemon.start()
        logging.info("all modules up; ctrl port %d", c.openr_ctrl_port)
        await stop.wait()
        logging.info("shutting down")
        await daemon.stop()
        return 0

    return asyncio.run(run())


if __name__ == "__main__":
    sys.exit(main())
