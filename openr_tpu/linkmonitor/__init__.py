"""LinkMonitor: links, adjacencies, peering, drain state.

Equivalent of openr/link-monitor/LinkMonitor.{h,cpp}.
"""

from openr_tpu.linkmonitor.link_monitor import (
    InterfaceEntry,
    LinkMonitor,
    LinkMonitorConfig,
)

__all__ = ["InterfaceEntry", "LinkMonitor", "LinkMonitorConfig"]
