"""LinkMonitor: interface tracking with flap dampening, Spark-event →
adjacency translation, KvStore advertisement and peering.

Behavioral port of openr/link-monitor/LinkMonitor.{h,cpp}:
  - InterfaceEntry with exponential-backoff link-flap dampening
    (link-monitor/InterfaceEntry.h); only stably-up interfaces are handed
    to Spark (advertiseInterfaces LinkMonitor.cpp:726).
  - neighborUpEvent/neighborDownEvent (LinkMonitor.cpp:373,453): neighbor
    events become Adjacency entries; adjacency database advertised under
    'adj:<node>' via the KvStore client's persist semantics
    (advertiseAdjacencies LinkMonitor.cpp:625-700).
  - KvStore peering follows established neighbors (advertiseKvStorePeers
    LinkMonitor.cpp:542-623).
  - drain/overload controls: node overload, per-link overload (soft
    drain), per-link metric override — all re-advertised immediately and
    persisted in the config store when provided.
  - RTT-vs-hop metric choice (enable_rtt_metric).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from openr_tpu.kvstore.client import KvStoreClient
from openr_tpu.kvstore.store import KvStore, PeerSpec
from openr_tpu.messaging import QueueClosedError, RQueue
from openr_tpu.spark.spark import NeighborEvent, NeighborEventType
from openr_tpu.types import (
    Adjacency,
    AdjacencyDatabase,
    PerfEvent,
    PerfEvents,
    adj_key,
)
from openr_tpu.utils import ExponentialBackoff, AsyncThrottle
from openr_tpu.utils.ownership import owned_by
from openr_tpu.utils.counters import CountersMixin, HistogramsMixin
from openr_tpu.utils import serializer

# PerfEvent names stamped onto the advertised AdjacencyDatabase so REMOTE
# nodes can reconstruct the origin's pre-publish span stages (wall clock —
# the only clock that crosses nodes; Decision maps them back onto its
# monotonic Span, decision.py:_PRE_STAGE_EVENTS)
NEIGHBOR_EVENT_RECVD = "NEIGHBOR_EVENT_RECVD"
ADJ_DB_ADVERTISED = "ADJ_DB_ADVERTISED"

# config-store keys (LinkMonitor.h kConfigKey equivalent)
CONFIG_KEY = "link-monitor-config"


@dataclass
class LinkMonitorConfig:
    node_name: str
    node_label: int = 0
    enable_rtt_metric: bool = False
    flap_initial_backoff: float = 0.06  # 60ms
    flap_max_backoff: float = 1.0
    adv_throttle: float = 0.005  # advertisement coalescing window
    areas: List[str] = field(default_factory=lambda: ["0"])
    # KvStore peer addressing (createPeerSpec, LinkMonitor.cpp:60-74):
    # "node_id"  — in-process transport, peers addressed by node name
    # "tcp"      — real sockets: "host:port" from the Spark handshake's
    #              transport address + kvstore_cmd_port
    peer_addr_mode: str = "node_id"


class InterfaceEntry:
    """Interface with flap-dampening backoff (InterfaceEntry.h)."""

    def __init__(self, if_name: str, backoff: ExponentialBackoff) -> None:
        self.if_name = if_name
        self.is_up = False
        self.backoff = backoff
        self.addresses: List[str] = []

    def update(self, is_up: bool) -> bool:
        """Returns True if state changed."""
        changed = self.is_up != is_up
        if changed:
            self.is_up = is_up
            # every transition is an error event for dampening purposes
            self.backoff.report_error()
        return changed

    def is_active(self) -> bool:
        """Up and out of the dampening window."""
        return self.is_up and self.backoff.can_try_now()


@dataclass
class _AdjacencyEntry:
    adjacency: Adjacency
    area: str
    is_restarting: bool = False
    peer_addr: str = ""  # KvStore transport address for this neighbor


@owned_by("link-monitor-loop")
class LinkMonitor(CountersMixin, HistogramsMixin):
    def __init__(
        self,
        config: LinkMonitorConfig,
        neighbor_events: RQueue,
        kvstore: KvStore,
        spark,  # Spark instance (update_interfaces target)
        config_store=None,  # optional PersistentStore-like (dict interface)
        interface_updates_queue=None,  # ReplicateQueue[InterfaceDatabase]
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.config = config
        self.neighbor_events = neighbor_events
        self.kvstore = kvstore
        # config_store attaches the warm-boot version floors: after a
        # graceful restart the re-advertised 'adj:<node>' key strictly
        # supersedes the replicas peers held through the GR window
        self.kvstore_client = KvStoreClient(
            kvstore, config.node_name, loop, config_store=config_store
        )
        self.spark = spark
        self.config_store = config_store
        self.interface_updates_queue = interface_updates_queue
        self._loop = loop

        self.interfaces: Dict[str, InterfaceEntry] = {}
        # (node, local iface) -> adjacency entry
        self.adjacencies: Dict[Tuple[str, str], _AdjacencyEntry] = {}
        self.node_overloaded = False
        self.overloaded_links: Set[str] = set()
        self.link_metric_overrides: Dict[str, int] = {}
        # (local iface, adjacent node) -> metric; wins over the link-wide
        # override (LinkMonitor.cpp setAdjacencyMetric)
        self.adj_metric_overrides: Dict[Tuple[str, str], int] = {}

        self._load_state()
        self._adv_throttle = AsyncThrottle(
            config.adv_throttle, self._advertise, loop=loop
        )
        self._iface_timer: Optional[asyncio.TimerHandle] = None
        self._task: Optional[asyncio.Task] = None
        self.counters: Dict[str, int] = {}
        self.histograms: Dict = {}
        # oldest un-advertised Spark event stamp (monotonic): the throttled
        # _advertise() coalesces a burst of neighbor events into one adj-db
        # write, and the convergence span — like Decision's debounce rule —
        # measures from the FIRST event of the burst
        self._pending_event_ts: Optional[float] = None

    def loop(self) -> asyncio.AbstractEventLoop:
        return self._loop or asyncio.get_event_loop()

    def start(self) -> None:
        self._task = self.loop().create_task(self._run())

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        self._adv_throttle.cancel()
        if self._iface_timer is not None:
            self._iface_timer.cancel()
            self._iface_timer = None
        self.kvstore_client.stop()

    # ------------------------------------------------------------------
    # durable drain state (PersistentStore seam)
    # ------------------------------------------------------------------

    def _load_state(self) -> None:
        if self.config_store is None:
            return
        blob = self.config_store.load(CONFIG_KEY)
        if blob is None:
            return
        state = serializer.loads(blob)
        self.node_overloaded = state.get("node_overloaded", False)
        self.overloaded_links = set(state.get("overloaded_links", []))
        self.link_metric_overrides = dict(
            state.get("link_metric_overrides", {})
        )
        self.adj_metric_overrides = {
            tuple(key.split("|", 1)): metric
            for key, metric in state.get("adj_metric_overrides", {}).items()
        }

    def _save_state(self) -> None:
        if self.config_store is None:
            return
        self.config_store.store(
            CONFIG_KEY,
            serializer.dumps(
                {
                    "node_overloaded": self.node_overloaded,
                    "overloaded_links": sorted(self.overloaded_links),
                    "link_metric_overrides": dict(
                        self.link_metric_overrides
                    ),
                    "adj_metric_overrides": {
                        f"{iface}|{node}": metric
                        for (iface, node), metric
                        in self.adj_metric_overrides.items()
                    },
                }
            ),
        )

    # ------------------------------------------------------------------
    # interface events (netlink seam)
    # ------------------------------------------------------------------

    def update_interface(self, if_name: str, is_up: bool) -> None:
        """Apply a link event from the platform (netlink) layer."""
        entry = self.interfaces.get(if_name)
        if entry is None:
            entry = InterfaceEntry(
                if_name,
                ExponentialBackoff(
                    self.config.flap_initial_backoff,
                    self.config.flap_max_backoff,
                ),
            )
            # a fresh interface starts clean: no dampening on first up
            self.interfaces[if_name] = entry
            entry.is_up = is_up
            self._advertise_interfaces_when_stable()
            return
        if entry.update(is_up):
            self._bump("link_monitor.link_flap")
            self._advertise_interfaces_when_stable()

    def _advertise_interfaces_when_stable(self) -> None:
        """Push the active interface set to Spark, re-checking when
        dampening windows expire (single re-evaluation timer, no pile-up)."""
        if self._iface_timer is not None:
            self._iface_timer.cancel()
            self._iface_timer = None
        active = [e.if_name for e in self.interfaces.values() if e.is_active()]
        self.spark.update_interfaces(active)
        if self.interface_updates_queue is not None:
            # publish raw (un-dampened) status so Fib can shrink ECMP groups
            # immediately on a down event (LinkMonitor.cpp:726-749 →
            # interfaceUpdatesQueue consumed by Fib::processInterfaceDb)
            from openr_tpu.types import InterfaceDatabase, InterfaceInfo

            self.interface_updates_queue.push(
                InterfaceDatabase(
                    self.config.node_name,
                    {
                        e.if_name: InterfaceInfo(
                            is_up=e.is_up, networks=tuple(e.addresses)
                        )
                        for e in self.interfaces.values()
                    },
                )
            )
        # schedule re-evaluation at the earliest backoff expiry
        pending = [
            e.backoff.get_time_remaining_until_retry()
            for e in self.interfaces.values()
            if e.is_up and not e.backoff.can_try_now()
        ]
        if pending:
            self._iface_timer = self.loop().call_later(
                min(pending) + 0.001, self._advertise_interfaces_when_stable
            )

    # ------------------------------------------------------------------
    # neighbor events
    # ------------------------------------------------------------------

    async def _run(self) -> None:
        while True:
            try:
                event = await self.neighbor_events.get()
            except (QueueClosedError, asyncio.CancelledError):
                return
            try:
                self._process_neighbor_event(event)
            except Exception:
                import logging

                logging.getLogger(__name__).exception(
                    "failed to process neighbor event"
                )
                self._bump("link_monitor.errors")

    def _process_neighbor_event(self, event: NeighborEvent) -> None:
        if event.event_type == NeighborEventType.NEIGHBOR_UP:
            self._neighbor_up(event)
        elif event.event_type == NeighborEventType.NEIGHBOR_RESTARTED:
            self._neighbor_up(event)
        elif event.event_type == NeighborEventType.NEIGHBOR_DOWN:
            self._neighbor_down(event)
        elif event.event_type == NeighborEventType.NEIGHBOR_RESTARTING:
            entry = self.adjacencies.get(
                (event.node_name, event.local_if_name)
            )
            if entry is not None:
                entry.is_restarting = True
            # keep adjacency + peering during graceful restart
        elif event.event_type == NeighborEventType.NEIGHBOR_RTT_CHANGE:
            if self.config.enable_rtt_metric:
                entry = self.adjacencies.get(
                    (event.node_name, event.local_if_name)
                )
                if entry is not None:
                    entry.adjacency = self._make_adjacency(event)
                    self._note_event_ts(event)
                    self._adv_throttle()

    def _note_event_ts(self, event: NeighborEvent) -> None:
        """Keep the oldest pending Spark event stamp for the next
        advertisement's span stages."""
        ts = event.ts_monotonic
        if not ts:
            return
        if self._pending_event_ts is None or ts < self._pending_event_ts:
            self._pending_event_ts = ts

    def _metric_for(self, event: NeighborEvent) -> int:
        adj_override = self.adj_metric_overrides.get(
            (event.local_if_name, event.node_name)
        )
        if adj_override is not None:
            return adj_override
        if self.config.enable_rtt_metric and event.rtt_us > 0:
            # rtt-based metric: microseconds / 100 (getRttMetric)
            return max(1, event.rtt_us // 100)
        metric = 1
        override = self.link_metric_overrides.get(event.local_if_name)
        if override is not None:
            metric = override
        return metric

    def _make_adjacency(self, event: NeighborEvent) -> Adjacency:
        return Adjacency(
            other_node_name=event.node_name,
            if_name=event.local_if_name,
            other_if_name=event.remote_if_name,
            metric=self._metric_for(event),
            adj_label=event.label,
            is_overloaded=event.local_if_name in self.overloaded_links,
            rtt=event.rtt_us,
            nexthop_v4=event.transport_address_v4,
            nexthop_v6=event.transport_address_v6,
        )

    def _peer_addr_for(self, event: NeighborEvent) -> str:
        """KvStore transport address for a discovered neighbor."""
        if self.config.peer_addr_mode == "tcp":
            # fall back to v4 first: a v6 transport address is typically
            # link-local (fe80::) whose scope id cannot ride "host:port"
            host = (
                event.kvstore_host
                or event.transport_address_v4
                or event.transport_address_v6
            )
            return f"{host}:{event.kvstore_cmd_port}"
        return event.node_name

    def _neighbor_up(self, event: NeighborEvent) -> None:
        self._bump("link_monitor.neighbor_up")
        area = event.area or "0"
        self.adjacencies[(event.node_name, event.local_if_name)] = (
            _AdjacencyEntry(
                self._make_adjacency(event),
                area,
                peer_addr=self._peer_addr_for(event),
            )
        )
        self._advertise_kvstore_peers()
        self._note_event_ts(event)
        self._adv_throttle()

    def _neighbor_down(self, event: NeighborEvent) -> None:
        self._bump("link_monitor.neighbor_down")
        self.adjacencies.pop((event.node_name, event.local_if_name), None)
        self._advertise_kvstore_peers()
        self._note_event_ts(event)
        self._adv_throttle()

    # ------------------------------------------------------------------
    # advertisement
    # ------------------------------------------------------------------

    def _advertise_kvstore_peers(self) -> None:
        """Sync KvStore peering with the adjacency set
        (advertiseKvStorePeers LinkMonitor.cpp:542-623)."""
        for area in self.config.areas:
            desired: Dict[str, PeerSpec] = {}
            for (node, _), entry in self.adjacencies.items():
                if entry.area != area:
                    continue
                desired[node] = PeerSpec(peer_addr=entry.peer_addr or node)
            current = self.kvstore.dbs[area].get_peers()
            to_del = [n for n in current if n not in desired]
            to_add = {
                n: spec for n, spec in desired.items() if current.get(n) != spec
            }
            if to_del:
                self.kvstore.del_peers(to_del, area=area)
            if to_add:
                self.kvstore.add_peers(to_add, area=area)

    def _advertise(self) -> None:
        """Build + persist 'adj:<node>' per area (advertiseAdjacencies).

        Convergence tracing: the oldest pending Spark event stamp becomes
        the first span stage (spark.neighbor_event), this advertisement the
        second (linkmonitor.adj_advertised) — both handed through the
        KvStore write as monotonic Publication.span_stages for the LOCAL
        span, and mirrored as wall-clock PerfEvents on the AdjacencyDatabase
        so remote nodes can reconstruct the same stages after the flood.
        """
        event_ts = self._pending_event_ts
        self._pending_event_ts = None
        adv_ts = time.monotonic()
        span_stages = None
        perf_events = None
        if event_ts is not None:
            self._observe(
                "link_monitor.adj_advertise_ms", (adv_ts - event_ts) * 1e3
            )
            span_stages = [
                ("spark.neighbor_event", event_ts),
                ("linkmonitor.adj_advertised", adv_ts),
            ]
            now_wall_ms = time.time() * 1e3
            perf_events = PerfEvents(
                [
                    # wall stamps derived from the monotonic deltas so both
                    # clocks tell the same story
                    PerfEvent(
                        self.config.node_name,
                        NEIGHBOR_EVENT_RECVD,
                        now_wall_ms - (adv_ts - event_ts) * 1e3,
                    ),
                    PerfEvent(
                        self.config.node_name, ADJ_DB_ADVERTISED, now_wall_ms
                    ),
                ]
            )
        for area in self.config.areas:
            adjacencies = [
                entry.adjacency
                for (node, _), entry in sorted(self.adjacencies.items())
                if entry.area == area
            ]
            adj_db = AdjacencyDatabase(
                this_node_name=self.config.node_name,
                adjacencies=adjacencies,
                is_overloaded=self.node_overloaded,
                node_label=self.config.node_label,
                area=area,
                perf_events=perf_events,
            )
            self.kvstore_client.persist_key(
                adj_key(self.config.node_name),
                serializer.dumps(adj_db),
                area=area,
                span_stages=span_stages,
            )
            self._bump("link_monitor.advertise_adj_db")

    # ------------------------------------------------------------------
    # drain / overload controls (OpenrCtrl surface)
    # ------------------------------------------------------------------

    # analysis: shared — sync ctrl handler, loop-serialized with the owner
    def set_node_overload(self, overloaded: bool) -> None:
        if self.node_overloaded != overloaded:
            self.node_overloaded = overloaded
            self._save_state()
            self._adv_throttle()

    # analysis: shared — sync ctrl handler, loop-serialized with the owner
    def set_link_overload(self, if_name: str, overloaded: bool) -> None:
        changed = (
            if_name not in self.overloaded_links
            if overloaded
            else if_name in self.overloaded_links
        )
        if overloaded:
            self.overloaded_links.add(if_name)
        else:
            self.overloaded_links.discard(if_name)
        if changed:
            self._save_state()
            self._rebuild_adjacencies()
            self._adv_throttle()

    # analysis: shared — sync ctrl handler, loop-serialized with the owner
    def set_link_metric(self, if_name: str, metric: Optional[int]) -> None:
        if metric is None:
            self.link_metric_overrides.pop(if_name, None)
        else:
            self.link_metric_overrides[if_name] = metric
        self._save_state()
        self._rebuild_adjacencies()
        self._adv_throttle()

    # analysis: shared — sync ctrl handler, loop-serialized with the owner
    def set_adjacency_metric(
        self, if_name: str, adj_node: str, metric: Optional[int]
    ) -> None:
        """Per-adjacency metric override; wins over set_link_metric
        (LinkMonitor.cpp setAdjacencyMetric/unsetAdjacencyMetric)."""
        if metric is None:
            self.adj_metric_overrides.pop((if_name, adj_node), None)
        else:
            self.adj_metric_overrides[(if_name, adj_node)] = metric
        self._save_state()
        self._rebuild_adjacencies()
        self._adv_throttle()

    def _rebuild_adjacencies(self) -> None:
        from openr_tpu.types import replace

        for key, entry in self.adjacencies.items():
            adj = entry.adjacency
            metric = adj.metric
            if not self.config.enable_rtt_metric:
                metric = self.link_metric_overrides.get(adj.if_name, 1)
            adj_override = self.adj_metric_overrides.get(
                (adj.if_name, adj.other_node_name)
            )
            if adj_override is not None:
                metric = adj_override
            entry.adjacency = replace(
                adj,
                metric=metric,
                is_overloaded=adj.if_name in self.overloaded_links,
            )

    def get_interfaces(self) -> Dict[str, InterfaceEntry]:
        return self.interfaces

    def get_adjacencies(self) -> Dict[Tuple[str, str], Adjacency]:
        return {k: e.adjacency for k, e in self.adjacencies.items()}

