"""Topology-as-code builders for tests and benchmarks.

Equivalent of the fixture builders in openr/decision/tests/DecisionTestUtils.h
(createGrid, createAdjacency) and the grid/fabric generators in
openr/decision/tests/DecisionBenchmark.cpp:640-728 (grid n×n; 3-tier fabric
with ssw spines per plane and fsw/rsw pods).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from openr_tpu.types import Adjacency, AdjacencyDatabase

Edge = Tuple[str, str, int]  # (node_a, node_b, metric)


def make_adj_pair(
    a: str, b: str, metric_ab: int = 1, metric_ba: Optional[int] = None
) -> Tuple[Adjacency, Adjacency]:
    """Two directed adjacencies forming one bidirectional link a<->b.

    Interface naming convention: 'if-<local>-<remote>' so every (node, iface)
    pair is unique, letting parallel links between the same node pair use
    explicit interface names instead.
    """
    import zlib

    def _h(s: str) -> int:  # hash-seed-independent digest
        return zlib.crc32(s.encode())

    if_ab = f"if-{a}-{b}"
    if_ba = f"if-{b}-{a}"
    adj_a = Adjacency(
        other_node_name=b,
        if_name=if_ab,
        other_if_name=if_ba,
        metric=metric_ab,
        nexthop_v6=f"fe80::{_h(b) % 0xFFFF:x}",
        nexthop_v4=f"169.254.{_h(b) % 255}.{_h(if_ba) % 255}",
    )
    adj_b = Adjacency(
        other_node_name=a,
        if_name=if_ba,
        other_if_name=if_ab,
        metric=metric_ba if metric_ba is not None else metric_ab,
        nexthop_v6=f"fe80::{_h(a) % 0xFFFF:x}",
        nexthop_v4=f"169.254.{_h(a) % 255}.{_h(if_ab) % 255}",
    )
    return adj_a, adj_b


def build_adj_dbs(
    edges: List[Edge],
    area: str = "0",
    node_labels: bool = True,
    overloaded_nodes: Optional[set] = None,
) -> Dict[str, AdjacencyDatabase]:
    """Build per-node AdjacencyDatabases from an undirected edge list."""
    adjs: Dict[str, List[Adjacency]] = {}
    for edge in edges:
        a, b, metric = edge
        adj_a, adj_b = make_adj_pair(a, b, metric)
        adjs.setdefault(a, []).append(adj_a)
        adjs.setdefault(b, []).append(adj_b)
    overloaded = overloaded_nodes or set()
    dbs = {}
    for i, node in enumerate(sorted(adjs)):
        dbs[node] = AdjacencyDatabase(
            this_node_name=node,
            adjacencies=adjs.get(node, []),
            area=area,
            node_label=(i + 100) if node_labels else 0,
            is_overloaded=node in overloaded,
        )
    return dbs


def grid_edges(n: int, metric: int = 1) -> List[Edge]:
    """n×n grid; node name 'g<row>_<col>' (DecisionBenchmark grid topology)."""
    edges: List[Edge] = []
    for r in range(n):
        for c in range(n):
            if c + 1 < n:
                edges.append((f"g{r}_{c}", f"g{r}_{c+1}", metric))
            if r + 1 < n:
                edges.append((f"g{r}_{c}", f"g{r+1}_{c}", metric))
    return edges


def ring_edges(n: int, metric: int = 1) -> List[Edge]:
    return [(f"r{i}", f"r{(i + 1) % n}", metric) for i in range(n)]


def fabric_edges(
    pods: int,
    planes: int = 4,
    ssw_per_plane: int = 9,
    fsw_per_pod: int = 8,
    rsw_per_pod: int = 48,
) -> List[Edge]:
    """3-tier Clos fabric (DecisionBenchmark.cpp:51-56 style):
    rsw (rack) — fsw (fabric, per pod) — ssw (spine, per plane).
    fsw i in each pod connects to all ssw of plane (i mod planes)."""
    edges: List[Edge] = []
    for p in range(pods):
        for f in range(fsw_per_pod):
            fsw = f"fsw{p}_{f}"
            for r in range(rsw_per_pod):
                edges.append((fsw, f"rsw{p}_{r}", 1))
            plane = f % planes
            for s in range(ssw_per_plane):
                edges.append((fsw, f"ssw{plane}_{s}", 1))
    return edges


def wan_edges(n: int, degree: int = 4, seed: int = 0) -> List[Edge]:
    """Synthetic WAN: ring + deterministic pseudo-random chords with varied
    metrics (connected, degree ≈ 2+chords)."""
    import random

    rng = random.Random(seed)
    edges = [
        (f"w{i}", f"w{(i + 1) % n}", rng.randint(1, 100)) for i in range(n)
    ]
    seen = {(min(i, (i + 1) % n), max(i, (i + 1) % n)) for i in range(n)}
    available_pairs = n * (n - 1) // 2 - len(seen)
    target_chords = min(n * max(0, degree - 2) // 2, available_pairs)
    while len(edges) < n + target_chords:
        a, b = rng.randrange(n), rng.randrange(n)
        if a == b:
            continue
        key = (min(a, b), max(a, b))
        if key in seen:
            continue
        seen.add(key)
        edges.append((f"w{a}", f"w{b}", rng.randint(1, 100)))
    return edges
