"""Topology-churn soak harness (the FastReChain-style scenario).

Reconfigurable fabrics (OCS-based, https://arxiv.org/pdf/2507.12265)
don't fail one link at a time — they retune in *waves*: bulk link
add/remove batches land together, repeatedly, for hours, while ordinary
faults keep firing underneath. `run_soak` drives a long-running
`VirtualNetwork` through exactly that:

  - a **base line topology** n0–n1–…–n(k-1) that is never touched (the
    graph stays connected, so convergence is always well-defined), plus
    a pool of **chord links** (i, i+2) standing in for the optical
    circuit inventory;
  - scheduled **reconfiguration waves**: each wave removes a batch of
    currently-up chords and adds a batch of currently-down ones (the
    OCS bulk add/remove), then waits for the adjacency view and routes
    to settle;
  - a **chaos overlay**: on designated waves, `testing/faults.py`
    schedules fire at the production fault seams (fib.program,
    kvstore.flood_send, spark.packet_send, ...) while the wave is in
    flight, and the harness records the wall-clock fault intervals for
    window attribution;
  - a **scrape loop**: after every wave each node's exporter renders the
    Prometheus exposition; the harness parses it back, times the render,
    and checks counter monotonicity + registry coverage — the continuous
    telemetry path exercised end to end, not just at shutdown;
  - a **judged report**: per-window convergence trend (p50/p95/max from
    the eviction-proof rollup), fault-vs-clean attribution, and a
    verdict block whose checks include the no-eviction-loss invariant
    (rollup events == spans Fib ever closed, even though the LogSample
    rings only hold the tail) and a monotonic-regression test over the
    windowed p95 series.

`run_soak_smoke` is the SOAK_SMOKE tier-1 mode (seconds, not hours):
a 3-node line, one wave, one injected fault, a deliberately tiny
`max_event_log` so ring eviction provably happens — asserting the whole
verdict machinery runs end to end. `python -m openr_tpu.testing.soak`
runs a configurable soak and writes the JSON report
(`breeze perf soak-report` renders it).
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import time
from dataclasses import asdict, dataclass
from typing import Any, Dict, List, Optional, Tuple

from openr_tpu.monitor.exporter import (
    CounterEpochTracker,
    parse_metrics_text,
    prom_name,
)
from openr_tpu.monitor.report import (
    ConvergenceRollup,
    merge_rollup_snapshots,
    percentile_summary,
)
from openr_tpu.testing.faults import FaultInjector, injected
from openr_tpu.utils.counters import Histogram


@dataclass
class SoakConfig:
    nodes: int = 6
    waves: int = 4
    wave_links: int = 1  # chords added + chords removed per wave
    settle_s: float = 1.0  # dwell after each wave before scraping
    converge_timeout_s: float = 60.0
    # chaos overlay: every fault_every-th wave runs with armed schedules
    # (0 disables); fault_budget bounds firings per chaos wave
    fault_every: int = 2
    fault_budget: int = 2
    fault_probability: float = 0.5
    # restart waves: every restart_every-th wave additionally restarts a
    # random interior node through VirtualNetwork.restart_node (graceful
    # restart + warm boot — the whole-node churn class; 0 disables).
    # Nodes get per-run configstore files and GR enabled when armed.
    restart_every: int = 0
    # partition waves: every partition_every-th wave asymmetrically
    # blackholes one direction of a random line edge through the chaos
    # mesh (testing/chaos.py) for partition_hold_s, then heals — the
    # verdict gains `partitions_recovered` (convergence returns after
    # heal) and `flood_health_attributed` (no fleet flood_health breach
    # outside a fault/partition interval); 0 disables
    partition_every: int = 0
    partition_hold_s: float = 0.5
    seed: int = 7
    # telemetry knobs pushed into every node's monitor_config
    max_event_log: int = 100
    window_s: float = 1.0
    max_windows: int = 600
    # streaming scrape mode (docs/Streaming.md): every node gets a
    # `subscribeKvStore` adj-delta subscription over its real ctrl
    # socket, wave scrapes trigger on stream activity instead of a poll,
    # and the report gains a `stream` section (frames/resyncs per node)
    stream_scrapes: bool = False
    # attach the fleet observer (openr_tpu/fleet) to the run over the
    # real ctrl sockets: continuous scrape+stream collection + the SLO
    # watchdog; the judged report gains a `fleet` section with the
    # observer's verdict embedded (docs/Monitoring.md "Fleet observer")
    fleet_observer: bool = False
    fleet_budget_ms: float = 2000.0  # convergence p95 SLO for the watchdog
    fleet_interval_s: float = 0.5


def _chord_pool(n: int) -> List[Tuple[int, int]]:
    return [(i, i + 2) for i in range(n - 2)]


def _chord_ifaces(a: int, b: int) -> Tuple[str, str]:
    return f"s{a}_{b}a", f"s{a}_{b}b"


class _ScrapeLog:
    """Per-node scrape bookkeeping: render latency, parse errors, counter
    monotonicity (the exporter's cumulative view must never go
    backwards), registry coverage (every counter/histogram the monitor
    knows must appear in the exposition).

    Restart waves are first-class, not forgiven ad hoc: `note_restart`
    opens a restart window for a node, and within it (a) a node that
    dies mid-scrape is *attributed* to the restart (`restart_attributed`)
    instead of failing scrape health, and (b) the post-boot counter
    reset is consumed as a typed epoch (`CounterEpochTracker`,
    monitor/exporter.py) counted in `epoch_resets`. A counter decrease
    with no restart window to blame is still a monotonicity violation —
    the check the typed epoch sharpens rather than waters down."""

    def __init__(self) -> None:
        self.count = 0
        self.errors = 0
        self.monotonic_violations = 0
        self.coverage_misses = 0
        self.restart_attributed = 0
        self.epoch_resets = 0
        self.render_ms: List[float] = []
        self._epochs = CounterEpochTracker()
        self._restarting: set = set()

    def note_restart(self, node: str) -> None:
        """A controlled restart of `node` is in flight: attribute the
        next scrape failure and/or counter epoch to it."""
        self._restarting.add(node)

    def scrape(self, node: str, daemon) -> None:
        self.count += 1
        try:
            # registry snapshot BEFORE the render: the exporter's own
            # overhead metrics are recorded during the render itself, so
            # (like Prometheus's scrape_duration) they appear one scrape
            # late — the exported set must be a superset of this snapshot
            expected = {
                prom_name(name) for name in daemon.monitor.get_counters()
            }
            expected.update(
                prom_name(name) + "_count"
                for name in daemon.monitor.get_cumulative_histograms()
            )
            t0 = time.perf_counter()
            text = daemon.exporter.render()
            self.render_ms.append((time.perf_counter() - t0) * 1e3)
            parsed = parse_metrics_text(text)
        except Exception:
            # a node that died mid-scrape (connection refused / stopped
            # daemon) during its restart window is expected churn
            if node in self._restarting:
                self.restart_attributed += 1
            else:
                self.errors += 1
            return
        obs = self._epochs.observe(node, dict(parsed["counters"]))
        if obs["reset"]:
            if node in self._restarting:
                self.epoch_resets += 1
                self._restarting.discard(node)
            else:
                self.monotonic_violations += len(obs["decreased"])
        self.coverage_misses += len(expected - set(parsed["samples"]))

    def summary(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "errors": self.errors,
            "monotonic_violations": self.monotonic_violations,
            "coverage_misses": self.coverage_misses,
            "restart_attributed": self.restart_attributed,
            "epoch_resets": self.epoch_resets,
            "render_ms": percentile_summary(self.render_ms),
        }


def _window_overlaps(
    start: float, width: float, intervals: List[Tuple[float, float]]
) -> bool:
    end = start + width
    return any(t0 < end and start < t1 for t0, t1 in intervals)


def series_slope(series: List[float]) -> float:
    """Least-squares slope (ms per window) of a windowed series — the
    drift detector: a sustained positive slope over a long soak means
    convergence latency is trending up even if no single window broke."""
    n = len(series)
    if n < 2:
        return 0.0
    xs = range(n)
    mean_x = (n - 1) / 2.0
    mean_y = sum(series) / n
    num = sum((x - mean_x) * (y - mean_y) for x, y in zip(xs, series))
    den = sum((x - mean_x) ** 2 for x in xs)
    return num / den if den else 0.0


def detect_step(
    series: List[float],
    *,
    min_side: int = 2,
    min_ratio: float = 2.0,
    min_delta_ms: float = 5.0,
) -> Optional[Dict[str, float]]:
    """Step-change detector over a windowed p95 series: the split point
    maximizing the after-mean/before-mean jump, reported only when the
    jump clears BOTH a relative (`min_ratio`) and an absolute
    (`min_delta_ms`) threshold with at least `min_side` windows on each
    side — double-gating keeps µs-scale emulator noise from flagging.
    Returns {"index", "before_ms", "after_ms", "ratio"} or None."""
    n = len(series)
    best: Optional[Dict[str, float]] = None
    for split in range(min_side, n - min_side + 1):
        before = series[:split]
        after = series[split:]
        mean_b = sum(before) / len(before)
        mean_a = sum(after) / len(after)
        delta = mean_a - mean_b
        if delta < min_delta_ms:
            continue
        ratio = mean_a / mean_b if mean_b > 0 else float("inf")
        if ratio < min_ratio:
            continue
        if best is None or delta > best["after_ms"] - best["before_ms"]:
            best = {
                "index": split,
                "before_ms": round(mean_b, 3),
                "after_ms": round(mean_a, 3),
                "ratio": round(ratio, 3) if ratio != float("inf") else -1.0,
            }
    return best


def analyze_trend(
    windows: List[Dict[str, Any]],
    stage_series: Dict[str, List[float]],
    fault_intervals: List[Tuple[float, float]],
    window_s: float,
) -> Dict[str, Any]:
    """The sharpened soak judge: windowed p95 slope + step detection on
    the end-to-end series, with per-stage attribution of a detected
    break — the stages whose own p95 series step at (or within one
    window of) the same split are the likely cause, turning "p95 got
    worse" into "fib.program regressed at wave 7"."""
    p95_series = [w["e2e_p95_ms"] for w in windows if w["events"]]
    live = [w for w in windows if w["events"]]
    trend: Dict[str, Any] = {
        "windows": len(p95_series),
        "p95_slope_ms_per_window": round(series_slope(p95_series), 4),
        "step": None,
        "attributed_stages": [],
    }
    step = detect_step(p95_series)
    if step is not None:
        idx = int(step["index"])
        window = live[min(idx, len(live) - 1)]
        step["window_start"] = window["start"]
        step["faulted"] = _window_overlaps(
            window["start"], window_s, fault_intervals
        )
        trend["step"] = step
        for stage, series in sorted(stage_series.items()):
            stage_step = detect_step(series)
            if stage_step is not None and abs(
                int(stage_step["index"]) - idx
            ) <= 1:
                trend["attributed_stages"].append(
                    {"stage": stage, **stage_step}
                )
    return trend


def _judge(
    merged: Dict[str, Any],
    fault_intervals: List[Tuple[float, float]],
    *,
    fib_spans_closed: int,
    spans_in_rings: int,
    waves: List[Dict[str, Any]],
    scrapes: Dict[str, Any],
    fleet_findings: Optional[List[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """Fold the merged rollup + wave/scrape evidence into the judged
    sections of the soak report (windows, attribution, verdict)."""
    window_s = merged["window_s"] or 1.0
    windows = []
    clean = Histogram()
    faulted = Histogram()
    clean_windows = faulted_windows = 0
    p95_series: List[float] = []
    stage_series: Dict[str, List[float]] = {}
    for window in merged["windows"]:
        total = window["stages"].get(ConvergenceRollup.TOTAL_STAGE)
        is_faulted = _window_overlaps(
            window["start"], window_s, fault_intervals
        )
        stats = (total or Histogram()).to_dict()
        windows.append(
            {
                "start": window["start"],
                "events": window["events"],
                "faulted": is_faulted,
                "e2e_p50_ms": stats["p50"],
                "e2e_p95_ms": stats["p95"],
                "e2e_max_ms": stats["max"],
            }
        )
        if total is not None and window["events"]:
            p95_series.append(stats["p95"])
            # aligned per-stage p95 series (0.0-filled where a stage had
            # no samples) so a step in the e2e series can be attributed
            # to the pipeline stage that broke at the same window
            seen = set()
            for stage, hist in window["stages"].items():
                if stage == ConvergenceRollup.TOTAL_STAGE:
                    continue
                seen.add(stage)
                stage_series.setdefault(
                    stage, [0.0] * (len(p95_series) - 1)
                ).append(hist.percentile(95))
            for stage, series in stage_series.items():
                if stage not in seen:
                    series.append(0.0)
            if is_faulted:
                faulted.merge(total)
                faulted_windows += 1
            else:
                clean.merge(total)
                clean_windows += 1
    trend = analyze_trend(windows, stage_series, fault_intervals, window_s)

    checks: Dict[str, Dict[str, Any]] = {}

    def check(name: str, ok: bool, detail: str) -> None:
        checks[name] = {"ok": bool(ok), "detail": detail}

    windowed = sum(w["events"] for w in merged["windows"])
    accounted = windowed + merged["evicted_events"]
    check(
        "windowed_accounting",
        accounted == merged["events_total"],
        f"windows hold {windowed} + {merged['evicted_events']} evicted "
        f"of {merged['events_total']} events",
    )
    check(
        "no_eviction_loss",
        merged["events_total"] == fib_spans_closed,
        f"rollup counted {merged['events_total']} of {fib_spans_closed} "
        f"spans Fib closed (rings retain only {spans_in_rings})",
    )
    check(
        "waves_converged",
        all(w["converged"] for w in waves),
        f"{sum(1 for w in waves if w['converged'])}/{len(waves)} waves "
        f"converged within deadline",
    )
    partition_waves = [w for w in waves if w.get("partitioned")]
    check(
        "partitions_recovered",
        all(w["converged"] for w in partition_waves),
        f"{sum(1 for w in partition_waves if w['converged'])}/"
        f"{len(partition_waves)} partition wave(s) re-converged after "
        f"heal",
    )
    flood = [
        f
        for f in (fleet_findings or [])
        if f.get("kind") == "flood_health"
    ]
    unattributed = [
        f
        for f in flood
        if not _window_overlaps(
            float(f.get("ts") or 0.0), 0.0, fault_intervals
        )
    ]
    check(
        "flood_health_attributed",
        not unattributed,
        f"{len(flood)} flood_health breach(es), {len(unattributed)} "
        f"outside any fault/partition interval",
    )
    check(
        "scrape_health",
        scrapes["errors"] == 0
        and scrapes["monotonic_violations"] == 0
        and scrapes["coverage_misses"] == 0,
        f"{scrapes['count']} scrapes, {scrapes['errors']} errors, "
        f"{scrapes['monotonic_violations']} monotonicity violations, "
        f"{scrapes['coverage_misses']} registry-coverage misses",
    )
    regression = len(p95_series) >= 3 and all(
        b > a for a, b in zip(p95_series, p95_series[1:])
    )
    check(
        "no_monotonic_regression",
        not regression,
        f"windowed e2e p95 trend over {len(p95_series)} non-empty "
        f"window(s): "
        + "/".join(f"{v:.1f}" for v in p95_series[:16]),
    )
    step = trend["step"]
    clean_break = step is not None and not step["faulted"]
    check(
        "no_clean_trend_break",
        not clean_break,
        (
            "no p95 step break detected"
            if step is None
            else (
                f"p95 step at window {step['index']} "
                f"({step['before_ms']:.1f} -> {step['after_ms']:.1f}ms, "
                f"{'fault-attributed' if step['faulted'] else 'CLEAN'}"
                + (
                    ", stages: "
                    + ",".join(
                        s["stage"] for s in trend["attributed_stages"]
                    )
                    if trend["attributed_stages"]
                    else ""
                )
                + f"); slope "
                f"{trend['p95_slope_ms_per_window']:+.3f}ms/window"
            )
        ),
    )
    from openr_tpu.utils.build_info import (
        ARTIFACT_SCHEMA_VERSION,
        build_fingerprint,
    )

    return {
        "schema_version": ARTIFACT_SCHEMA_VERSION,
        "build": build_fingerprint(),
        "windows": windows,
        "trend": trend,
        "attribution": {
            "clean_windows": clean_windows,
            "faulted_windows": faulted_windows,
            "clean_e2e_ms": clean.to_dict(),
            "faulted_e2e_ms": faulted.to_dict(),
        },
        "cumulative_e2e_ms": (
            merged["cumulative"]
            .get(ConvergenceRollup.TOTAL_STAGE, Histogram())
            .to_dict()
        ),
        "verdict": {
            "pass": all(c["ok"] for c in checks.values()),
            "checks": checks,
        },
    }


def run_soak(
    cfg: SoakConfig, arm_chaos=None
) -> Dict[str, Any]:
    """Run one soak to completion; returns the judged report dict.

    `arm_chaos(injector, wave_index, cfg)` overrides the default chaos
    schedule armed on fault waves (the smoke uses it to inject exactly
    one deterministic fault)."""
    from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

    n = max(3, cfg.nodes)
    rng = random.Random(cfg.seed)
    chords = _chord_pool(n)
    chord_state: Dict[Tuple[int, int], str] = {c: "new" for c in chords}

    def default_chaos(inj: FaultInjector, wave: int, _cfg) -> None:
        inj.arm("fib.program", times=1)
        inj.arm(
            "kvstore.flood_send",
            probability=_cfg.fault_probability,
            times=_cfg.fault_budget,
        )

    arm = arm_chaos if arm_chaos is not None else default_chaos

    async def body(store_dir: Optional[str]) -> Dict[str, Any]:
        mesh = None
        if cfg.partition_every > 0:
            from openr_tpu.testing.chaos import ChaosMesh

            mesh = ChaosMesh(seed=cfg.seed)
        net = VirtualNetwork(chaos=mesh)
        overrides: Dict[str, Any] = {
            "monitor_config": {
                "max_event_log": cfg.max_event_log,
                "rollup_window_s": cfg.window_s,
                "rollup_max_windows": cfg.max_windows,
            }
        }
        if cfg.restart_every:
            # restart waves need graceful restart on the wire and a
            # durable configstore per node (warm-boot version floors)
            overrides["spark_config"] = {"graceful_restart_enabled": True}
        for i in range(n):
            net.add_node(
                f"n{i}",
                loopback_prefix=f"10.{i}.0.0/24",
                config_overrides=overrides,
                config_store_path=(
                    None
                    if store_dir is None
                    else f"{store_dir}/n{i}.bin"
                ),
            )
        await net.start_all()
        for i in range(n - 1):
            net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")

        def converged() -> bool:
            for i in range(n):
                got = set(net.wrappers[f"n{i}"].programmed_prefixes())
                want = {f"10.{j}.0.0/24" for j in range(n) if j != i}
                if not want.issubset(got):
                    return False
            return True

        def chords_applied(toggles) -> bool:
            for (a, b), up in toggles:
                adjacent = net.wrappers[f"n{a}"].adjacent_nodes()
                if up != (f"n{b}" in adjacent):
                    return False
            return True

        scrapes = _ScrapeLog()
        wave_log: List[Dict[str, Any]] = []
        fault_intervals: List[Tuple[float, float]] = []
        fired: Dict[str, int] = {}

        # fleet observer (openr_tpu/fleet): continuous scrape+stream
        # collection over the real ctrl sockets + the SLO watchdog,
        # verdict embedded in the report's `fleet` section
        observer = None
        if cfg.fleet_observer:
            from openr_tpu.fleet import FleetConfig, FleetObserver, SloConfig

            observer = FleetObserver.for_network(
                net,
                config=FleetConfig(
                    scrape_interval_s=cfg.fleet_interval_s,
                    slo=SloConfig(
                        convergence_p95_budget_ms=cfg.fleet_budget_ms
                    ),
                ),
            )

        def scrape_all() -> None:
            for name, wrapper in net.wrappers.items():
                scrapes.scrape(name, wrapper.daemon)

        # streaming scrape mode: each node carries a live
        # `subscribeKvStore` adj-delta subscription over its real ctrl
        # socket; wave scrapes trigger on delivered stream frames
        # instead of polling (docs/Streaming.md)
        stream_counts: Dict[str, Dict[str, int]] = {}
        stream_tasks: List[asyncio.Task] = []
        stream_clients: List[Any] = []

        async def _watch_stream(name: str, client) -> None:
            try:
                async for frame in client.subscribe(
                    "subscribeKvStore",
                    area="0",
                    prefixes=["adj:"],
                    client="soak-scrape",
                ):
                    stream_counts[name]["frames"] += 1
                    if frame.get("type") == "resync":
                        stream_counts[name]["resyncs"] += 1
            except Exception:
                stream_counts[name]["errors"] = (
                    stream_counts[name].get("errors", 0) + 1
                )

        async def _start_streams() -> None:
            from openr_tpu.ctrl.client import CtrlClient

            for name, wrapper in net.wrappers.items():
                client = await CtrlClient(
                    "127.0.0.1", wrapper.ctrl_port
                ).connect()
                stream_clients.append(client)
                stream_counts[name] = {"frames": 0, "resyncs": 0}
                stream_tasks.append(
                    asyncio.get_running_loop().create_task(
                        _watch_stream(name, client)
                    )
                )

        def stream_frames_total() -> int:
            return sum(c["frames"] for c in stream_counts.values())

        with injected(FaultInjector(seed=cfg.seed)) as inj:
            try:
                await wait_until(
                    converged, timeout=cfg.converge_timeout_s
                )
                if cfg.stream_scrapes:
                    await _start_streams()
                    # the initial snapshot frames prove every stream is up
                    await wait_until(
                        lambda: all(
                            c["frames"] >= 1 for c in stream_counts.values()
                        ),
                        timeout=cfg.converge_timeout_s,
                    )
                if observer is not None:
                    await observer.start()
                scrape_all()
                for wave_i in range(cfg.waves):
                    chaos = (
                        cfg.fault_every > 0
                        and (wave_i + 1) % cfg.fault_every == 0
                    )
                    if chaos:
                        arm(inj, wave_i, cfg)
                        fault_t0 = time.time()
                    # partition wave: asymmetrically blackhole one
                    # direction of a random line edge through the chaos
                    # mesh, hold, heal — the wave's convergence wait
                    # below then proves recovery after heal
                    partitioned: List[str] = []
                    if (
                        mesh is not None
                        and (wave_i + 1) % cfg.partition_every == 0
                    ):
                        from openr_tpu.testing.chaos import ChaosLinkSpec

                        edge = rng.randrange(0, n - 1)
                        src, dst = f"n{edge}", f"n{edge + 1}"
                        part_t0 = time.time()
                        mesh.set_link(
                            src,
                            dst,
                            ChaosLinkSpec(
                                partition=True, spark_loss=0.0
                            ),
                        )
                        partitioned.append(f"{src}->{dst}")
                        await asyncio.sleep(cfg.partition_hold_s)
                        mesh.clear_link(src, dst)
                    # the OCS bulk reconfiguration: remove up-chords,
                    # add down-chords, all in one batch
                    frames_before = stream_frames_total()
                    ups = [c for c in chords if chord_state[c] == "up"]
                    downs = [c for c in chords if chord_state[c] != "up"]
                    rng.shuffle(ups)
                    rng.shuffle(downs)
                    removed = ups[: cfg.wave_links]
                    added = downs[: cfg.wave_links]
                    toggles = []
                    for a, b in removed:
                        ia, ib = _chord_ifaces(a, b)
                        net.fail_link(f"n{a}", ia, f"n{b}", ib)
                        chord_state[(a, b)] = "down"
                        toggles.append(((a, b), False))
                    for a, b in added:
                        ia, ib = _chord_ifaces(a, b)
                        if chord_state[(a, b)] == "new":
                            net.connect(f"n{a}", ia, f"n{b}", ib)
                        else:
                            net.restore_link(f"n{a}", ia, f"n{b}", ib)
                        chord_state[(a, b)] = "up"
                        toggles.append(((a, b), True))
                    # restart wave: after the chord batch lands, bounce a
                    # random interior node through the graceful-restart
                    # warm-boot path — the wave only converges once the
                    # respawn has resynced and reprogrammed
                    restarted: List[str] = []
                    if (
                        cfg.restart_every > 0
                        and (wave_i + 1) % cfg.restart_every == 0
                    ):
                        victim = f"n{rng.randrange(1, n - 1)}"
                        # open the restart windows FIRST: a scrape/stream
                        # racing the bounce is attributed, not an error
                        scrapes.note_restart(victim)
                        if observer is not None:
                            observer.note_restart(victim)
                        await net.restart_node(victim)
                        restarted.append(victim)
                    t0 = time.time()
                    wave_ok = True
                    try:
                        await wait_until(
                            lambda: chords_applied(toggles)
                            and converged(),
                            timeout=cfg.converge_timeout_s,
                        )
                    except AssertionError:
                        wave_ok = False
                    converge_ms = (time.time() - t0) * 1e3
                    if cfg.stream_scrapes and wave_ok:
                        # scrape on push, not poll: the wave's adjacency
                        # deltas must arrive over the subscription
                        # streams before the post-wave scrape fires
                        await wait_until(
                            lambda: stream_frames_total() > frames_before,
                            timeout=cfg.converge_timeout_s,
                        )
                    await asyncio.sleep(cfg.settle_s)
                    if chaos:
                        for point in ("fib.program", "kvstore.flood_send",
                                      "spark.packet_send"):
                            fired[point] = fired.get(point, 0) + inj.fired(
                                point
                            )
                            inj.disarm(point)
                        fault_intervals.append((fault_t0, time.time()))
                    if partitioned:
                        # cover the hold AND the settle: a flood_health
                        # breach the watchdog stamps just after heal is
                        # still partition-attributed
                        fault_intervals.append((part_t0, time.time()))
                    scrape_all()
                    wave_log.append(
                        {
                            "index": wave_i,
                            "added": [f"n{a}-n{b}" for a, b in added],
                            "removed": [
                                f"n{a}-n{b}" for a, b in removed
                            ],
                            "restarted": restarted,
                            "partitioned": partitioned,
                            "faulted": chaos,
                            "converged": wave_ok,
                            "converge_ms": round(converge_ms, 2),
                        }
                    )

                # let the monitor queues drain every closed span into the
                # rollups before judging (record-time fold, async drain)
                def fib_spans() -> int:
                    return sum(
                        w.daemon.fib.counters.get(
                            "fib.convergence_spans", 0
                        )
                        for w in net.wrappers.values()
                    )

                def rollup_events() -> int:
                    return sum(
                        w.daemon.monitor.rollup.events_total
                        for w in net.wrappers.values()
                    )

                try:
                    await wait_until(
                        lambda: rollup_events() >= fib_spans(),
                        timeout=20.0,
                    )
                except AssertionError:
                    pass  # the no_eviction_loss check will report it
                scrape_all()
                fib_spans_closed = fib_spans()
                reports = net.node_reports()
            finally:
                fleet_report = None
                if observer is not None:
                    await observer.stop()
                    fleet_report = observer.report()
                for task in stream_tasks:
                    task.cancel()
                if stream_tasks:
                    await asyncio.gather(
                        *stream_tasks, return_exceptions=True
                    )
                for client in stream_clients:
                    await client.close()
                await net.stop_all()

        merged = merge_rollup_snapshots(
            r["rollup"] for r in reports if r.get("rollup")
        )
        spans_in_rings = sum(len(r["spans"]) for r in reports)
        judged = _judge(
            merged,
            fault_intervals,
            fib_spans_closed=fib_spans_closed,
            spans_in_rings=spans_in_rings,
            waves=wave_log,
            scrapes=scrapes.summary(),
            fleet_findings=(fleet_report or {}).get("findings"),
        )
        return {
            "config": asdict(cfg),
            "nodes": n,
            "waves": wave_log,
            "faults": {
                "fired": fired,
                "intervals": [list(iv) for iv in fault_intervals],
            },
            "scrapes": scrapes.summary(),
            "stream": {
                "enabled": cfg.stream_scrapes,
                "nodes": dict(stream_counts),
                "frames_total": stream_frames_total(),
                "resyncs_total": sum(
                    c["resyncs"] for c in stream_counts.values()
                ),
            },
            "events": {
                "total": merged["events_total"],
                "windowed": sum(
                    w["events"] for w in merged["windows"]
                ),
                "evicted_window_events": merged["evicted_events"],
                "spans_in_rings": spans_in_rings,
                "fib_spans_closed": fib_spans_closed,
            },
            "fleet": fleet_report,
            **judged,
        }

    loop = asyncio.new_event_loop()
    try:
        if cfg.restart_every:
            import tempfile

            with tempfile.TemporaryDirectory() as td:
                return loop.run_until_complete(body(td))
        return loop.run_until_complete(body(None))
    finally:
        loop.close()


def run_soak_smoke() -> Dict[str, Any]:
    """SOAK_SMOKE tier-1 (the churn sibling of FAULT_SMOKE/TRACE_SMOKE):
    a 3-node line, ONE reconfiguration wave (the n0–n2 chord comes up),
    ONE injected fault (fib.program), and a max_event_log small enough
    that ring eviction provably happens — asserting the judged-report
    machinery end to end: windowed totals account for 100% of events
    (the acceptance invariant), every scrape parses with full registry
    coverage, and the verdict block carries every check. Topology size
    scales via SOAK_SMOKE_NODES; returns the report."""
    import os

    n = max(3, int(os.environ.get("SOAK_SMOKE_NODES", "3")))
    cfg = SoakConfig(
        nodes=n,
        waves=1,
        wave_links=1,
        settle_s=0.3,
        fault_every=1,  # the single wave is a fault wave
        seed=3,
        max_event_log=3,  # force ring eviction: rings hold only a tail
        window_s=0.5,
        max_windows=240,
    )

    def one_fault(inj: FaultInjector, wave: int, _cfg) -> None:
        inj.arm("fib.program", times=1)

    report = run_soak(cfg, arm_chaos=one_fault)
    events = report["events"]
    assert events["total"] > cfg.max_event_log, events
    assert (
        events["windowed"] + events["evicted_window_events"]
        == events["total"]
    ), events
    assert events["spans_in_rings"] < events["total"], events
    assert report["faults"]["fired"].get("fib.program") == 1, report[
        "faults"
    ]
    checks = report["verdict"]["checks"]
    for name in (
        "windowed_accounting",
        "no_eviction_loss",
        "waves_converged",
        "scrape_health",
        "no_monotonic_regression",
        "no_clean_trend_break",
    ):
        assert name in checks, sorted(checks)
        assert checks[name]["ok"], (name, checks[name])
    assert report["verdict"]["pass"], checks
    assert report["scrapes"]["count"] >= 2 * n, report["scrapes"]
    return report


def run_soak_round(
    round_index: int = 1,
    cfg: Optional[SoakConfig] = None,
    fanout_subscribers: int = 2048,
    fanout_nodes: int = 8,
    fanout_flaps: int = 2,
    fanout_inproc: Optional[int] = None,
    fanout_ab_runs: int = 2,
    out_dir: str = ".",
) -> Dict[str, Any]:
    """The real soak round, wired into the artifact flow (the ROADMAP
    "run the long soak at scale" item): one full chord+chaos+restart
    soak with stream-mode scrapes AND the fleet observer attached (its
    verdict embedded in the artifact), followed by the fan-out proof
    (docs/Streaming.md "Shared-encode fan-out") — the convergence flap
    batch run three ways:

      1. `fanout_before`: `fanout_subscribers` socket subscriptions with
         `shared_encode=false` — the historical per-subscriber re-encode
         bill (the SOAK_r01 serving wall), measured fresh;
      2. `fanout`: the SAME batch with sharing on — encode share and
         delta throughput before/after on identical work;

    Both A/B legs serve the flap batch ENRICHED with production-sized
    key churn (`churn_keys`/`churn_value_bytes` per wave, flooded
    area-wide — LSDB-sized publications, not bare adjacency deltas),
    run with the SPF debounce window pinned (so events/s denominators
    don't eat 10–250 ms of per-wave timer jitter), and each leg runs
    `fanout_ab_runs` times with the best run kept (all runs recorded in
    the artifact) — one emulated core serves 8 daemons plus 2048
    watchers, so single-run wall clocks carry ±20% scheduler noise;
      3. `fanout_scale`: the 100k-subscriber push — the socket cohort
         (mixed JSON/binary codecs, admission control live, one
         subscriber deliberately stalled into overflow→resync) plus the
         in-process cohort (`fanout_inproc`, testing/fanout.py — the fd
         limit forbids 100k real sockets; the artifact reports the
         split honestly) with the fleet observer attached as SLO judge:
         every `stream_backpressure` breach must be attributable to the
         stalled subscriber's node, anything else fails the round.

    `fanout_inproc` defaults to SOAK_FANOUT_INPROC (98304: with the
    2048-socket cohort the total crosses 100k). Writes `SOAK_r<NN>.json`;
    returns the artifact dict."""
    from openr_tpu.testing.decision_harness import run_bench_convergence

    if cfg is None:
        nodes = int(os.environ.get("SOAK_ROUND_NODES", "96"))
        cfg = SoakConfig(
            nodes=nodes,
            waves=int(os.environ.get("SOAK_ROUND_WAVES", "12")),
            wave_links=2,
            # per-wave drain time: the judged trend must measure the
            # protocol, not cross-wave monitor-queue backlog
            settle_s=2.0,
            # a deep line topology floods adjacency across its whole
            # diameter per wave: scale the deadline with the fleet
            converge_timeout_s=max(120.0, 2.5 * nodes),
            fault_every=3,
            restart_every=4,
            # partition waves ride the round too: one asymmetric
            # line-edge split per 5th wave, healed after half a second
            partition_every=5,
            partition_hold_s=0.5,
            seed=11,
            window_s=8.0,
            stream_scrapes=True,
            fleet_observer=True,
            # the SLO budget is an operator choice per fleet: a deep
            # line emulated on shared CPU converges in seconds, not ms
            fleet_budget_ms=float(
                os.environ.get("SOAK_ROUND_BUDGET_MS", "15000")
            ),
        )
    if fanout_inproc is None:
        fanout_inproc = int(os.environ.get("SOAK_FANOUT_INPROC", "98304"))

    t0 = time.time()
    soak_report = run_soak(cfg)
    soak_s = time.time() - t0

    # the shared A/B batch shape: mixed codecs (the cohort shape),
    # production-sized key churn riding every wave, debounce pinned
    ab_kwargs: Dict[str, Any] = dict(
        nodes=fanout_nodes,
        flaps=fanout_flaps,
        backend="cpu",
        measure_exporter=False,
        subscribers=fanout_subscribers,
        codec="mixed",
        churn_keys=int(os.environ.get("SOAK_FANOUT_CHURN_KEYS", "8")),
        churn_value_bytes=int(
            os.environ.get("SOAK_FANOUT_CHURN_BYTES", "16384")
        ),
        debounce_ms=(10.0, 50.0),
    )

    def best_of(runs: int, **kwargs) -> Tuple[Dict[str, Any], List[float]]:
        """Best events/s of `runs` identical legs (every run's
        throughput recorded): one core serves the whole emulation, so
        the best run is the least scheduler-polluted measurement."""
        best: Optional[Dict[str, Any]] = None
        seen: List[float] = []
        for _ in range(max(1, runs)):
            leg = run_bench_convergence(**kwargs)
            seen.append(round(leg.get("stream_events_per_s", 0.0), 1))
            if best is None or leg.get(
                "stream_events_per_s", 0.0
            ) > best.get("stream_events_per_s", 0.0):
                best = leg
        return best, seen

    # 1. before: sharing off — the per-subscriber re-encode bill
    t0 = time.time()
    fanout_before, before_runs = best_of(
        fanout_ab_runs, shared_encode=False, **ab_kwargs
    )
    before_s = time.time() - t0

    # 2. after: identical batch with the shared-encode path on
    t0 = time.time()
    fanout, after_runs = best_of(fanout_ab_runs, **ab_kwargs)
    fanout_s = time.time() - t0
    fanout_before["events_per_s_runs"] = before_runs
    fanout["events_per_s_runs"] = after_runs

    share_before = fanout_before.get("stream_encode_share", 0.0)
    share = fanout.get("stream_encode_share", 0.0)
    events_before = fanout_before.get("stream_events_per_s", 0.0)
    events_after = fanout.get("stream_events_per_s", 0.0)
    speedup = events_after / events_before if events_before else 0.0
    per_frame = fanout.get("stream_encode_us_per_frame", 0.0)
    fanout["verdict"] = (
        f"{fanout_subscribers} subscribers x {fanout_nodes} nodes: "
        f"shared-encode fan-out cut the encode share of the batch wall "
        f"clock from {share_before * 100:.1f}% (per-subscriber "
        f"re-encode) to {share * 100:.1f}% "
        f"({fanout.get('stream_encode_classes', 0)} class encodes, "
        f"{fanout.get('stream_encode_class_hits', 0)} shared reuses at "
        f"{per_frame:.1f}us/encode) and moved delta delivery from "
        f"{events_before:.0f} to {events_after:.0f} events/s "
        f"({speedup:.2f}x) on identical flap batches — "
        + (
            "the serving wall is down: fan-out cost is now "
            "O(filter-classes), not O(subscribers)"
            if share <= 0.05 and speedup >= 2.0
            else "below the >=2x / <=5%-share acceptance bar; "
            "investigate before trusting the shared path"
        )
    )

    # 3. scale: the 100k hybrid cohort with the fleet observer as judge
    t0 = time.time()
    fanout_scale = run_bench_convergence(
        nodes=fanout_nodes,
        flaps=fanout_flaps,
        backend="cpu",
        measure_exporter=False,
        subscribers=fanout_subscribers,
        fleet_observer=True,
        codec="mixed",
        churn_keys=ab_kwargs["churn_keys"],
        churn_value_bytes=ab_kwargs["churn_value_bytes"],
        debounce_ms=ab_kwargs["debounce_ms"],
        inproc_subscribers=fanout_inproc,
        stall_subscriber=True,
        # every cohort member counts against the per-node cap; leave
        # admission control LIVE but sized for the cohort plus headroom
        max_subscribers=(
            (fanout_subscribers + fanout_inproc) // fanout_nodes + 64
        ),
    )
    scale_s = time.time() - t0
    total_subs = fanout_subscribers + fanout_scale.get(
        "stream_inproc_subscribers", 0
    )
    # the stalled socket subscriber is index 0 -> node n0: any
    # stream_backpressure finding elsewhere is an UNATTRIBUTED breach
    backpressure_nodes = fanout_scale.get(
        "fleet_findings_by_kind", {}
    ).get("stream_backpressure", [])
    unattributed = [nd for nd in backpressure_nodes if nd != "n0"]
    fanout_scale["verdict"] = (
        f"{total_subs} total subscribers "
        f"({fanout_subscribers} real sockets, mixed JSON/binary codecs, "
        f"{fanout_scale.get('stream_inproc_subscribers', 0)} in-process "
        f"via testing/fanout.py) across {fanout_nodes} nodes with one "
        f"deliberately stalled socket subscriber: encode share "
        f"{fanout_scale.get('stream_encode_share', 0.0) * 100:.1f}%, "
        f"class hit rate "
        f"{fanout_scale.get('stream_class_hit_rate', 0.0):.3f}, "
        f"stream_backpressure findings on "
        f"{backpressure_nodes or 'no nodes'} — "
        + (
            "every breach attributable to the stalled subscriber's "
            "node; admission control and slow-client isolation held "
            "at scale"
            if not unattributed
            else f"UNATTRIBUTED breach on {unattributed}: sharing leaked "
            "backpressure across subscribers"
        )
    )
    fanout_scale["backpressure_attributed"] = not unattributed

    from openr_tpu.utils.build_info import (
        ARTIFACT_SCHEMA_VERSION,
        build_fingerprint,
    )

    artifact = {
        "schema_version": ARTIFACT_SCHEMA_VERSION,
        "build": build_fingerprint(),
        "round": round_index,
        "kind": "SOAK",
        "config": asdict(cfg),
        "soak_wall_s": round(soak_s, 1),
        "fanout_before_wall_s": round(before_s, 1),
        "fanout_wall_s": round(fanout_s, 1),
        "fanout_scale_wall_s": round(scale_s, 1),
        "soak": soak_report,
        "fleet_verdict": (soak_report.get("fleet") or {}).get("verdict"),
        "fanout_before": fanout_before,
        "fanout": fanout,
        "fanout_scale": fanout_scale,
        "fanout_total_subscribers": total_subs,
        "fanout_socket_subscribers": fanout_subscribers,
        "fanout_inproc_subscribers": fanout_scale.get(
            "stream_inproc_subscribers", 0
        ),
        "encode_share_before": share_before,
        "encode_share_after": share,
        "fanout_speedup": round(speedup, 3),
    }
    path = os.path.join(out_dir, f"SOAK_r{round_index:02d}.json")
    with open(path, "w") as fh:
        json.dump(artifact, fh, indent=2, sort_keys=True, default=str)
    artifact["path"] = path
    return artifact


def main(argv: Optional[List[str]] = None) -> int:
    """CLI soak driver: python -m openr_tpu.testing.soak --nodes 8
    --waves 12 --out soak.json (render with `breeze perf soak-report`);
    `--round N` runs the full artifact round (soak + fleet observer +
    fan-out push) and writes SOAK_rNN.json instead."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="soak", description="topology-churn soak harness"
    )
    parser.add_argument("--nodes", type=int, default=6)
    parser.add_argument("--waves", type=int, default=4)
    parser.add_argument("--wave-links", type=int, default=1)
    parser.add_argument("--settle-s", type=float, default=1.0)
    parser.add_argument("--fault-every", type=int, default=2)
    parser.add_argument("--restart-every", type=int, default=0)
    parser.add_argument(
        "--partition-every",
        type=int,
        default=0,
        help=(
            "every Nth wave asymmetrically partitions one line-edge "
            "direction via the chaos mesh, then heals (0 disables)"
        ),
    )
    parser.add_argument("--partition-hold-s", type=float, default=0.5)
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--window-s", type=float, default=1.0)
    parser.add_argument("--max-event-log", type=int, default=100)
    parser.add_argument(
        "--fleet-observer",
        action="store_true",
        help="attach the fleet observer (verdict embedded in the report)",
    )
    parser.add_argument(
        "--round",
        type=int,
        default=None,
        help="run the full SOAK_rNN.json artifact round instead",
    )
    parser.add_argument(
        "--fanout-subscribers",
        type=int,
        default=2048,
        help="fan-out push socket-subscriber count for the artifact round",
    )
    parser.add_argument(
        "--fanout-inproc",
        type=int,
        default=None,
        help=(
            "in-process cohort size for the scale run (default "
            "SOAK_FANOUT_INPROC or 98304; sockets+inproc >= 100k)"
        ),
    )
    parser.add_argument("--out", default=None, help="JSON report path")
    args = parser.parse_args(argv)
    if args.round is not None:
        artifact = run_soak_round(
            round_index=args.round,
            fanout_subscribers=args.fanout_subscribers,
            fanout_inproc=args.fanout_inproc,
        )
        verdict = artifact["soak"]["verdict"]
        fleet = artifact.get("fleet_verdict") or {}
        attributed = artifact["fanout_scale"]["backpressure_attributed"]
        print(
            json.dumps(
                {
                    "soak": "PASS" if verdict["pass"] else "FAIL",
                    "fleet": "PASS" if fleet.get("pass") else "BREACH",
                    "encode_share_before": artifact["encode_share_before"],
                    "encode_share_after": artifact["encode_share_after"],
                    "fanout_speedup": artifact["fanout_speedup"],
                    "total_subscribers": artifact[
                        "fanout_total_subscribers"
                    ],
                    "backpressure": (
                        "ATTRIBUTED" if attributed else "UNATTRIBUTED"
                    ),
                    "artifact": artifact["path"],
                }
            )
        )
        return 0 if (verdict["pass"] and attributed) else 1
    cfg = SoakConfig(
        nodes=args.nodes,
        waves=args.waves,
        wave_links=args.wave_links,
        settle_s=args.settle_s,
        fault_every=args.fault_every,
        restart_every=args.restart_every,
        partition_every=args.partition_every,
        partition_hold_s=args.partition_hold_s,
        seed=args.seed,
        window_s=args.window_s,
        max_event_log=args.max_event_log,
        fleet_observer=args.fleet_observer,
    )
    report = run_soak(cfg)
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
    verdict = report["verdict"]
    print(
        json.dumps(
            {
                "soak": "PASS" if verdict["pass"] else "FAIL",
                "events_total": report["events"]["total"],
                "waves": len(report["waves"]),
                "windows": len(report["windows"]),
            }
        )
    )
    return 0 if verdict["pass"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
