"""OpenrWrapper: one whole-stack virtual node; VirtualNetwork: the shared
mock fabric connecting many of them (tests/OpenrWrapper.h:36-90 +
tests/mocks/MockIoProvider + in-process KvStore transport)."""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

from openr_tpu.config import Config
from openr_tpu.kvstore.transport import InProcessTransport
from openr_tpu.openr import OpenrDaemon
from openr_tpu.platform import MockFibHandler
from openr_tpu.spark.io_provider import MockIoNetwork
from openr_tpu.types import IpPrefix, PrefixEntry, PrefixType
from openr_tpu.utils.ownership import owned_by


@owned_by("emulator-loop")
class VirtualNetwork:
    """Shared fabric: Spark packet network + KvStore transport.

    Owned by the emulating test's event loop: topology mutations
    (add_node/connect/fail_link) must run on the loop the daemons run on —
    the thread-ownership analyzer (docs/Analysis.md) enforces that no
    ctrl-reachable path mutates this state from outside."""

    def __init__(self, chaos=None) -> None:
        self.io_network = MockIoNetwork()
        # with a ChaosMesh the whole fabric — Spark packets and KvStore
        # RPCs — runs through the seeded chaos schedule (testing/chaos)
        self.chaos = chaos
        if chaos is not None:
            from openr_tpu.testing.chaos import ChaosKvTransport

            self.io_network.chaos = chaos
            self.kv_transport = ChaosKvTransport(chaos)
        else:
            self.kv_transport = InProcessTransport()
        self.wrappers: Dict[str, "OpenrWrapper"] = {}

    def add_node(self, name: str, **kw) -> "OpenrWrapper":
        wrapper = OpenrWrapper(name, self, **kw)
        self.wrappers[name] = wrapper
        return wrapper

    def connect(
        self,
        a: str,
        a_iface: str,
        b: str,
        b_iface: str,
        latency_ms: float = 1.0,
    ) -> None:
        """Create a virtual link; both ends see their interface come up."""
        self.io_network.connect((a, a_iface), (b, b_iface), latency_ms)
        self.wrappers[a].set_interface(a_iface, True)
        self.wrappers[b].set_interface(b_iface, True)

    def fail_link(self, a: str, a_iface: str, b: str, b_iface: str) -> None:
        self.io_network.disconnect((a, a_iface), (b, b_iface))
        self.wrappers[a].set_interface(a_iface, False)
        self.wrappers[b].set_interface(b_iface, False)

    def restore_link(self, a: str, a_iface: str, b: str, b_iface: str) -> None:
        self.io_network.reconnect((a, a_iface), (b, b_iface))
        self.wrappers[a].set_interface(a_iface, True)
        self.wrappers[b].set_interface(b_iface, True)

    async def start_all(self) -> None:
        for wrapper in self.wrappers.values():
            await wrapper.start()

    async def stop_all(self) -> None:
        for wrapper in reversed(list(self.wrappers.values())):
            await wrapper.stop()

    async def restart_node(
        self, name: str, config_overrides: Optional[dict] = None
    ) -> "OpenrWrapper":
        """Whole-node crash/restart: stop the daemon (its stop path floods
        restarting hellos when `spark_config.graceful_restart_enabled` is
        set, so neighbors enter the GR hold) and respawn it with the SAME
        config, configstore path and FIB agent object — the agent keeps
        forwarding on its surviving routes through the gap, exactly like
        a kernel FIB under a restarting routing daemon. The respawn's
        first post-boot sync closes the `restart.e2e_ms` span anchored at
        the restarting-hello flood. Returns the new wrapper."""
        import time

        wrapper = self.wrappers[name]
        anchor = time.monotonic()
        await wrapper.stop()
        respawn = OpenrWrapper(
            name,
            self,
            config_overrides=(
                config_overrides
                if config_overrides is not None
                else wrapper.config_overrides
            ),
            loopback_prefix=wrapper.loopback_prefix,
            config_store_path=wrapper.config_store_path,
            fib_handler=wrapper.fib_handler,
        )
        self.wrappers[name] = respawn
        respawn.daemon.fib.note_restart_anchor(anchor)
        await respawn.start()
        # the fabric kept the links; the fresh daemon must re-raise its
        # interfaces to rejoin discovery
        for iface in self.io_network.interfaces_of(name):
            respawn.set_interface(iface, True)
        return respawn

    # -- network-wide observability ---------------------------------------

    def node_reports(self) -> List[dict]:
        """Per-node convergence reports (the in-process equivalent of
        calling ctrl getConvergenceReport on every daemon)."""
        from openr_tpu.monitor.report import node_convergence_report

        return [
            node_convergence_report(
                name, wrapper.daemon.monitor, kvstore=wrapper.daemon.kvstore
            )
            for name, wrapper in self.wrappers.items()
        ]

    def convergence_report(self) -> dict:
        """Network-wide convergence report over all emulated nodes —
        p50/p95/max node-to-converge, per-stage distributions with
        slowest-hop attribution, flood-health stats, plus the
        eviction-proof rollup's cumulative-vs-windowed split (what
        `breeze perf report --hosts ...` computes for real
        deployments)."""
        from openr_tpu.monitor.report import aggregate_convergence_reports

        return aggregate_convergence_reports(self.node_reports())

    def scrape_all(self) -> Dict[str, str]:
        """Per-node Prometheus exposition text — the in-process
        equivalent of polling GET /metrics on every daemon's ctrl port
        (what the soak harness's scrape loop does each wave)."""
        return {
            name: wrapper.daemon.exporter.render()
            for name, wrapper in self.wrappers.items()
        }


# tightened timers for in-process convergence (OpenrSystemTest.cpp:23-35)
_FAST_TIMERS = {
    "spark_config": {
        "hello_time_s": 2.0,
        "fastinit_hello_time_ms": 50.0,
        "keepalive_time_s": 0.2,
        "hold_time_s": 1.0,
        "graceful_restart_time_s": 3.0,
    },
    "link_monitor_config": {
        "linkflap_initial_backoff_ms": 8,
        "linkflap_max_backoff_ms": 64,
    },
    "decision_config": {
        "debounce_min_ms": 5.0,
        "debounce_max_ms": 20.0,
    },
    "fib_config": {
        # the emulator keeps the seed's immediate first sync: a non-zero
        # hold would subsume each node's first route deltas into the
        # pending sync (losing their convergence spans); warm-boot gating
        # rides the stale set + EOR, not this hold
        "cold_start_duration_s": 0.0,
        "stale_sweep_deadline_s": 30.0,
    },
}


@owned_by("emulator-loop")
class OpenrWrapper:
    def __init__(
        self,
        name: str,
        network: VirtualNetwork,
        config_overrides: Optional[dict] = None,
        loopback_prefix: Optional[str] = None,
        config_store_path: Optional[str] = None,
        fib_handler: Optional[MockFibHandler] = None,
    ) -> None:
        self.name = name
        self.network = network
        # kept verbatim so restart_node can respawn with the same config
        self.config_overrides = config_overrides
        self.config_store_path = config_store_path
        cfg = {"node_name": name, "dryrun": False, **_FAST_TIMERS}
        if config_overrides:
            for key, value in config_overrides.items():
                if isinstance(value, dict) and isinstance(
                    cfg.get(key), dict
                ):
                    cfg[key] = {**cfg[key], **value}
                else:
                    cfg[key] = value
        # the FIB agent outlives daemon incarnations (it is the kernel's
        # stand-in): restart_node hands the same handler to the respawn so
        # forwarding state survives the daemon gap
        self.fib_handler = (
            fib_handler if fib_handler is not None else MockFibHandler()
        )
        self.daemon = OpenrDaemon(
            Config.from_dict(cfg),
            io_provider=network.io_network.provider(name),
            kv_transport=network.kv_transport,
            fib_service=self.fib_handler,
            config_store_path=config_store_path,
            ctrl_port=0,
        )
        self.loopback_prefix = loopback_prefix
        self.ctrl_port: Optional[int] = None

    async def start(self) -> None:
        self.ctrl_port = await self.daemon.start()
        if self.loopback_prefix is not None:
            self.daemon.prefix_manager.advertise_prefixes(
                [
                    PrefixEntry(
                        prefix=IpPrefix(self.loopback_prefix),
                        type=PrefixType.LOOPBACK,
                    )
                ]
            )

    async def stop(self) -> None:
        await self.daemon.stop()

    # -- convenience views ------------------------------------------------

    def set_interface(self, if_name: str, is_up: bool) -> None:
        self.daemon.link_monitor.update_interface(if_name, is_up)

    def programmed_prefixes(self) -> List[str]:
        from openr_tpu.platform import FIB_CLIENT_OPENR

        return sorted(
            str(dest)
            for dest in self.fib_handler.unicast_routes.get(
                FIB_CLIENT_OPENR, {}
            )
        )

    def programmed_route(self, prefix: str):
        from openr_tpu.platform import FIB_CLIENT_OPENR

        return self.fib_handler.unicast_routes.get(FIB_CLIENT_OPENR, {}).get(
            IpPrefix(prefix)
        )

    def adjacent_nodes(self) -> List[str]:
        return sorted(
            {
                node
                for node, _ in self.daemon.link_monitor.get_adjacencies()
            }
        )

    def kvstore_keys(self) -> List[str]:
        return sorted(self.daemon.kvstore.dump_all().key_vals)

    def kvstore_key_count(self, area: str = "0") -> int:
        """O(1) key count, same area scope as kvstore_keys() — convergence
        predicates at emulation scale must not dump_all() every poll (a
        192-node poll loop spent more time unpacking dumps than running
        the protocol)."""
        db = self.daemon.kvstore.dbs.get(area)
        return len(db.store) if db is not None else 0


async def wait_until(predicate, timeout: float = 20.0, interval=0.02):
    """Await a condition with deadline — the test convergence helper."""
    loop = asyncio.get_event_loop()
    deadline = loop.time() + timeout
    while not predicate():
        if loop.time() >= deadline:
            raise AssertionError("condition did not converge in time")
        await asyncio.sleep(interval)
