"""Seeded chaos transport mesh (CHAOS_SMOKE).

Hostile-network hardening needs a hostile network. `ChaosMesh` is a
deterministic, per-direction fault schedule over the whole emulated
fabric — Spark datagrams (via `MockIoNetwork.chaos`) and KvStore RPCs
(via `ChaosKvTransport`) both consult it:

  - **loss**: the RPC raises `KvStoreTransportError` / the datagram is
    silently dropped — exactly what a congested or lossy path does;
  - **duplication**: the frame is delivered twice (feeds the flood
    duplicate ratio that arms adaptive anti-entropy);
  - **reorder/delay**: bounded extra latency, drawn per frame, so
    frames overtake each other on the fabric;
  - **corruption**: the key_vals payload is round-tripped through the
    JSON wire codec with one flipped byte — the *receiver* counts the
    typed reject (`kvstore.wire.rejected.*`) and the *sender* sees a
    transport error, mirroring what `KvStoreTcpServer` does when a
    corrupted frame arrives over a real socket;
  - **partition**: one *direction* blackholed (`spec.partition` for
    KvStore RPCs, `spec.spark_partition` for hellos) — asymmetric
    partitions are the nasty case the peer-quarantine ladder exists
    for.

Everything draws from one seeded `random.Random`, so a failing schedule
replays byte-for-byte.

`run_chaos_smoke` is the tier-1 proof: a 5-node line converges clean,
proves flood-storm damping end to end (a flapping key is held at the
originator and the *latest* value is served on release), survives a
seeded loss+delay+corruption storm (adaptive anti-entropy repairs the
divergence), trips peer quarantine under an asymmetric partition,
recovers through the probe path after heal, and ends oracle-equal: all
stores pairwise-identical and every node's programmed routes matching a
never-chaosed oracle network.
"""

from __future__ import annotations

import asyncio
import os
import random
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from openr_tpu.kvstore import wire
from openr_tpu.kvstore.transport import (
    InProcessTransport,
    KvStoreTransportError,
)
from openr_tpu.types import KeyVals, PerfEvents, Publication

_B64_ALPHABET = (
    "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/"
)


@dataclass(frozen=True)
class ChaosLinkSpec:
    """Per-direction fault schedule for one src→dst edge."""

    loss: float = 0.0  # P(KvStore RPC raises transport error)
    dup: float = 0.0  # P(frame delivered twice)
    reorder: float = 0.0  # P(extra reorder delay on top of delay_ms)
    delay_ms: Tuple[float, float] = (0.0, 0.0)  # uniform extra latency
    corrupt: float = 0.0  # P(kv.set payload corrupted in flight)
    partition: bool = False  # KvStore RPCs blackholed
    spark_loss: Optional[float] = None  # None → follow `loss`
    spark_partition: bool = False  # Spark datagrams blackholed


class ChaosMesh:
    """Seeded per-direction fault schedules for the whole fabric.

    `set_default` applies to every directed pair without an explicit
    `set_link` entry; `clear()` heals everything. Stats are mesh-local
    bookkeeping for test reports (node-side evidence lives in the
    per-store counters)."""

    def __init__(self, seed: int = 0) -> None:
        self.rng = random.Random(seed)
        self._default = ChaosLinkSpec()
        self._links: Dict[Tuple[str, str], ChaosLinkSpec] = {}
        self.stats: Dict[str, int] = {}

    def note(self, what: str) -> None:
        self.stats[what] = self.stats.get(what, 0) + 1

    def set_default(self, spec: ChaosLinkSpec) -> None:
        self._default = spec

    def set_link(self, src: str, dst: str, spec: ChaosLinkSpec) -> None:
        self._links[(src, dst)] = spec

    def clear_link(self, src: str, dst: str) -> None:
        self._links.pop((src, dst), None)

    def clear(self) -> None:
        """Heal the fabric: drop every schedule, default included."""
        self._default = ChaosLinkSpec()
        self._links.clear()

    def spec(self, src: str, dst: str) -> ChaosLinkSpec:
        return self._links.get((src, dst), self._default)

    def extra_delay(self, spec: ChaosLinkSpec) -> float:
        lo, hi = spec.delay_ms
        extra = self.rng.uniform(lo, hi) / 1000.0 if hi > 0 else 0.0
        if spec.reorder and self.rng.random() < spec.reorder:
            # enough on top of the base draw for frames to overtake
            extra += self.rng.uniform(0.0, 4.0 * max(hi, 1.0)) / 1000.0
        return extra

    def packet_verdict(
        self, src: str, dst: str
    ) -> Optional[Tuple[int, float]]:
        """Spark-datagram gate (consulted by `MockIoNetwork._send`).

        Returns None to drop, else (copies, extra_delay_s)."""
        spec = self.spec(src, dst)
        if spec.spark_partition:
            self.note("spark_dropped")
            return None
        loss = spec.spark_loss if spec.spark_loss is not None else spec.loss
        if loss and self.rng.random() < loss:
            self.note("spark_dropped")
            return None
        copies = 1
        if spec.dup and self.rng.random() < spec.dup:
            copies = 2
            self.note("spark_duplicated")
        return copies, self.extra_delay(spec)


class ChaosKvTransport(InProcessTransport):
    """InProcessTransport with the mesh's schedule on every RPC.

    Must subclass `InProcessTransport` — the KvStore container only
    self-registers on transports of that type. Request and response
    directions are gated independently (an asymmetric partition fails
    dumps whose *reply* path is dead, even though the request landed)."""

    def __init__(self, mesh: ChaosMesh, delay: float = 0.0) -> None:
        super().__init__(delay)
        self.mesh = mesh

    async def _gate(
        self, src: str, dst: str, what: str
    ) -> ChaosLinkSpec:
        spec = self.mesh.spec(src, dst)
        if spec.partition:
            self.mesh.note("kv_partitioned")
            raise KvStoreTransportError(
                f"chaos partition: {src} -> {dst} ({what})"
            )
        if spec.loss and self.mesh.rng.random() < spec.loss:
            self.mesh.note("kv_dropped")
            raise KvStoreTransportError(
                f"chaos loss: {src} -> {dst} ({what})"
            )
        extra = self.mesh.extra_delay(spec)
        if extra > 0.0:
            self.mesh.note("kv_delayed")
            await asyncio.sleep(extra)
        return spec

    def _corrupt_kind(self, key_vals: KeyVals) -> str:
        """Flip one byte of the frame through the real wire codec and
        return the typed reject kind the receiver would count."""
        frame = wire.key_vals_to_json(key_vals)
        victims = [k for k, v in frame.items() if v.get("value")]
        if victims:
            key = victims[self.mesh.rng.randrange(len(victims))]
            text = frame[key]["value"]
            pos = self.mesh.rng.randrange(len(text))
            repl = self.mesh.rng.choice(
                [c for c in _B64_ALPHABET if c != text[pos]]
            )
            frame[key] = dict(frame[key])
            frame[key]["value"] = text[:pos] + repl + text[pos + 1 :]
        elif frame:
            # refresh-only frame (no value bodies): smash a version field
            key = next(iter(frame))
            frame[key] = dict(frame[key])
            frame[key]["version"] = "garbage"
        else:
            return "malformed"
        try:
            wire.key_vals_from_json(frame)
        except wire.WireDecodeError as exc:
            return exc.kind
        # the flip landed somewhere the codec tolerates (e.g. base64
        # padding aliasing) — a real receiver would still merge garbage
        # bytes, but for the emulated reject path count it as malformed
        return "malformed"

    async def call_set(
        self,
        caller: str,
        peer_addr: str,
        area: str,
        key_vals: KeyVals,
        node_ids: Optional[list],
        perf_events: Optional[PerfEvents] = None,
    ) -> None:
        spec = await self._gate(caller, peer_addr, "kv.set")
        if spec.corrupt and self.mesh.rng.random() < spec.corrupt:
            kind = self._corrupt_kind(key_vals)
            target = self._stores.get(peer_addr)
            note = getattr(target, "note_wire_reject", None)
            if note is not None:
                note(kind)
            self.mesh.note("kv_corrupted")
            raise KvStoreTransportError(
                f"chaos corruption ({kind}): {caller} -> {peer_addr}"
            )
        await super().call_set(
            caller, peer_addr, area, key_vals, node_ids, perf_events
        )
        if spec.dup and self.mesh.rng.random() < spec.dup:
            self.mesh.note("kv_duplicated")
            await super().call_set(
                caller,
                peer_addr,
                area,
                dict(key_vals),
                list(node_ids) if node_ids is not None else None,
                perf_events,
            )

    async def call_dump(
        self,
        caller: str,
        peer_addr: str,
        area: str,
        key_val_hashes: Optional[KeyVals],
    ) -> Publication:
        await self._gate(caller, peer_addr, "kv.dump")
        pub = await super().call_dump(
            caller, peer_addr, area, key_val_hashes
        )
        await self._gate(peer_addr, caller, "kv.dump-reply")
        return pub

    async def call_dual(
        self, caller: str, peer_addr: str, area: str, msgs
    ) -> None:
        await self._gate(caller, peer_addr, "kv.dual")
        await super().call_dual(caller, peer_addr, area, msgs)

    async def call_flood_topo_set(
        self,
        caller: str,
        peer_addr: str,
        area: str,
        root_id: str,
        src_id: str,
        set_child: bool,
        all_roots: bool,
    ) -> None:
        await self._gate(caller, peer_addr, "kv.floodTopoSet")
        await super().call_flood_topo_set(
            caller, peer_addr, area, root_id, src_id, set_child, all_roots
        )


# ---------------------------------------------------------------------------
# CHAOS_SMOKE harness
# ---------------------------------------------------------------------------

# fast knobs so the hardening machinery is observable inside a tier-1
# budget: 1 s anti-entropy ticks, sub-second damping half-life, ~50 ms
# probe backoffs, quarantine after 4 consecutive failures
_CHAOS_OVERRIDES: Dict[str, Any] = {
    # deterministic metrics for the oracle differential (RTT-derived
    # metrics vary with the chaos delay draws)
    "link_monitor_config": {"use_rtt_metric": False},
    "kvstore_config": {
        "sync_interval_s": 1,
        "damping_half_life_s": 0.5,
        "damping_max_hold_s": 2.0,
        "peer_suspect_failures": 2,
        "peer_quarantine_failures": 4,
        "peer_probe_min_backoff_s": 0.05,
        "peer_probe_max_backoff_s": 0.4,
        "peer_probe_successes": 2,
        "flood_duplicate_budget": 0.3,
    },
}


def _programmed_tables(net) -> Dict[str, Dict[str, List[tuple]]]:
    from openr_tpu.platform import FIB_CLIENT_OPENR

    out: Dict[str, Dict[str, List[tuple]]] = {}
    for name, wrapper in net.wrappers.items():
        table = wrapper.fib_handler.unicast_routes.get(FIB_CLIENT_OPENR, {})
        out[name] = {
            str(dest): sorted((nh.address, nh.iface) for nh in r.nexthops)
            for dest, r in table.items()
        }
    return out


def _lsdb_digest(wrapper) -> Dict[str, tuple]:
    """key -> (version, originator, value bytes); TTL fields excluded
    (countdowns legitimately differ node to node)."""
    pub = wrapper.daemon.kvstore.dump_all()
    return {
        k: (v.version, v.originator_id, v.value)
        for k, v in pub.key_vals.items()
    }


def _converged(net, n: int):
    def check() -> bool:
        for i in range(n):
            got = set(net.wrappers[f"n{i}"].programmed_prefixes())
            want = {f"10.{j}.0.0/24" for j in range(n) if j != i}
            if not want.issubset(got):
                return False
        return True

    return check


def _counter(net, node: str, name: str) -> int:
    return int(net.wrappers[node].daemon.kvstore.db().counters.get(name, 0))


def _counter_sum(net, name: str) -> int:
    return sum(_counter(net, node, name) for node in net.wrappers)


async def _build_line(net, n: int, store_dir: str) -> None:
    for i in range(n):
        net.add_node(
            f"n{i}",
            loopback_prefix=f"10.{i}.0.0/24",
            config_overrides=_CHAOS_OVERRIDES,
            config_store_path=os.path.join(store_dir, f"n{i}.bin"),
        )
    await net.start_all()
    for i in range(n - 1):
        net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")


async def _run_chaos_smoke(
    store_dir: str, nodes: int, seed: int
) -> Dict[str, Any]:
    from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

    mesh = ChaosMesh(seed=seed)
    net = VirtualNetwork(chaos=mesh)
    report: Dict[str, Any] = {"nodes": nodes, "seed": seed}
    try:
        await _build_line(net, nodes, store_dir)
        await wait_until(_converged(net, nodes), timeout=30.0)

        # -- phase 1: flood-storm damping -------------------------------
        # flap a (non-adjacency) key fast enough to cross the suppress
        # limit at the originator; the held key must release with the
        # LATEST value everywhere
        flaps = 12
        client = net.wrappers["n0"].daemon.kvstore_client
        for i in range(flaps):
            client.set_key("chaos:flap", f"flap-{i}".encode())
            await asyncio.sleep(0.02)
        assert _counter(net, "n0", "kvstore.damping.holds") >= 1, (
            "flapping key never crossed the damping suppress limit"
        )
        assert _counter(net, "n0", "kvstore.damping.suppressed") >= 1
        final_flap = f"flap-{flaps - 1}".encode()

        def flap_settled() -> bool:
            if _counter(net, "n0", "kvstore.damping.released") < 1:
                return False
            for wrapper in net.wrappers.values():
                value = wrapper.daemon.kvstore.get_key("chaos:flap")
                if value is None or value.value != final_flap:
                    return False
            return True

        await wait_until(flap_settled, timeout=15.0)
        report["damping"] = {
            "holds": _counter(net, "n0", "kvstore.damping.holds"),
            "suppressed": _counter(net, "n0", "kvstore.damping.suppressed"),
            "released": _counter(net, "n0", "kvstore.damping.released"),
        }

        # -- phase 2: seeded loss/delay/reorder/dup/corruption storm ----
        mesh.set_default(
            ChaosLinkSpec(
                loss=0.15,
                dup=0.15,
                reorder=0.2,
                delay_ms=(0.0, 8.0),
                corrupt=0.05,
                spark_loss=0.05,
            )
        )
        # one edge gets deterministic corruption so the typed wire-reject
        # path is exercised regardless of the seed's draws
        mesh.set_link(
            "n2",
            "n3",
            ChaosLinkSpec(corrupt=1.0, spark_loss=0.0),
        )
        for i in range(4):
            origin = net.wrappers[f"n{i % nodes}"].daemon.kvstore_client
            origin.set_key(f"chaos:storm-{i}", f"storm-{i}".encode())
            await asyncio.sleep(0.3)
        await wait_until(
            lambda: _counter_sum(net, "kvstore.wire.rejected_total") >= 1,
            timeout=10.0,
        )
        mesh.clear_link("n2", "n3")
        for i in range(4, 8):
            origin = net.wrappers[f"n{i % nodes}"].daemon.kvstore_client
            origin.set_key(f"chaos:storm-{i}", f"storm-{i}".encode())
            await asyncio.sleep(0.3)
        # the storm's failures/duplicates must arm adaptive anti-entropy
        await wait_until(
            lambda: (
                _counter_sum(net, "kvstore.anti_entropy.rounds")
                + _counter_sum(net, "kvstore.anti_entropy.round_failures")
            )
            >= 1,
            timeout=15.0,
        )

        # -- phase 3: asymmetric partition → quarantine trip ------------
        # n0's RPCs toward n1 blackhole while n1→n0 and Spark stay clean:
        # the adjacency survives, so this is precisely the failure class
        # only the peer-health ladder can see
        mesh.set_link(
            "n0",
            "n1",
            ChaosLinkSpec(partition=True, spark_loss=0.0),
        )
        # keep n0 originating so its flood/full-sync attempts toward n1
        # keep failing (a silent node never notices a dead direction)
        for i in range(60):
            client.set_key(f"chaos:part-{i}", f"part-{i}".encode())
            await asyncio.sleep(0.25)
            if _counter(net, "n0", "kvstore.quarantine.trips") >= 1:
                break
        else:
            raise AssertionError(
                "quarantine never tripped under asymmetric partition"
            )
        health = net.wrappers["n0"].daemon.kvstore.get_peer_health()
        assert health["n1"]["health"] in ("QUARANTINED", "PROBING"), health
        assert _counter(net, "n0", "kvstore.forensics_dumps") >= 1

        # -- phase 4: heal → probe-driven recovery ----------------------
        mesh.clear()
        await wait_until(
            lambda: _counter(net, "n0", "kvstore.quarantine.recoveries")
            >= 1,
            timeout=20.0,
        )

        def all_healthy() -> bool:
            for wrapper in net.wrappers.values():
                for peer in wrapper.daemon.kvstore.get_peer_health().values():
                    if peer["health"] != "HEALTHY":
                        return False
            return True

        await wait_until(all_healthy, timeout=20.0)
        report["quarantine"] = {
            "trips": _counter_sum(net, "kvstore.quarantine.trips"),
            "probes": _counter_sum(net, "kvstore.quarantine.probes"),
            "recoveries": _counter_sum(net, "kvstore.quarantine.recoveries"),
            "floods_skipped": _counter_sum(
                net, "kvstore.quarantine.floods_skipped"
            ),
        }

        # -- phase 5: post-heal flooding works end to end ---------------
        client.set_key("chaos:final", b"after-the-storm")

        def final_everywhere() -> bool:
            for wrapper in net.wrappers.values():
                value = wrapper.daemon.kvstore.get_key("chaos:final")
                if value is None or value.value != b"after-the-storm":
                    return False
            return True

        await wait_until(final_everywhere, timeout=20.0)

        # -- phase 6: oracle-equal convergence --------------------------
        digests = {
            name: _lsdb_digest(wrapper)
            for name, wrapper in net.wrappers.items()
        }

        def stores_identical() -> bool:
            nonlocal digests
            digests = {
                name: _lsdb_digest(wrapper)
                for name, wrapper in net.wrappers.items()
            }
            base = digests["n0"]
            return all(d == base for d in digests.values())

        await wait_until(stores_identical, timeout=20.0)
        await wait_until(_converged(net, nodes), timeout=20.0)
        report["lsdb_keys"] = len(digests["n0"])
        report["chaos_tables"] = _programmed_tables(net)
        report["wire_rejects"] = _counter_sum(
            net, "kvstore.wire.rejected_total"
        )
        report["anti_entropy_rounds"] = _counter_sum(
            net, "kvstore.anti_entropy.rounds"
        )
        report["mesh_stats"] = dict(mesh.stats)
    finally:
        await net.stop_all()

    # oracle differential: a clean network with the same topology must
    # program the same route tables (the chaos run may not bend routing)
    oracle = VirtualNetwork()
    try:
        await _build_line(oracle, nodes, os.path.join(store_dir, "oracle"))
        await wait_until(_converged(oracle, nodes), timeout=30.0)
        report["oracle_tables"] = _programmed_tables(oracle)
    finally:
        await oracle.stop_all()
    report["oracle_equal"] = (
        report["chaos_tables"] == report["oracle_tables"]
    )
    return report


def run_chaos_smoke(nodes: int = 5, seed: int = 1) -> Dict[str, Any]:
    """Drive the full hostile-network differential; returns the report
    dict CHAOS_SMOKE asserts on (and raises on any phase failure)."""
    import tempfile

    with tempfile.TemporaryDirectory() as store_dir:
        os.makedirs(os.path.join(store_dir, "oracle"), exist_ok=True)
        loop = asyncio.new_event_loop()
        try:
            return loop.run_until_complete(
                _run_chaos_smoke(store_dir, nodes, seed)
            )
        finally:
            loop.close()


if __name__ == "__main__":  # pragma: no cover
    import json

    out = run_chaos_smoke()
    out.pop("chaos_tables", None)
    out.pop("oracle_tables", None)
    print(json.dumps(out, indent=2, default=str))
