"""Test/emulation harness: whole-stack virtual nodes in one process.

Equivalent of openr/tests/OpenrWrapper.{h,cpp}:36-90 — boots ALL modules of
one virtual Open/R node (monitor → kvstore → spark → link-monitor →
decision → fib) against mock seams, so multi-node topologies run in a
single process: Spark discovery over MockIoNetwork mailboxes, KvStore
flooding over the in-process transport, route programming into
MockFibHandler. This is the no-cluster multi-node trick the reference's
OpenrSystemTest builds ring topologies with (tests/OpenrSystemTest.cpp).

Also home of the deterministic fault-injection harness
(`openr_tpu.testing.faults`): production modules (ops/spf, solver/tpu,
fib, kvstore) import `fault_point` from that submodule directly, so this
package __init__ resolves its heavyweight harness exports lazily (PEP 562)
— importing the faults seam from a hot-path module must not drag the whole
daemon stack into the import graph.
"""

_WRAPPER_EXPORTS = {"OpenrWrapper", "VirtualNetwork"}
_HARNESS_EXPORTS = {
    "assert_route_delta_equal",
    "decision_route_delta",
    "lsdb_publication",
    "run_decision_backend_parity",
}

__all__ = sorted(_WRAPPER_EXPORTS | _HARNESS_EXPORTS)


def __getattr__(name: str):
    if name in _WRAPPER_EXPORTS:
        from openr_tpu.testing import wrapper

        return getattr(wrapper, name)
    if name in _HARNESS_EXPORTS:
        from openr_tpu.testing import decision_harness

        return getattr(decision_harness, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
