"""Test/emulation harness: whole-stack virtual nodes in one process.

Equivalent of openr/tests/OpenrWrapper.{h,cpp}:36-90 — boots ALL modules of
one virtual Open/R node (monitor → kvstore → spark → link-monitor →
decision → fib) against mock seams, so multi-node topologies run in a
single process: Spark discovery over MockIoNetwork mailboxes, KvStore
flooding over the in-process transport, route programming into
MockFibHandler. This is the no-cluster multi-node trick the reference's
OpenrSystemTest builds ring topologies with (tests/OpenrSystemTest.cpp).
"""

from openr_tpu.testing.wrapper import OpenrWrapper, VirtualNetwork
from openr_tpu.testing.decision_harness import (
    assert_route_delta_equal,
    decision_route_delta,
    lsdb_publication,
    run_decision_backend_parity,
)

__all__ = [
    "OpenrWrapper",
    "VirtualNetwork",
    "assert_route_delta_equal",
    "decision_route_delta",
    "lsdb_publication",
    "run_decision_backend_parity",
]
