"""In-process fan-out cohort: the scale half of the 100k-subscriber proof.

Real TCP subscribers cap out at the file-descriptor limit (one socket
each on both ends — ~10k subscribers against a 20k fd limit), so the
100k-subscriber soak round (docs/Streaming.md, testing/soak.py --round)
runs a HYBRID cohort:

  - a real-socket cohort (a few thousand `subscribeKvStore` connections,
    mixed JSON/binary codecs, admission control and slow-client
    isolation live under load), and
  - an in-process cohort: subscribers registered directly on each
    node's `StreamManager` — indistinguishable from socket subscribers
    to the fan-out dispatch, the filter-class grouping, coalescing and
    resync machinery — drained by ONE pump task per node through the
    exact delivery path the ctrl server uses: `SharedFrame.body()`
    (shared class encode), envelope splice via the frame-segment
    builders, `note_deliver`/`mark_delivered` metering, with the bytes
    landing in a counting sink instead of a socket.

The cohort sizes are reported separately everywhere (SOAK artifact,
bench summaries) so the accounting stays honest about what was a real
socket and what was in-process.
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Dict, List

from openr_tpu.streaming import SharedFrame
from openr_tpu.streaming import codec as stream_codec


class InprocFanout:
    """`count` in-process KvStore subscribers on one daemon's
    StreamManager, drained by a single pump task.

    All subscribers share one filter class by default (`area`, no
    prefix/originator filters) — the shape the shared-encode path
    amortizes; pass `prefixes` per the class you want to exercise.
    """

    def __init__(
        self,
        daemon,
        count: int,
        *,
        codec: str = stream_codec.CODEC_JSON,
        area: str = "0",
        prefixes: List[str] | None = None,
    ) -> None:
        self.daemon = daemon
        self.count = count
        self.codec = stream_codec.normalize_codec(codec)
        self.area = area
        self.prefixes = list(prefixes or [])
        self.subs: List[Any] = []
        self._task: asyncio.Task | None = None
        self._stop = False
        self.stats: Dict[str, int] = {
            "subscribers": count,
            "frames": 0,
            "deltas": 0,
            "resyncs": 0,
            "bytes": 0,
        }

    def attach(self) -> None:
        """Register the cohort (counts against `max_subscribers`, same
        as socket subscribers — raise the cap in the node config for
        scale runs)."""
        manager = self.daemon.stream_manager
        for i in range(self.count):
            self.subs.append(
                manager.add_kvstore_subscriber(
                    area=self.area,
                    prefixes=self.prefixes,
                    label=f"inproc-{i}",
                )
            )

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._pump())

    async def stop(self) -> None:
        self._stop = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        manager = self.daemon.stream_manager
        for sub in self.subs:
            manager.remove_subscriber(sub)
        self.subs.clear()

    async def _pump(self) -> None:
        """One task drains every cohort subscriber: all members share a
        filter class, so a sequential sweep never blocks on one empty
        queue while another has frames — each sweep delivers whatever
        the dispatch enqueued since the last one."""
        manager = self.daemon.stream_manager
        seqs = [0] * len(self.subs)
        while not self._stop:
            delivered = False
            for idx, sub in enumerate(self.subs):
                while sub._frames or sub._resync_at is not None:
                    kind, frame, t_enq = await sub.next_frame()
                    if kind == "closed":
                        break
                    seqs[idx] += 1
                    if kind == "resync":
                        # the real resync cost: fresh dump + private encode
                        pub = self.daemon.kvstore.dump_all(area=self.area)
                        t0 = time.perf_counter()
                        body = stream_codec.encode_kv_body(pub, self.codec)
                        manager.note_encode(
                            (time.perf_counter() - t0) * 1e3, len(body)
                        )
                        self.stats["resyncs"] += 1
                    elif isinstance(frame, SharedFrame):
                        body = frame.body(self.codec)
                        self.stats["deltas"] += 1
                    else:
                        t0 = time.perf_counter()
                        body = stream_codec.encode_kv_body(frame, self.codec)
                        manager.note_encode(
                            (time.perf_counter() - t0) * 1e3, len(body)
                        )
                        self.stats["deltas"] += 1
                    # the per-subscriber delivery work, identical to the
                    # ctrl server's: envelope splice + buffer "write"
                    t0 = time.perf_counter()
                    segments = stream_codec.kv_frame_segments(
                        self.codec, 1, kind, seqs[idx], self.area, body
                    )
                    nbytes = sum(len(s) for s in segments)
                    manager.note_deliver(
                        (time.perf_counter() - t0) * 1e3, nbytes
                    )
                    self.stats["bytes"] += nbytes
                    self.stats["frames"] += 1
                    manager.mark_delivered(sub, t_enq)
                    delivered = True
                    # cooperative: a 12k-subscriber sweep must not
                    # monopolize the loop the daemon itself runs on
                    if self.stats["frames"] % 512 == 0:
                        await asyncio.sleep(0)
            if not delivered:
                await asyncio.sleep(0.02)
