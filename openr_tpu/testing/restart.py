"""Whole-node graceful-restart harness (RESTART_SMOKE).

The one churn class the soak harness could not drive before this module
existed: a node dying and returning. `run_restart_smoke` runs the
end-to-end warm-boot differential on an emulated line —

  - a line n0–n1–…–n(k-1) with loopback prefixes, graceful restart
    enabled (`spark_config.graceful_restart_enabled`), per-node
    configstore files (KvStore version floors + drain state survive the
    gap) and EOR gating (`eor_time_s`) so the restarted Decision holds
    its first computation until the LSDB refills;
  - the middle node is restarted through
    `VirtualNetwork.restart_node()`: the daemon's stop path floods
    restarting hellos, neighbors enter the Spark RESTART hold, the FIB
    agent object survives into the respawn carrying its routes;
  - a concurrent watcher asserts the GR invariants through the gap:
    neighbors never withdraw routes toward the restarted node's
    prefixes while it is away, and the restarted node's agent table is
    never empty (forwarding continues on stale routes);
  - a planted orphan route (a prefix the topology no longer advertises)
    proves the reconciliation sweep: post-boot it is deleted exactly
    once (`fib.stale_routes_swept`), everything else is reconciled in
    place;
  - an **oracle differential**: a second, never-restarted network with
    the same topology must end with identical programmed route tables
    on every node.

Restart failures snapshot through the PR 13 forensics path
(`Fib.dump_restart_forensics`): the harness dumps `gr_expired_mid_boot`
when a neighbor dropped the adjacency during the window and
`resync_divergence` when the oracle differential fails;
`run_stale_deadline_drill` drives the third reason — Decision
convergence fault-injected away (every inbound Spark datagram dropped),
so the restarted Fib's `stale_sweep_deadline_s` force-flushes with a
`stale_deadline_flush` dump.
"""

from __future__ import annotations

import asyncio
import os
import time
from typing import Any, Dict, List, Optional

from openr_tpu.platform import FIB_CLIENT_OPENR
from openr_tpu.testing.faults import FaultInjector, injected


def _node_overrides(extra: Optional[dict] = None) -> dict:
    overrides: Dict[str, Any] = {
        "spark_config": {"graceful_restart_enabled": True},
        # EOR gating: the restarted Decision holds its first computation
        # until the LSDB refills, so Fib's reconciliation sync runs
        # against a CONVERGED route db, not a half-synced one
        "eor_time_s": 1,
        # deterministic metrics for the oracle differential (RTT-derived
        # metrics vary run to run)
        "link_monitor_config": {"use_rtt_metric": False},
    }
    for key, value in (extra or {}).items():
        if isinstance(value, dict) and isinstance(overrides.get(key), dict):
            overrides[key] = {**overrides[key], **value}
        else:
            overrides[key] = value
    return overrides


def _programmed_tables(net) -> Dict[str, Dict[str, List[tuple]]]:
    """node -> {prefix: sorted (address, iface) nexthops} — the oracle
    comparison key (metrics excluded: RTT-free runs pin them anyway)."""
    out: Dict[str, Dict[str, List[tuple]]] = {}
    for name, wrapper in net.wrappers.items():
        table = wrapper.fib_handler.unicast_routes.get(FIB_CLIENT_OPENR, {})
        out[name] = {
            str(dest): sorted((nh.address, nh.iface) for nh in r.nexthops)
            for dest, r in table.items()
        }
    return out


async def _build_line(net, n: int, store_dir: str) -> None:
    for i in range(n):
        net.add_node(
            f"n{i}",
            loopback_prefix=f"10.{i}.0.0/24",
            # the state journal rides the restart differential: per-node
            # durable logs next to the configstore files, so the respawn
            # reloads its pre-crash history (replay parity is asserted
            # against the never-restarted oracle below)
            config_overrides=_node_overrides(
                {
                    "journal_config": {
                        "enabled": True,
                        "path": os.path.join(
                            store_dir, f"n{i}.journal.bin"
                        ),
                        # flush every append: the restart gap must not
                        # lose the tail to a pending batch timer
                        "flush_interval_s": 0.0,
                    }
                }
            ),
            config_store_path=os.path.join(store_dir, f"n{i}.bin"),
        )
    await net.start_all()
    for i in range(n - 1):
        net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")


def _converged(net, n: int):
    def check() -> bool:
        for i in range(n):
            got = set(net.wrappers[f"n{i}"].programmed_prefixes())
            want = {f"10.{j}.0.0/24" for j in range(n) if j != i}
            if not want.issubset(got):
                return False
        return True

    return check


def run_restart_smoke() -> Dict[str, Any]:
    """RESTART_SMOKE tier-1: restart the middle node of a line and assert
    the full warm-boot contract. Topology size scales via
    RESTART_SMOKE_NODES; returns a report dict."""
    import tempfile

    from openr_tpu.testing.wrapper import VirtualNetwork, wait_until
    from openr_tpu.types import IpPrefix, NextHop, UnicastRoute

    n = max(3, int(os.environ.get("RESTART_SMOKE_NODES", "3")))
    mid = n // 2
    mid_name = f"n{mid}"
    mid_prefix = f"10.{mid}.0.0/24"
    orphan_prefix = "10.99.0.0/24"

    async def body(store_dir: str) -> Dict[str, Any]:
        net = VirtualNetwork()
        await _build_line(net, n, store_dir)
        converged = _converged(net, n)
        try:
            await wait_until(converged, timeout=30.0)

            # plant an orphan route in the middle node's agent: a prefix
            # the topology no longer advertises. It must survive the gap
            # (forwarding continuity) and be swept EXACTLY ONCE by the
            # post-boot reconciliation.
            mid_handler = net.wrappers[mid_name].fib_handler
            mid_handler.unicast_routes.setdefault(FIB_CLIENT_OPENR, {})[
                IpPrefix(orphan_prefix)
            ] = UnicastRoute(
                IpPrefix(orphan_prefix),
                (NextHop(address="fe80::dead", iface="if0"),),
            )

            neighbors = [f"n{mid - 1}", f"n{mid + 1}"]
            down_before = {
                name: net.wrappers[name].daemon.link_monitor.counters.get(
                    "link_monitor.neighbor_down", 0
                )
                for name in neighbors
            }

            # GR invariant watcher: from restart initiation until the
            # respawned node re-establishes its first adjacency, the
            # neighbors must keep forwarding toward the restarted node's
            # prefix and its own agent table must never be empty
            violations: List[str] = []
            watch_done = asyncio.Event()
            old_daemon = net.wrappers[mid_name].daemon

            async def watch() -> None:
                while not watch_done.is_set():
                    current = net.wrappers[mid_name].daemon
                    if (
                        current is not old_daemon
                        and current.link_monitor.adjacencies
                    ):
                        return  # respawn re-established: GR window over
                    for name in neighbors:
                        if mid_prefix not in net.wrappers[
                            name
                        ].programmed_prefixes():
                            violations.append(
                                f"{name} withdrew {mid_prefix} during GR"
                            )
                    if not mid_handler.unicast_routes.get(
                        FIB_CLIENT_OPENR
                    ):
                        violations.append(
                            f"{mid_name} agent table emptied during gap"
                        )
                    await asyncio.sleep(0.01)

            watcher = asyncio.get_event_loop().create_task(watch())
            pre_restart_seq = old_daemon.journal.stats()["last_seq"]
            t_restart = time.monotonic()
            respawn = await net.restart_node(mid_name)
            try:
                await asyncio.wait_for(watcher, timeout=30.0)
            finally:
                watch_done.set()
            assert not violations, violations

            # full reconvergence of the restarted network
            await wait_until(
                lambda: converged()
                and orphan_prefix
                not in net.wrappers[mid_name].programmed_prefixes(),
                timeout=30.0,
            )
            restart_s = time.monotonic() - t_restart

            fib = respawn.daemon.fib
            spark_counts = {
                name: dict(net.wrappers[name].daemon.spark.counters)
                for name in neighbors
            }
            # neighbors rode the GR hold: no NEIGHBOR_DOWN ever published
            for name in neighbors:
                after = net.wrappers[name].daemon.link_monitor.counters.get(
                    "link_monitor.neighbor_down", 0
                )
                if after != down_before[name]:
                    fib.dump_restart_forensics(
                        "gr_expired_mid_boot",
                        extra={"neighbor": name},
                    )
                    raise AssertionError(
                        f"{name} dropped the adjacency during the GR "
                        f"window (neighbor_down {down_before[name]} -> "
                        f"{after})"
                    )
                assert (
                    spark_counts[name].get("spark.gr_holds_active", 0) == 0
                ), spark_counts[name]
                assert (
                    spark_counts[name].get("spark.gr_hold_expiries", 0) == 0
                ), spark_counts[name]

            # warm-boot bookkeeping on the respawned node
            assert fib.counters.get("fib.warm_boots") == 1, fib.counters
            assert (
                fib.counters.get("fib.restart_reconciles") == 1
            ), fib.counters
            # the orphan was swept exactly once; nothing else was deleted
            assert (
                fib.counters.get("fib.stale_routes_swept") == 1
            ), fib.counters
            assert not fib.route_state.has_stale()
            assert (
                fib.counters.get("fib.stale_deadline_flushes", 0) == 0
            ), fib.counters
            # the restarting-hello -> post-boot-sync span closed
            restart_hist = fib.histograms.get("restart.e2e_ms")
            assert restart_hist is not None and restart_hist.count == 1
            # self-originated keys re-advertised above the persisted floor
            kv_counters = respawn.daemon.kvstore.counters
            assert kv_counters.get("kvstore.restart_syncs", 0) >= 1, (
                kv_counters
            )

            # the journal survived the restart: the respawn reloaded the
            # pre-crash durable log (sequence numbers continue past the
            # crash point, with no torn-tail truncation) and kept
            # recording through reconvergence
            journal = respawn.daemon.journal
            journal_stats = journal.stats()
            assert pre_restart_seq > 0
            assert journal_stats["last_seq"] > pre_restart_seq, (
                f"journal did not survive the restart: respawn at seq "
                f"{journal_stats['last_seq']} vs {pre_restart_seq} "
                f"pre-crash"
            )
            assert (
                journal_stats["counters"].get("journal.load_truncations", 0)
                == 0
            ), journal_stats["counters"]
            # replay determinism across the restart: every node's
            # reconstructed RIB re-derives through the CPU oracle
            journal_verified = 0
            for name, wrapper in net.wrappers.items():
                verdict = wrapper.daemon.journal.verify_replay()
                assert verdict["match"], (name, verdict["mismatches"])
                journal_verified += 1
            replayed_mid_rib = {
                str(prefix): entry
                for prefix, entry in (
                    journal.replay_at().rib.unicast_entries.items()
                )
            }

            restarted_tables = _programmed_tables(net)
        finally:
            await net.stop_all()

        # oracle differential: a never-restarted run of the same topology
        # must program identical route tables on every node
        oracle_net = VirtualNetwork()
        oracle_dir = os.path.join(store_dir, "oracle")
        os.makedirs(oracle_dir, exist_ok=True)
        await _build_line(oracle_net, n, oracle_dir)
        try:
            await wait_until(_converged(oracle_net, n), timeout=30.0)
            oracle_tables = _programmed_tables(oracle_net)
            # replay parity across the restart: the restarted node's
            # journal, reloaded from disk through the crash, must replay
            # to the SAME RIB the never-restarted oracle's journal
            # replays to (RibUnicastEntry equality: prefix + nexthops +
            # metrics, best_area excluded)
            oracle_mid_rib = {
                str(prefix): entry
                for prefix, entry in (
                    oracle_net.wrappers[mid_name]
                    .daemon.journal.replay_at()
                    .rib.unicast_entries.items()
                )
            }
        finally:
            await oracle_net.stop_all()
        assert replayed_mid_rib == oracle_mid_rib, (
            f"replay divergence across restart: "
            f"{sorted(set(replayed_mid_rib) ^ set(oracle_mid_rib))}"
        )
        if restarted_tables != oracle_tables:
            # report through the same forensics seam operators would read
            diverged = {
                name
                for name in restarted_tables
                if restarted_tables[name] != oracle_tables.get(name)
            }
            raise AssertionError(
                f"resync_divergence: post-boot route tables differ from "
                f"the never-restarted oracle on {sorted(diverged)}"
            )

        return {
            "nodes": n,
            "restarted": mid_name,
            "restart_s": round(restart_s, 3),
            "restart_e2e_ms": restart_hist.to_dict(),
            "fib_counters": {
                k: v
                for k, v in fib.counters.items()
                if "warm" in k or "stale" in k or "restart" in k
            },
            "kvstore_restart_syncs": kv_counters.get(
                "kvstore.restart_syncs", 0
            ),
            "oracle_parity": True,
            "journal_survived_restart": True,
            "journal_pre_restart_seq": pre_restart_seq,
            "journal_last_seq": journal_stats["last_seq"],
            "journal_verified_nodes": journal_verified,
            "journal_replay_parity": True,
        }

    loop = asyncio.new_event_loop()
    try:
        with tempfile.TemporaryDirectory() as td:
            return loop.run_until_complete(body(td))
    finally:
        loop.close()


def run_stale_deadline_drill() -> Dict[str, Any]:
    """Acceptance drill for the bounded-staleness path: restart one node
    of a pair with every inbound Spark datagram fault-injected away, so
    Decision never reconverges. The warm-boot stale set must force-flush
    at `stale_sweep_deadline_s` with a `stale_deadline_flush` forensics
    dump, and the neighbor's GR hold must expire into NEIGHBOR_DOWN
    (`gr_expired_mid_boot`, dumped through the same seam)."""
    import tempfile

    from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

    async def body(store_dir: str) -> Dict[str, Any]:
        net = VirtualNetwork()
        for i, name in enumerate(("a", "b")):
            net.add_node(
                name,
                loopback_prefix=f"10.{i}.0.0/24",
                config_overrides=_node_overrides(),
                config_store_path=os.path.join(store_dir, f"{name}.bin"),
            )
        await net.start_all()
        net.connect("a", "ifa", "b", "ifb")

        def converged() -> bool:
            return (
                "10.1.0.0/24" in net.wrappers["a"].programmed_prefixes()
                and "10.0.0.0/24"
                in net.wrappers["b"].programmed_prefixes()
            )

        try:
            await wait_until(converged, timeout=30.0)
            b_handler = net.wrappers["b"].fib_handler
            assert b_handler.unicast_routes.get(FIB_CLIENT_OPENR)

            with injected(FaultInjector(seed=11)) as inj:
                # Decision convergence fault-injected away: every inbound
                # datagram on b's interface drops, so the respawned b
                # never rediscovers a — no adjacency, no LSDB, no routes
                inj.arm(
                    "spark.packet_recv",
                    times=None,
                    when=lambda received: received is not None
                    and received.if_name == "ifb",
                )
                respawn = await net.restart_node(
                    "b",
                    config_overrides=_node_overrides(
                        {"fib_config": {"stale_sweep_deadline_s": 0.5}},
                    ),
                )
                fib = respawn.daemon.fib
                await wait_until(
                    lambda: fib.counters.get(
                        "fib.stale_deadline_flushes", 0
                    )
                    == 1,
                    timeout=15.0,
                )
                # the force-flush swept every leftover stale route (the
                # route db is empty: bounded blackholing, not stale
                # forwarding forever)
                await wait_until(
                    lambda: not b_handler.unicast_routes.get(
                        FIB_CLIENT_OPENR
                    ),
                    timeout=10.0,
                )
                assert fib.counters.get("fib.stale_routes_swept", 0) >= 1
                dumps = fib._forensics.dump_summaries()
                assert any(
                    d["reason"] == "stale_deadline_flush" for d in dumps
                ), dumps

                # the neighbor's GR hold expires mid-boot (b never came
                # back as far as a can tell) -> NEIGHBOR_DOWN, snapshot
                # through the same forensics seam
                a_spark = net.wrappers["a"].daemon.spark
                await wait_until(
                    lambda: a_spark.counters.get(
                        "spark.gr_hold_expiries", 0
                    )
                    >= 1,
                    timeout=15.0,
                )
                fib.dump_restart_forensics(
                    "gr_expired_mid_boot", extra={"neighbor": "a"}
                )
                dumps = fib._forensics.dump_summaries()
                assert any(
                    d["reason"] == "gr_expired_mid_boot" for d in dumps
                ), dumps
                return {
                    "flushes": fib.counters.get(
                        "fib.stale_deadline_flushes"
                    ),
                    "swept": fib.counters.get("fib.stale_routes_swept"),
                    "forensics": dumps,
                    "gr_hold_expiries": a_spark.counters.get(
                        "spark.gr_hold_expiries"
                    ),
                }
        finally:
            await net.stop_all()

    loop = asyncio.new_event_loop()
    try:
        with tempfile.TemporaryDirectory() as td:
            return loop.run_until_complete(body(td))
    finally:
        loop.close()
