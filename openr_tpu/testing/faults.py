"""Deterministic fault-injection harness.

Production modules declare *named fault points* — `fault_point("fib.sync")`
— at the exact seams where real deployments fail (device solve dispatch,
FIB agent RPCs, KvStore flood sends). With no injector installed a fault
point is a single global-None check, so the serving path pays nothing.
Tests install a `FaultInjector` and arm schedules against those names:

    with injected(FaultInjector(seed=7)) as inj:
        inj.arm("solver.tpu.solve", times=3)          # next 3 solves raise
        inj.arm("fib.sync", probability=0.5, times=8) # seeded coin flips
        inj.arm("fib.keepalive", action=lambda fib: handler.restart())
        ...

Determinism rules:
  - trigger-count schedules (`after` skip + `times` budget) are exact;
  - probability schedules draw from the injector's own seeded RNG, so a
    given seed replays the same fault pattern;
  - every decision is recorded (`hits` / `fired`) for assertions.

The injector never fires on its own thread or timer — faults happen only
when execution reaches the instrumented seam, which keeps multi-module
failure scenarios (e.g. Decision(tpu)→Fib flap sequences) fully
reproducible without real hardware errors. This is the testing half of the
solver fault domain (docs/Robustness.md); `SolverSupervisor` et al. are
the serving half.
"""

from __future__ import annotations

import contextlib
import random
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional


class FaultInjected(RuntimeError):
    """Default exception raised by an armed fault point."""

    def __init__(self, point: str) -> None:
        super().__init__(f"injected fault at {point}")
        self.point = point


@dataclass
class FaultSpec:
    """One armed schedule for one named fault point.

    The fault fires when all of:
      - more than `after` hits have already been observed (skip-count);
      - the `times` budget (None = unlimited) is not exhausted;
      - the seeded coin flip passes (`probability`, default always).

    Firing raises `exc(point)` — or calls `action(ctx)` instead when an
    action is armed (state-mutating faults: agent restarts, warm-state
    corruption), in which case nothing is raised unless the action raises.
    """

    point: str
    times: Optional[int] = 1
    probability: float = 1.0
    after: int = 0
    exc: Callable[[str], BaseException] = FaultInjected
    action: Optional[Callable[[Any], None]] = None
    # instance targeting: hits whose ctx fails the predicate are ignored
    # entirely (multi-instance scenarios arm one module object, not all)
    when: Optional[Callable[[Any], bool]] = None
    # bookkeeping
    hits: int = 0
    fired: int = 0

    def exhausted(self) -> bool:
        return self.times is not None and self.fired >= self.times


@dataclass
class FaultInjector:
    """Named fault points with deterministic trigger schedules."""

    seed: int = 0
    _specs: Dict[str, FaultSpec] = field(default_factory=dict)
    _hits: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)

    # -- arming --------------------------------------------------------

    def arm(
        self,
        point: str,
        *,
        times: Optional[int] = 1,
        probability: float = 1.0,
        after: int = 0,
        exc: Callable[[str], BaseException] = FaultInjected,
        action: Optional[Callable[[Any], None]] = None,
        when: Optional[Callable[[Any], bool]] = None,
    ) -> FaultSpec:
        assert 0.0 <= probability <= 1.0, probability
        spec = FaultSpec(
            point=point,
            times=times,
            probability=probability,
            after=after,
            exc=exc,
            action=action,
            when=when,
        )
        self._specs[point] = spec
        return spec

    def disarm(self, point: str) -> None:
        self._specs.pop(point, None)

    def reset(self) -> None:
        self._specs.clear()
        self._hits.clear()

    # -- introspection -------------------------------------------------

    def hits(self, point: str) -> int:
        """How many times execution reached the point (armed or not)."""
        return self._hits.get(point, 0)

    def fired(self, point: str) -> int:
        spec = self._specs.get(point)
        return spec.fired if spec is not None else 0

    def spec(self, point: str) -> Optional[FaultSpec]:
        return self._specs.get(point)

    # -- the firing seam -----------------------------------------------

    def fire(self, point: str, ctx: Any = None) -> None:
        """Called by `fault_point`; raises/acts when the point is armed and
        its schedule says so."""
        self._hits[point] = self._hits.get(point, 0) + 1
        spec = self._specs.get(point)
        if spec is None or spec.exhausted():
            return
        if spec.when is not None and not spec.when(ctx):
            return
        spec.hits += 1
        if spec.hits <= spec.after:
            return
        if spec.probability < 1.0 and self._rng.random() >= spec.probability:
            return
        spec.fired += 1
        if spec.action is not None:
            spec.action(ctx)
            return
        raise spec.exc(spec.point)


# ---------------------------------------------------------------------------
# global installation (what production fault points consult)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_installed: Optional[FaultInjector] = None


def install(injector: FaultInjector) -> FaultInjector:
    global _installed
    with _lock:
        _installed = injector
    return injector


def uninstall() -> None:
    global _installed
    with _lock:
        _installed = None


def installed() -> Optional[FaultInjector]:
    return _installed


def fault_point(name: str, ctx: Any = None) -> None:
    """Production seam: no-op unless an injector is installed AND has an
    armed, unexhausted schedule for `name`."""
    inj = _installed
    if inj is not None:
        inj.fire(name, ctx)


@contextlib.contextmanager
def injected(injector: Optional[FaultInjector] = None):
    """Install an injector for the scope of a with-block (always
    uninstalls, even when the injected fault propagates out)."""
    inj = injector if injector is not None else FaultInjector()
    install(inj)
    try:
        yield inj
    finally:
        uninstall()


# Fault-point catalog (docs/Robustness.md keeps the authoritative table):
#   solver.tpu.solve      _AreaSolve device solve dispatch (solver/tpu.py)
#   solver.tpu.warm_d     post-solve hook, ctx=_AreaSolve — corrupt warm D
#   ops.spf.batched_spf   cold batched solve entry (ops/spf.py)
#   ops.spf.batched_spf_vw  per-row-weights solve entry (KSP path)
#   fib.program           route-delta programming RPC block (fib/fib.py)
#   fib.sync              full-state syncFib push (fib/fib.py)
#   fib.keepalive         agent aliveSince poll, ctx=Fib (fib/fib.py)
#   kvstore.flood_send    per-peer flood RPC, ctx=peer name (kvstore/store.py)
#   kvstore.full_sync     3-way full-sync dump RPC, ctx=peer name
#   kvstore.quarantine_probe  quarantined-peer probe dump RPC, ctx=peer name
#   kvstore.anti_entropy  adaptive anti-entropy digest sync, ctx=peer name
#   spark.packet_send     outbound datagram seam, ctx=iface (spark/spark.py)
#   spark.packet_recv     inbound datagram seam, ctx=ReceivedPacket
#   te.optimize           TE optimization device dispatch (te/service.py)
#   monitor.exporter.push metrics push-sink write, ctx=MetricsExporter
#                         (monitor/exporter.py)
#   ctrl.stream.publish   streaming fan-out dispatch, ctx=item
#                         (streaming/subscription.py)
#   ctrl.stream.deliver   per-frame stream delivery, ctx=subscription;
#                         actions may set sub.throttle_s (ctrl/server.py)
#   ctrl.admission.dispatch  admitted expensive-RPC dispatch, ctx=method
#                         (streaming/admission.py)
#   configstore.save      PersistentStore durable write (journal append or
#                         snapshot compaction), ctx=PersistentStore
#                         (configstore/persistent_store.py)
#   configstore.load      PersistentStore boot-time read, ctx=PersistentStore
#   fleet.scrape          fleet-observer per-node scrape, ctx=node name
#                         (fleet/observer.py)
