"""Shared Decision parity harness.

One implementation of "feed the same publication to Decision(backend=X) and
Decision(backend=Y), compare the emitted route deltas" used by both the
driver dry-run (__graft_entry__._dryrun_daemon_path) and the test suite
(tests/test_tpu_solver_mesh.py) — so Decision startup/shutdown or
Publication-shape changes have one place to land.
"""

from __future__ import annotations

import asyncio
import time
from typing import Iterable, List, Optional, Tuple

from openr_tpu.decision import Decision, DecisionConfig
from openr_tpu.messaging import ReplicateQueue, RQueue, RWQueue
from openr_tpu.types import (
    IpPrefix,
    PrefixDatabase,
    PrefixEntry,
    Publication,
    Value,
    adj_key,
    prefix_key,
)
from openr_tpu.utils import serializer


def lsdb_publication(
    adj_dbs: Iterable, announcers: Optional[dict] = None, area: str = "0"
) -> Publication:
    """One KvStore publication carrying full adjacency databases plus
    per-node prefix announcements ({node: [prefix_str, ...]})."""
    pub = Publication(area=area)
    for db in adj_dbs:
        pub.key_vals[adj_key(db.this_node_name)] = Value(
            1, db.this_node_name, serializer.dumps(db)
        )
    for node, pfxs in (announcers or {}).items():
        pdb = PrefixDatabase(
            node, [PrefixEntry(IpPrefix(p)) for p in pfxs]
        )
        pub.key_vals[prefix_key(node)] = Value(
            1, node, serializer.dumps(pdb)
        )
    return pub


async def decision_route_delta(
    my_node: str,
    publication: Publication,
    backend: str,
    mesh: Optional[tuple] = None,
    timeout: float = 30.0,
):
    """Boot a Decision, push one publication, await + return the emitted
    route delta, and shut the module down cleanly (task awaited)."""
    kv_q: RWQueue = RWQueue()
    route_q: ReplicateQueue = ReplicateQueue()
    decision = Decision(
        DecisionConfig(
            my_node_name=my_node,
            solver_backend=backend,
            solver_mesh=mesh,
            debounce_min=0.005,
            debounce_max=0.02,
        ),
        RQueue(kv_q),
        route_q,
    )
    reader = route_q.get_reader()
    decision.start()
    try:
        kv_q.push(publication)
        return await asyncio.wait_for(reader.get(), timeout)
    finally:
        task = decision._task
        decision.stop()
        if task is not None:
            await asyncio.gather(task, return_exceptions=True)


def assert_route_delta_equal(a, b) -> Tuple[int, int]:
    """Compare two DecisionRouteUpdates; returns (n_unicast, n_mpls)."""
    a_uni = {e.prefix: e for e in a.unicast_routes_to_update}
    b_uni = {e.prefix: e for e in b.unicast_routes_to_update}
    assert a_uni == b_uni, "unicast route delta mismatch"
    a_mpls = {e.label: e for e in a.mpls_routes_to_update}
    b_mpls = {e.label: e for e in b.mpls_routes_to_update}
    assert a_mpls == b_mpls, "mpls route delta mismatch"
    assert sorted(a.unicast_routes_to_delete) == sorted(
        b.unicast_routes_to_delete
    )
    assert sorted(a.mpls_routes_to_delete) == sorted(b.mpls_routes_to_delete)
    return len(a_uni), len(a_mpls)


async def run_convergence_trace(
    my_node: str,
    publications: Iterable[Publication],
    backend: str = "tpu",
    mesh: Optional[tuple] = None,
    timeout: float = 30.0,
):
    """Full KvStore→Decision→Fib observability pass.

    Boots Decision(backend) and a dryrun Fib wired by the route queue plus
    a Monitor aggregating both (the daemon's registration layout), stamps
    and pushes each publication the way KvStore.flood_publication does, and
    waits for Fib to close that event's convergence span before pushing the
    next — each publication MUST change routes or this times out. Returns
    (monitor, decision, fib) with the modules stopped but their counters,
    histograms and the monitor's event-log ring intact for assertions.
    """
    from openr_tpu.fib import Fib, FibConfig
    from openr_tpu.monitor import Monitor
    from openr_tpu.platform import MockFibHandler

    kv_q: RWQueue = RWQueue()
    route_q: ReplicateQueue = ReplicateQueue()
    log_q: ReplicateQueue = ReplicateQueue()
    decision = Decision(
        DecisionConfig(
            my_node_name=my_node,
            solver_backend=backend,
            solver_mesh=mesh,
            debounce_min=0.005,
            debounce_max=0.02,
        ),
        RQueue(kv_q),
        route_q,
    )
    fib = Fib(
        FibConfig(my_node_name=my_node, dryrun=True, cold_start_duration=0.0),
        MockFibHandler(),
        route_q.get_reader(),
        log_sample_fn=log_q.push,
    )
    monitor = Monitor(my_node, log_q.get_reader())
    monitor.register_module("decision", decision)
    monitor.register_module("fib", fib)
    monitor.start()
    decision.start()
    fib.start()
    loop = asyncio.get_running_loop()
    try:
        done = 0
        for pub in publications:
            pub.ts_monotonic = time.monotonic()
            kv_q.push(pub)
            done += 1
            deadline = loop.time() + timeout
            while (
                fib.histograms.get("convergence.e2e_ms") is None
                or fib.histograms["convergence.e2e_ms"].count < done
            ):
                if loop.time() > deadline:
                    raise TimeoutError(
                        f"publication {done} produced no convergence span"
                    )
                await asyncio.sleep(0.005)
        # let the monitor drain the emitted CONVERGENCE_TRACE samples
        deadline = loop.time() + timeout
        while len(monitor.get_event_logs()) < done:
            if loop.time() > deadline:
                raise TimeoutError("monitor did not drain span log samples")
            await asyncio.sleep(0.005)
    finally:
        tasks: List[asyncio.Task] = [
            t for t in (decision._task, *fib._tasks) if t is not None
        ]
        fib.stop()
        decision.stop()
        monitor.stop()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
    return monitor, decision, fib


def _fib_table(handler) -> dict:
    """dest -> frozenset of (address, iface) actually programmed."""
    from openr_tpu.platform import FIB_CLIENT_OPENR

    return {
        dest: frozenset((nh.address, nh.iface) for nh in route.nexthops)
        for dest, route in handler.unicast_routes.get(
            FIB_CLIENT_OPENR, {}
        ).items()
    }


def run_fault_smoke() -> dict:
    """FAULT_SMOKE tier-1 smoke: a short Decision(tpu)→Fib flap sequence
    with one injected solver failure and one injected fib-program failure,
    asserting convergence completes DEGRADED — the supervised tpu stack's
    programmed FIB stays identical to an unfaulted CPU-oracle stack fed
    the same publications, while the breaker serves from the fallback and
    Fib recovers through its dirty-marking + full-resync path.

    Topology size comes from FAULT_SMOKE_SIDE (grid side, default 3) so CI
    can scale it; returns a summary dict of the degraded-path evidence.
    """
    import os

    from openr_tpu.fib import Fib, FibConfig
    from openr_tpu.platform import MockFibHandler
    from openr_tpu.testing.faults import FaultInjector, injected
    from openr_tpu.topology import build_adj_dbs, grid_edges

    side = int(os.environ.get("FAULT_SMOKE_SIDE", "3"))
    edges = grid_edges(side)
    far = f"g{side - 1}_{side - 1}"
    announcers = {far: ["10.1.0.0/24"], f"g0_{side - 1}": ["10.2.0.0/24"]}

    def build_stack(backend, handler, **decision_kw):
        kv_q: RWQueue = RWQueue()
        route_q: ReplicateQueue = ReplicateQueue()
        decision = Decision(
            DecisionConfig(
                my_node_name="g0_0",
                solver_backend=backend,
                debounce_min=0.005,
                debounce_max=0.02,
                **decision_kw,
            ),
            RQueue(kv_q),
            route_q,
        )
        fib = Fib(
            FibConfig(
                my_node_name="g0_0",
                dryrun=False,
                cold_start_duration=0.0,
                backoff_min=0.002,
                backoff_max=0.05,
                backoff_seed=0,
            ),
            handler,
            route_q.get_reader(),
        )
        return kv_q, decision, fib

    async def body() -> dict:
        tpu_handler = MockFibHandler()
        cpu_handler = MockFibHandler()
        # one injected solver failure with failure_threshold=1: the very
        # first device solve trips the breaker and the event converges
        # via the CPU fallback — degraded, never wrong
        kv_tpu, dec_tpu, fib_tpu = build_stack(
            "tpu",
            tpu_handler,
            solver_failure_threshold=1,
            solver_max_attempts=1,
            solver_probe_interval_s=3600.0,  # no probe flips mid-smoke
        )
        kv_cpu, dec_cpu, fib_cpu = build_stack("cpu", cpu_handler)

        with injected(FaultInjector(seed=1)) as inj:
            inj.arm("solver.tpu.solve", times=1)
            inj.arm(
                "fib.program",
                times=1,
                when=lambda ctx: ctx is fib_tpu,  # spare the oracle stack
            )
            for module in (dec_tpu, fib_tpu, dec_cpu, fib_cpu):
                module.start()
            loop = asyncio.get_running_loop()

            async def converge(timeout=20.0):
                deadline = loop.time() + timeout
                while True:
                    t_tpu, t_cpu = _fib_table(tpu_handler), _fib_table(
                        cpu_handler
                    )
                    if (
                        t_tpu
                        and t_tpu == t_cpu
                        and fib_tpu.has_synced_fib
                        and not fib_tpu._sync_scheduled
                    ):
                        return t_tpu
                    if loop.time() > deadline:
                        raise TimeoutError(
                            f"fault smoke did not converge: "
                            f"tpu={sorted(map(str, t_tpu))} "
                            f"cpu={sorted(map(str, t_cpu))}"
                        )
                    await asyncio.sleep(0.005)

            try:
                dbs = build_adj_dbs(edges)
                kv_tpu.push(lsdb_publication(dbs.values(), announcers))
                kv_cpu.push(lsdb_publication(dbs.values(), announcers))
                table1 = await converge()

                # flap: bump one spine link's metric and republish the
                # two endpoint adj dbs (the incremental event path)
                flapped = [
                    (a, b, 7 if (a, b) == ("g0_0", "g0_1") else m)
                    for a, b, m in edges
                ]
                dbs2 = build_adj_dbs(flapped)
                flap_pub = lsdb_publication(
                    [dbs2["g0_0"], dbs2["g0_1"]]
                )
                kv_tpu.push(flap_pub)
                kv_cpu.push(flap_pub)
                table2 = await converge()
            finally:
                tasks = [
                    t
                    for t in (
                        dec_tpu._task,
                        dec_cpu._task,
                        *fib_tpu._tasks,
                        *fib_cpu._tasks,
                    )
                    if t is not None
                ]
                for module in (fib_tpu, fib_cpu, dec_tpu, dec_cpu):
                    module.stop()
                if tasks:
                    await asyncio.gather(*tasks, return_exceptions=True)

            health = dec_tpu.get_solver_health()
            summary = {
                "converged": bool(table1) and bool(table2),
                "routes_programmed": len(table2),
                "solver_faults_fired": inj.fired("solver.tpu.solve"),
                "fib_faults_fired": inj.fired("fib.program"),
                "fallback_active": health["fallback_active"],
                "breaker_state": health["breaker_state"],
                "solver_failures": dec_tpu.solver.counters.get(
                    "decision.spf.solver_failures", 0
                ),
                "fib_program_failures": fib_tpu.counters.get(
                    "fib.thrift.failure.add_del_route", 0
                ),
                "fib_sync_calls": fib_tpu.counters.get(
                    "fib.sync_fib_calls", 0
                ),
            }
        assert summary["solver_faults_fired"] == 1, summary
        assert summary["fib_faults_fired"] == 1, summary
        assert summary["fallback_active"] == 1, summary
        assert summary["fib_program_failures"] >= 1, summary
        assert summary["converged"], summary
        return summary

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(body())
    finally:
        loop.close()


def _measure_exporter_overhead(net) -> dict:
    """Exporter-overhead measurement on a converged emulator run (the
    bench 'exporter_scrape_render_ms' line): best full-registry render
    latency across nodes (each render parsed back to keep the sample
    honest — an exposition that stops parsing is a failure, not a fast
    render), plus the per-record windowed-rollup cost measured by
    replaying the run's real span samples into a fresh rollup."""
    import os

    from openr_tpu.monitor.exporter import parse_metrics_text
    from openr_tpu.monitor.report import ConvergenceRollup

    render_ms: List[float] = []
    series = 0
    for wrapper in net.wrappers.values():
        wrapper.daemon.exporter.render()  # warm the self-metric families
        t0 = time.perf_counter()
        text = wrapper.daemon.exporter.render()
        render_ms.append((time.perf_counter() - t0) * 1e3)
        series = max(series, len(parse_metrics_text(text)["types"]))

    spans = [
        span for report in net.node_reports() for span in report["spans"]
    ]
    records = max(1, int(os.environ.get("BENCH_EXPORTER_RECORDS", "2000")))
    rollup = ConvergenceRollup(window_s=60.0)
    replayed = 0
    t0 = time.perf_counter()
    while spans and replayed < records:
        for span in spans:
            rollup.record_span(span)
            replayed += 1
            if replayed >= records:
                break
    elapsed = time.perf_counter() - t0
    return {
        "scrape_render_ms": round(min(render_ms), 4) if render_ms else 0.0,
        "rollup_record_us": (
            round(elapsed / replayed * 1e6, 3) if replayed else 0.0
        ),
        "metrics_series": series,
    }


# stage-duration keys every node's flap span must carry (the spark→fib
# chain; flood-hop stages are topology-dependent and checked separately)
TRACE_SMOKE_STAGES = (
    "spark.neighbor_event_ms",
    "linkmonitor.adj_advertised_ms",
    "kvstore.publish_ms",
    "decision.recv_ms",
    "decision.debounce_ms",
    "decision.route_build_ms",
    "fib.recv_ms",
    "fib.program_ms",
)


def run_trace_smoke() -> dict:
    """TRACE_SMOKE tier-1 smoke (the observability sibling of
    run_fault_smoke): an N-node line-topology emulator run
    (TRACE_SMOKE_NODES, default 5) converges, one link flaps, and the
    network-wide trace substrate must hold up end to end —

      - every node finishes a COMPLETE spark→fib convergence span
        (locally-stamped monotonic stages on the flap endpoints,
        flood-reconstructed stages on remote nodes);
      - flood hop counts match topology distance on the line (node i
        receives the flap origin's publication after exactly i-1 hops);
      - the aggregated report (VirtualNetwork.convergence_report, the
        `breeze perf report` math) carries sane network-wide percentiles
        with slowest-hop attribution.

    Returns a summary dict of the evidence.
    """
    import os

    from openr_tpu.monitor.report import aggregate_convergence_reports
    from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

    n = max(3, int(os.environ.get("TRACE_SMOKE_NODES", "5")))

    def complete_span(report: dict) -> bool:
        return any(
            all(span.get(stage) is not None for stage in TRACE_SMOKE_STAGES)
            for span in report["spans"]
        )

    async def body() -> dict:
        net = VirtualNetwork()
        for i in range(n):
            net.add_node(f"n{i}", loopback_prefix=f"10.{i}.0.0/24")
        await net.start_all()
        for i in range(n - 1):
            net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")

        def converged() -> bool:
            for i in range(n):
                got = set(net.wrappers[f"n{i}"].programmed_prefixes())
                want = {f"10.{j}.0.0/24" for j in range(n) if j != i}
                if not want.issubset(got):
                    return False
            return True

        try:
            await wait_until(converged, timeout=60.0)

            # the flap: sever n0–n1; n1's adjacency withdrawal floods down
            # the line and every node reprograms (withdraws 10.0.0.0/24)
            net.fail_link("n0", "if0r", "n1", "if1l")

            def withdrawn() -> bool:
                for i in range(1, n):
                    got = net.wrappers[f"n{i}"].programmed_prefixes()
                    if "10.0.0.0/24" in got:
                        return False
                return True

            await wait_until(withdrawn, timeout=60.0)
            # spans finish asynchronously of route state: poll the monitor
            # rings until every node shows a complete spark→fib span
            await wait_until(
                lambda: all(complete_span(r) for r in net.node_reports()),
                timeout=30.0,
            )

            reports = {r["node"]: r for r in net.node_reports()}
            hop_evidence = {}
            for i in range(2, n):
                node = f"n{i}"
                hops = [
                    f["hop_count"]
                    for f in reports[node]["floods"]
                    if f.get("origin") == "n1"
                ]
                assert (i - 1) in hops, (node, sorted(set(hops)))
                hop_evidence[node] = i - 1
                # remote nodes measured per-hop flood latency
                assert any(
                    f.get("hop_ms") is not None
                    for f in reports[node]["floods"]
                ), node

            agg = aggregate_convergence_reports(reports.values())
        finally:
            await net.stop_all()

        assert agg["nodes"] == n, agg
        assert agg["spans_total"] >= n, agg
        e2e = agg["e2e_ms"]
        assert 0.0 < e2e["p50"] <= e2e["p95"] <= e2e["max"], e2e
        assert agg["slowest_stage"] is not None, agg
        assert agg["flood"]["received"] > 0, agg
        assert agg["flood"]["hop_count_max"] >= n - 2, agg
        for stage in ("decision.route_build", "fib.program"):
            assert stage in agg["stages"], sorted(agg["stages"])
        return {
            "nodes": n,
            "spans_total": agg["spans_total"],
            "e2e_p50_ms": e2e["p50"],
            "e2e_p95_ms": e2e["p95"],
            "e2e_max_ms": e2e["max"],
            "slowest_stage": agg["slowest_stage"],
            "flood_received": agg["flood"]["received"],
            "flood_duplicate_ratio": agg["flood"]["duplicate_ratio"],
            "hop_evidence": hop_evidence,
        }

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(body())
    finally:
        loop.close()


def run_decision_backend_parity(
    my_node: str,
    publication: Publication,
    mesh: Optional[tuple],
) -> Tuple[int, int]:
    """Decision(tpu, mesh) vs Decision(cpu) on one publication; returns
    (n_unicast, n_mpls) on success, raises AssertionError on divergence.
    Creates and closes its own event loop (callers are sync entry points).
    """

    async def body():
        cpu = await decision_route_delta(my_node, publication, "cpu")
        tpu = await decision_route_delta(
            my_node, publication, "tpu", mesh=mesh
        )
        return assert_route_delta_equal(cpu, tpu)

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(body())
    finally:
        loop.close()


def run_bench_convergence(
    nodes: int = 5,
    flaps: int = 2,
    backend: str = "tpu",
    measure_exporter: bool = True,
    subscribers: int = 0,
    fleet_observer: bool = False,
    codec: str = "json",
    inproc_subscribers: int = 0,
    shared_encode: bool = True,
    stall_subscriber: bool = False,
    max_subscribers: Optional[int] = None,
    churn_keys: int = 0,
    churn_value_bytes: int = 4096,
    debounce_ms: Optional[Tuple[float, float]] = None,
    journal: bool = False,
    chaos_loss: float = 0.0,
    chaos_seed: int = 1,
) -> dict:
    """Hello-to-programmed-route percentiles from an emulator flap run —
    bench.py's second metric line (ROADMAP "relight the benchmark").

    A `nodes`-node line topology converges, then the middle link fails and
    restores `flaps` times; every event's spark→fib convergence span lands
    in the per-node monitor rings and is folded network-wide by
    `VirtualNetwork.convergence_report()` (the `breeze perf report` math).
    Returns the aggregate e2e percentiles, so DeltaPath / solver wins show
    up in the benchmark trajectory as `convergence.e2e_ms`, not just raw
    SPF/s. The daemons run the requested Decision solver backend (tpu by
    default: this is the path the delta extraction serves).

    With `subscribers` > 0 the same flap batch additionally carries N
    concurrent `subscribeKvStore` streams (spread round-robin across the
    nodes' real ctrl sockets) — bench.py's `stream_fanout_events_s` line:
    the summary gains stream_{subscribers,frames,deltas,resyncs,
    events_per_s} so delta-delivery throughput and the convergence-p95
    cost of fan-out are measured on one run, plus the per-subscriber
    frame-encode bill (`ctrl.stream.encode_ms/encode_bytes`, the
    serving-wall hypothesis meters): stream_encode_{ms_total,frames,
    bytes} and stream_encode_share — the fraction of the batch's wall
    clock the fleet spent re-encoding frames per connection
    (docs/Streaming.md).

    With `fleet_observer=True` the fleet observer (openr_tpu/fleet)
    attaches over the real ctrl sockets for the whole batch — bench.py's
    `fleet_watch_overhead_ms` line: the summary gains
    fleet_{tick_ms,scrape_ms,scrapes,ticks} so the continuous watchdog's
    per-tick cost is measured on the same run whose convergence p95 the
    detached baseline measured.

    Scale/proof knobs (docs/Streaming.md "Shared-encode fan-out"):
    `codec` picks the socket subscribers' frame codec — "json",
    "binary", or "mixed" (round-robin, the soak-round cohort shape);
    `inproc_subscribers` adds an in-process cohort per node
    (testing/fanout.py — the 100k-subscriber half the fd limit forbids
    as sockets), reported separately in the summary;
    `shared_encode=False` restores the per-subscriber re-encode path
    (before/after measurement on identical flap batches);
    `stall_subscriber=True` throttles the first socket subscriber into
    overflow→resync via the `ctrl.stream.deliver` fault point, proving
    slow-client isolation live under load; `max_subscribers` raises the
    per-node subscription cap for scale cohorts; `churn_keys` > 0
    enriches every flap wave with that many production-sized key
    originations (`churn_value_bytes` each, flooded area-wide) so the
    fan-out legs serve LSDB-sized publications instead of bare
    adjacency deltas — both A/B legs get the identical enriched
    batch; `debounce_ms=(min, max)` pins the SPF debounce window so
    A/B fan-out legs don't eat 10–250 ms of per-wave timer jitter in
    their events/s denominators.

    With `journal=True` every node records the flap batch into its
    state journal (openr_tpu/journal, in-memory ring) — bench.py's
    `journal_record_us` line: the summary gains journal_{records,
    record_us,evicted,replay_verified} so the per-record overhead and
    its convergence-p95 cost are measured on one run, and the final
    state is replay-verified against the CPU oracle on every node
    (docs/Journal.md)."""
    from openr_tpu.testing.wrapper import VirtualNetwork, wait_until

    n = max(3, nodes)
    mid = n // 2

    async def body() -> dict:
        stream_overrides: dict = {"shared_encode": shared_encode}
        if max_subscribers is not None:
            stream_overrides["max_subscribers"] = max_subscribers
        decision_overrides: dict = {"solver_backend": backend}
        if debounce_ms is not None:
            decision_overrides["debounce_min_ms"] = debounce_ms[0]
            decision_overrides["debounce_max_ms"] = debounce_ms[1]
        overrides: dict = {
            "decision_config": decision_overrides,
            "stream_config": stream_overrides,
        }
        if journal:
            overrides["journal_config"] = {"enabled": True}
        # chaos_loss > 0: the flap batch runs over a seeded lossy fabric
        # (KvStore RPC loss via testing/chaos.py; Spark stays clean so
        # adjacency churn is the flaps', not the schedule's) — bench.py's
        # `convergence_under_loss_p95_ms` line
        mesh = None
        if chaos_loss > 0.0:
            from openr_tpu.testing.chaos import ChaosLinkSpec, ChaosMesh

            mesh = ChaosMesh(seed=chaos_seed)
            mesh.set_default(
                ChaosLinkSpec(loss=chaos_loss, spark_loss=0.0)
            )
        net = VirtualNetwork(chaos=mesh)
        for i in range(n):
            net.add_node(
                f"n{i}",
                loopback_prefix=f"10.{i}.0.0/24",
                config_overrides=overrides,
            )
        await net.start_all()
        for i in range(n - 1):
            net.connect(f"n{i}", f"if{i}r", f"n{i + 1}", f"if{i + 1}l")

        counts = {"frames": 0, "deltas": 0, "resyncs": 0, "snapshots": 0}
        stalled_kinds: list = []
        sub_tasks: list = []
        sub_clients: list = []
        inproc_cohorts: list = []

        def _sub_codec(i: int) -> str:
            if codec == "mixed":
                return "binary" if i % 2 else "json"
            return codec

        async def watch(client, label, sub_codec) -> None:
            # decode=False: the watchers are throughput meters — they
            # read every frame off the socket but skip payload parsing
            # (the server's fan-out is what's being measured, and at
            # 2048 watchers on one box the consumer-side json.loads
            # otherwise dominates the wall clock of BOTH A/B legs)
            try:
                async for frame in client.subscribe(
                    "subscribeKvStore",
                    decode=False,
                    area="0",
                    client=label,
                    codec=sub_codec,
                ):
                    counts["frames"] += 1
                    kind = frame.get("type")
                    if label == "stalled":
                        stalled_kinds.append(kind)
                    if kind == "delta":
                        counts["deltas"] += 1
                    elif kind == "resync":
                        counts["resyncs"] += 1
                    elif kind == "snapshot":
                        counts["snapshots"] += 1
            except Exception:
                pass

        def read_stream_meters() -> dict:
            """Fleet-wide serving-wall meter totals (docs/Streaming.md):
            sampled before and after the flap batch so the reported
            stats cover the MEASURED WINDOW only — subscription-time
            snapshot encodes are setup cost, not fan-out serving."""
            t = {
                "encode_ms": 0.0,
                "encode_frames": 0,
                "encode_bytes": 0,
                "deliver_ms": 0.0,
                "deliver_bytes": 0,
                "deliveries": 0,
                "classes": 0,
                "class_hits": 0,
            }
            for wrapper in net.wrappers.values():
                sm = wrapper.daemon.stream_manager
                hist = sm.histograms.get("ctrl.stream.encode_ms")
                if hist is not None:
                    t["encode_ms"] += hist.sum
                    t["encode_frames"] += hist.count
                dhist = sm.histograms.get("ctrl.stream.deliver_ms")
                if dhist is not None:
                    t["deliver_ms"] += dhist.sum
                t["encode_bytes"] += sm.counters.get(
                    "ctrl.stream.encode_bytes", 0
                )
                t["deliver_bytes"] += sm.counters.get(
                    "ctrl.stream.deliver_bytes", 0
                )
                t["deliveries"] += sm.counters.get(
                    "ctrl.stream.delivered", 0
                )
                t["classes"] += sm.counters.get(
                    "ctrl.stream.encode_classes", 0
                )
                t["class_hits"] += sm.counters.get(
                    "ctrl.stream.encode_class_hits", 0
                )
            return t

        async def start_subscribers() -> None:
            from openr_tpu.ctrl.client import CtrlClient

            wrappers = list(net.wrappers.values())
            for i in range(subscribers):
                wrapper = wrappers[i % len(wrappers)]
                client = await CtrlClient(
                    "127.0.0.1", wrapper.ctrl_port
                ).connect()
                sub_clients.append(client)
                label = (
                    "stalled"
                    if (stall_subscriber and i == 0)
                    else "bench"
                )
                sub_tasks.append(
                    asyncio.get_running_loop().create_task(
                        watch(client, label, _sub_codec(i))
                    )
                )

        async def start_inproc() -> None:
            from openr_tpu.testing.fanout import InprocFanout

            wrappers = list(net.wrappers.values())
            base, extra = divmod(inproc_subscribers, len(wrappers))
            for i, wrapper in enumerate(wrappers):
                count = base + (1 if i < extra else 0)
                if not count:
                    continue
                cohort = InprocFanout(
                    wrapper.daemon, count, codec=_sub_codec(i)
                )
                cohort.attach()
                cohort.start()
                inproc_cohorts.append(cohort)

        def converged() -> bool:
            for i in range(n):
                got = set(net.wrappers[f"n{i}"].programmed_prefixes())
                want = {f"10.{j}.0.0/24" for j in range(n) if j != i}
                if not want.issubset(got):
                    return False
            return True

        def partitioned() -> bool:
            # after the mid link fails, the left side withdraws the
            # rightmost prefix (and vice versa)
            left = net.wrappers["n0"].programmed_prefixes()
            right = net.wrappers[f"n{n - 1}"].programmed_prefixes()
            return (
                f"10.{n - 1}.0.0/24" not in left
                and "10.0.0.0/24" not in right
            )

        observer = None
        injector_ctx = None
        try:
            if stall_subscriber:
                from openr_tpu.testing.faults import (
                    FaultInjector,
                    injected,
                )

                injector_ctx = injected(FaultInjector())
                inj = injector_ctx.__enter__()
                inj.arm(
                    "ctrl.stream.deliver",
                    times=None,
                    action=lambda sub: setattr(sub, "throttle_s", 0.3),
                    when=lambda sub: (
                        getattr(sub, "label", "") == "stalled"
                    ),
                )
            await wait_until(converged, timeout=60.0)
            if subscribers:
                await start_subscribers()
                # every socket subscriber must have its snapshot before
                # the flap clock starts: the initial dumps are private
                # per-subscriber encodes (setup, not fan-out serving)
                # and racing them into the measured window inflates
                # encode_share with O(subscribers) setup cost
                await wait_until(
                    lambda: counts["snapshots"] >= subscribers,
                    timeout=max(60.0, subscribers / 50.0),
                )
            if inproc_subscribers:
                # no snapshot wait: in-process subscribers register
                # directly on the manager (no initial dump rides their
                # queues — testing/fanout.py), so attach has no encode
                # cost to keep out of the window
                await start_inproc()
            if fleet_observer:
                from openr_tpu.fleet import FleetConfig, FleetObserver

                observer = FleetObserver.for_network(
                    net, config=FleetConfig(scrape_interval_s=0.2)
                )
                await observer.start()
            churn_wave = 0

            def churn() -> None:
                """`churn_keys` production-sized key originations per
                wave (flooded area-wide like any LSDB key), so the
                fan-out serves realistic publication bodies — identical
                content for both A/B legs."""
                nonlocal churn_wave
                if not churn_keys:
                    return
                churn_wave += 1
                kv = net.wrappers["n0"].daemon.kvstore
                pad = (f"wave{churn_wave}:".encode() * (
                    churn_value_bytes // 6 + 1
                ))[:churn_value_bytes]
                for k in range(churn_keys):
                    kv.set_key(
                        f"bench:churn:{k}",
                        Value(
                            version=churn_wave,
                            originator_id="n0",
                            value=pad,
                        ),
                        area="0",
                    )

            meters0 = read_stream_meters()
            t_stream0 = time.perf_counter()
            for _ in range(max(1, flaps)):
                net.fail_link(
                    f"n{mid}", f"if{mid}r", f"n{mid + 1}", f"if{mid + 1}l"
                )
                churn()
                await wait_until(partitioned, timeout=60.0)
                net.restore_link(
                    f"n{mid}", f"if{mid}r", f"n{mid + 1}", f"if{mid + 1}l"
                )
                churn()
                await wait_until(converged, timeout=60.0)
            if subscribers and not stall_subscriber:
                # the batch isn't served until every watcher has it:
                # the clock keeps running while deliveries drain, so a
                # leg that lags its subscribers pays for the lag in
                # events/s (frame counts stable over two 0.1s reads).
                # Skipped when a subscriber is deliberately stalled —
                # it trickles one frame per throttle period, so frame
                # counts never go stable on a meaningful timescale.
                stable = {"last": -1}

                def watchers_drained() -> bool:
                    now = counts["frames"]
                    done = now == stable["last"]
                    stable["last"] = now
                    return done

                await wait_until(
                    watchers_drained, timeout=60.0, interval=0.1
                )
            stream_elapsed = time.perf_counter() - t_stream0
            if subscribers or inproc_cohorts:
                # drain: deliveries race the last convergence check
                await asyncio.sleep(0.2)
            if inproc_cohorts:
                # let the pump tasks finish the backlog before reading
                # their stats (bounded wait: queues are bounded too)
                def inproc_drained() -> bool:
                    return all(
                        not sub._frames and sub._resync_at is None
                        for cohort in inproc_cohorts
                        for sub in cohort.subs
                    )

                # the backlog scales with cohort size: one CPU core
                # drains ~100k subscribers' final frames in tens of
                # seconds, so the deadline must scale with the cohort
                await wait_until(
                    inproc_drained,
                    timeout=max(30.0, inproc_subscribers / 500.0),
                )
                for cohort in inproc_cohorts:
                    await cohort.stop()
            agg = net.convergence_report()
            exporter_stats = (
                _measure_exporter_overhead(net) if measure_exporter else {}
            )
            encode_stats = {}
            if subscribers or inproc_cohorts:
                # the serving-wall meters (docs/Streaming.md): real body
                # serializations (encode_*) vs per-subscriber splice-and-
                # write work (deliver_*) vs shared-bytes reuse
                # (encode_classes/encode_class_hits), summed fleet-wide
                # and reported as WINDOW DELTAS against the pre-flap
                # baseline (meters0) so subscription-time snapshot
                # encodes never pollute the serving-wall share
                meters1 = read_stream_meters()
                ms_total = meters1["encode_ms"] - meters0["encode_ms"]
                frames = (
                    meters1["encode_frames"] - meters0["encode_frames"]
                )
                nbytes = meters1["encode_bytes"] - meters0["encode_bytes"]
                deliver_ms = meters1["deliver_ms"] - meters0["deliver_ms"]
                deliver_bytes = (
                    meters1["deliver_bytes"] - meters0["deliver_bytes"]
                )
                deliveries = meters1["deliveries"] - meters0["deliveries"]
                classes = meters1["classes"] - meters0["classes"]
                class_hits = meters1["class_hits"] - meters0["class_hits"]
                node_resyncs: dict = {}
                for name, wrapper in net.wrappers.items():
                    sm = wrapper.daemon.stream_manager
                    resyncs = sm.counters.get("ctrl.stream.resyncs", 0)
                    if resyncs:
                        node_resyncs[name] = resyncs
                encode_stats = {
                    "stream_shared_encode": shared_encode,
                    "stream_codec": codec,
                    "stream_encode_ms_total": round(ms_total, 3),
                    "stream_encode_frames": frames,
                    "stream_encode_bytes": nbytes,
                    "stream_encode_classes": classes,
                    "stream_encode_class_hits": class_hits,
                    "stream_class_hit_rate": round(
                        class_hits / (class_hits + classes), 6
                    )
                    if (class_hits + classes)
                    else 0.0,
                    "stream_deliver_ms_total": round(deliver_ms, 3),
                    "stream_deliver_bytes": deliver_bytes,
                    "stream_deliveries": deliveries,
                    "stream_node_resyncs": node_resyncs,
                    "stream_encode_us_per_frame": round(
                        ms_total / frames * 1e3, 3
                    )
                    if frames
                    else 0.0,
                    "stream_encode_share": round(
                        (ms_total / 1e3) / stream_elapsed, 6
                    )
                    if stream_elapsed > 0
                    else 0.0,
                }
                if inproc_cohorts:
                    encode_stats["stream_inproc_subscribers"] = sum(
                        c.stats["subscribers"] for c in inproc_cohorts
                    )
                    encode_stats["stream_inproc_frames"] = sum(
                        c.stats["frames"] for c in inproc_cohorts
                    )
                    encode_stats["stream_inproc_resyncs"] = sum(
                        c.stats["resyncs"] for c in inproc_cohorts
                    )
                    encode_stats["stream_inproc_bytes"] = sum(
                        c.stats["bytes"] for c in inproc_cohorts
                    )
                if stall_subscriber:
                    encode_stats["stream_stalled_kinds"] = sorted(
                        set(stalled_kinds)
                    )
            journal_stats = {}
            if journal:
                j_records = j_evicted = j_verified = 0
                rec_sum = 0.0
                rec_count = 0
                for wrapper in net.wrappers.values():
                    jr = wrapper.daemon.journal
                    j_records += jr.counters.get("journal.records", 0)
                    j_evicted += jr.counters.get("journal.evicted", 0)
                    hist = jr.histograms.get("journal.record_ms")
                    if hist is not None:
                        rec_sum += hist.sum
                        rec_count += hist.count
                    if jr.verify_replay().get("match"):
                        j_verified += 1
                journal_stats = {
                    "journal_records": j_records,
                    "journal_evicted": j_evicted,
                    # sampled guard: record_ms holds every sample_every-th
                    # record's cost, so the avg IS the per-record estimate
                    "journal_record_us": (
                        round(rec_sum / rec_count * 1e3, 3)
                        if rec_count
                        else 0.0
                    ),
                    "journal_replay_verified": j_verified,
                    "journal_nodes": len(net.wrappers),
                }
            fleet_stats = {}
            if observer is not None:
                await observer.stop()
                tick = observer.histograms.get("fleet.tick_ms")
                scrape = observer.histograms.get("fleet.scrape_ms")
                fleet_stats = {
                    "fleet_ticks": tick.count if tick else 0,
                    "fleet_tick_ms": round(tick.avg, 4) if tick else 0.0,
                    "fleet_scrape_ms": (
                        round(scrape.avg, 4) if scrape else 0.0
                    ),
                    "fleet_scrapes": observer.counters.get(
                        "fleet.scrapes", 0
                    ),
                    "fleet_findings": len(observer.findings),
                    # kind -> sorted node list, so callers can check a
                    # breach is ATTRIBUTABLE (the soak round's judge:
                    # stream_backpressure may only fire on the node
                    # hosting the deliberately stalled subscriber)
                    "fleet_findings_by_kind": {
                        kind: sorted(
                            {
                                f.node
                                for f in observer.findings
                                if f.kind == kind
                            }
                        )
                        for kind in sorted(
                            {f.kind for f in observer.findings}
                        )
                    },
                }
                observer = None
        finally:
            if injector_ctx is not None:
                injector_ctx.__exit__(None, None, None)
            if observer is not None:
                await observer.stop()
            for cohort in inproc_cohorts:
                if cohort._task is not None:
                    await cohort.stop()
            for task in sub_tasks:
                task.cancel()
            if sub_tasks:
                await asyncio.gather(*sub_tasks, return_exceptions=True)
            for client in sub_clients:
                await client.close()
            await net.stop_all()

        e2e = agg["e2e_ms"]
        stream_stats = {}
        if subscribers or encode_stats:
            stream_stats = {
                "stream_subscribers": subscribers,
                "stream_frames": counts["frames"],
                "stream_deltas": counts["deltas"],
                "stream_resyncs": counts["resyncs"],
                "stream_events_per_s": (
                    counts["deltas"] / stream_elapsed
                    if stream_elapsed > 0
                    else 0.0
                ),
                **encode_stats,
            }
        chaos_stats = {}
        if mesh is not None:
            chaos_stats = {
                "chaos_loss": chaos_loss,
                "chaos_seed": chaos_seed,
                "chaos_kv_dropped": mesh.stats.get("kv_dropped", 0),
            }
        return {
            "nodes": n,
            "flaps": max(1, flaps),
            "backend": backend,
            "spans_total": agg["spans_total"],
            "e2e_p50_ms": e2e["p50"],
            "e2e_p95_ms": e2e["p95"],
            "e2e_max_ms": e2e["max"],
            **exporter_stats,
            **stream_stats,
            **fleet_stats,
            **journal_stats,
            **chaos_stats,
        }

    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(body())
    finally:
        loop.close()
