"""Device-resident all-pairs shortest-path state for one area graph.

`ApspState` keeps one [n_pad, n_pad] distance matrix resident on device
per area (the blocked Floyd–Warshall close of the compiled-graph weight
matrix) and serves every consumer that needs arbitrary-pair distances —
LFA nexthop qualification for sources outside the solved batch, KSP
penalized-layer seeding, and TE hard-scoring — from that one matrix
instead of per-source column solves.

Discipline mirrors `_AreaSolve` (solver/tpu.py):

  - **Device residency + lazy host mirror.** The matrix stays on device
    between events; host readers go through the lazy `d` mirror and the
    copy-back is accounted in `d2h_bytes` (the device-transfer analysis
    rule's sanctioned-seam convention).
  - **Warm re-close.** A weight-change event patches the resident weight
    matrix with the changed (u, v) pair minima and re-closes only the
    block rows/columns reachable from the changed edges
    (apsp/kernels.py:_fw_seed_solver/_fw_reclose_solver). Events that
    poison the warm state — structural rebuild, overload-mask change,
    more than `_APSP_PATCH_SLOTS` increased pairs, a numpy-resident
    matrix — fall back to a cold close.
  - **Staleness guard.** `invalidate()` drops the resident matrix; the
    owning `_AreaSolve` calls it whenever its own warm solve was poisoned
    (patch overflow, cold start) and resharding/breaker trips drop the
    whole solve (and this state with it), so a stale APSP matrix can
    never serve a consumer.
  - **Supervised dispatch.** Device closes route through the solver fault
    domain when a dispatch hook is attached (SolverSupervisor
    .supervised_call via TpuSpfSolver): classified compile/runtime/
    device-loss faults feed the shared breaker and the close degrades to
    the numpy Floyd–Warshall fallback instead of failing the event.
  - **Shadow audit.** Every `audit_interval`-th close compares the
    resident matrix against the numpy FW oracle recomputed from host-side
    graph truth (mirroring the warm-state audit): a mismatch invalidates
    and cold re-closes in place — self-healing, never silently wrong.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from openr_tpu.apsp.kernels import (
    _APSP_PATCH_SLOTS,
    _fw_reclose_solver,
    _fw_seed_solver,
    _fw_solver,
    build_allow_matrix,
    build_weight_matrix,
    fw_block_shape,
    np_floyd_warshall,
)
from openr_tpu.ops.graph import CompiledGraph, _next_bucket
from openr_tpu.testing.faults import fault_point

# re-close safety margin: the restricted fixpoint stitches at least one
# old-path segment per round, so rounds beyond the block count mean a bug
# — fall back to a cold close rather than loop
_RECLOSE_ROUND_MARGIN = 4


class ApspState:
    """One resident blocked-FW APSP matrix, warm-re-closed per event."""

    def __init__(
        self,
        max_nodes: int,
        dispatch: Optional[Callable] = None,
        audit_interval: int = 0,
        warm: bool = True,
        area: str = "",
        on_refusal: Optional[Callable] = None,
    ) -> None:
        self.max_nodes = max_nodes
        # device-memory observatory (monitor/memledger.py): the resident
        # FW triple registers under this area tag; residency admission is
        # headroom-gated through the ledger's capacity model with
        # max_nodes as the fallback when no capacity source exists
        from openr_tpu.monitor.memledger import get_ledger

        self._ledger = get_ledger()
        self._mem_area = area or "apsp"
        self._mem_handle: Optional[int] = None
        self._on_refusal = on_refusal
        self.last_refusal: Optional[Dict] = None
        self._refused_version: Optional[int] = None
        # dispatch(op, primary_fn, fallback_fn) -> (result, degraded):
        # the SolverSupervisor.supervised_call signature; None = bare
        # try/except with the numpy fallback
        self._dispatch = dispatch
        self.audit_interval = audit_interval
        self.warm = warm

        # convergence/observability (decision.spf.apsp_* counters)
        self.closes = 0
        self.warm_closes = 0
        self.cold_closes = 0
        self.fallback_closes = 0  # closes served by the numpy FW fallback
        self.invalidations = 0
        self.audit_runs = 0
        self.audit_mismatches = 0
        self.reclose_rounds_last: Optional[int] = None
        self.close_ms_last: Optional[float] = None
        self.h2d_bytes = 0
        self.d2h_bytes = 0
        self.backend: Optional[str] = None  # "device" | "numpy"
        self.stale_reason: Optional[str] = None
        # counter-sync bookmarks (TpuSpfSolver._sync_apsp_counters)
        self._closes_synced = 0
        self._sync_marks: Dict[str, int] = {}

        # resident state
        self._src_ref: Optional[np.ndarray] = None
        self._version = -2
        self._n_pad = 0
        self._nb = 0
        self._bsz = 0
        self._w_host: Optional[np.ndarray] = None  # edge-array snapshot
        self._ov_host: Optional[np.ndarray] = None
        self._pair_pos: Dict[Tuple[int, int], np.ndarray] = {}
        self._d_dev = None
        self._w_dev = None
        self._allow_dev = None
        self._d_host: Optional[np.ndarray] = None
        self._closes_since_audit = 0

    # ------------------------------------------------------------------

    def enabled_for(self, graph: CompiledGraph) -> bool:
        """Dense FW residency admission. The PRIMARY gate is the memory
        ledger's predictive capacity model: the [n_pad, n_pad] triple is
        admitted only when `predict_fit` says it fits current headroom —
        a measured verdict from the same padding arithmetic the closer
        uses. The static `solver_apsp_max_nodes` cap is the FALLBACK,
        used only when no capacity source exists (the CPU backend exposes
        no memory stats). A definite no-fit is a refusal: counted,
        remembered for getSolverHealth, and surfaced through the owning
        solver as a SOLVER_CAPACITY_REFUSED sample instead of silent
        non-residency (docs/Apsp.md crossover)."""
        if graph.n <= 0:
            return False
        verdict = self._ledger.predict_fit(graph.n, "apsp", graph=graph)
        if verdict["fits"] is None:
            # no capacity source: the static node cap is the gate
            return graph.n <= self.max_nodes
        if verdict["fits"]:
            return True
        if self._refused_version != graph.version:
            # one refusal per graph snapshot: every consumer probe after
            # the first rides the remembered verdict
            self._refused_version = graph.version
            self._ledger.record_refusal(verdict)
            self.last_refusal = dict(verdict)
            if self._on_refusal is not None:
                self._on_refusal(verdict)
        return False

    def resident(self) -> bool:
        return self._d_dev is not None or self._d_host is not None

    def fresh_for(self, graph: CompiledGraph) -> bool:
        return (
            self.resident()
            and self._src_ref is graph.src
            and self._version == graph.version
        )

    def invalidate(self, reason: str) -> None:
        """Staleness guard: drop the resident matrix so the next ensure()
        cold-closes. Called by the owning solve whenever its own warm
        state was poisoned (patch overflow, cold start, resharding drops
        the solve wholesale) and by the shadow audit on a mismatch."""
        if self.resident():
            self.invalidations += 1
        self._d_dev = None
        self._d_host = None
        self._w_dev = None
        self._src_ref = None
        self._version = -2
        self.stale_reason = reason
        self._mem_register_resident()

    def _mem_register_resident(self) -> None:
        """Ledger seam: re-register the resident FW triple (d + w +
        allow) after a close, or release it when the matrix dropped
        (invalidation, numpy fallback, teardown) — staleness
        invalidation must return the ledger to its pre-close baseline."""
        self._ledger.release(self._mem_handle)
        self._mem_handle = None
        if self._d_dev is not None:
            self._mem_handle = self._ledger.register(
                self._mem_area,
                "apsp",
                layout="apsp",
                arrays=(self._d_dev, self._w_dev, self._allow_dev),
            )

    def close(self) -> None:
        """Teardown: release the ledger entry (owning solve dropped)."""
        self._ledger.release(self._mem_handle)
        self._mem_handle = None

    # ------------------------------------------------------------------

    def ensure(self, graph: CompiledGraph) -> bool:
        """Bring the resident matrix up to date with the graph snapshot;
        returns False when the graph exceeds the node cap (consumers fall
        back to their column-solve paths)."""
        if not self.enabled_for(graph):
            if self.resident():
                self.invalidate("graph_too_large")
            return False
        if self.fresh_for(graph):
            return True
        structural = (
            not self.resident()
            or self._src_ref is not graph.src
            or self._d_dev is None  # numpy-resident: no device warm base
        )
        ov_changed = not structural and not np.array_equal(
            self._ov_host, graph.overloaded
        )
        if structural or ov_changed or not self.warm:
            # an overload toggle re-masks every (i, j) pair: warm
            # invalidation would have to re-witness the whole matrix, so
            # the transit-mask change closes cold like a structural event
            self._close_cold(graph)
            return True
        changed = np.nonzero(self._w_host[: graph.e] != graph.w[: graph.e])[0]
        if not len(changed):
            self._version = graph.version  # snapshot is current, no diff
            return True
        inc, patch = self._classify_pairs(graph, changed)
        if len(inc) > _APSP_PATCH_SLOTS:
            # warm-patch overflow poisons the warm close (the same event
            # class that poisons the batch solver's warm state)
            self.invalidate("patch_overflow")
            self._close_cold(graph)
            return True
        self._close_warm(graph, inc, patch)
        return True

    # ------------------------------------------------------------------

    def _classify_pairs(self, graph: CompiledGraph, changed: np.ndarray):
        """Changed edge positions -> per-(u, v)-pair weight-minimum moves:
        (increases [(u, v, old_min)], patches [(u, v, new_min)]). Parallel
        edges collapse to the pair minimum, so an edge change only counts
        when it moves the pair's min."""
        pairs = {
            (int(graph.src[p]), int(graph.dst[p])) for p in changed
        }
        inc = []
        patch = []
        for u, v in sorted(pairs):
            pos = self._pair_pos[(u, v)]
            old = int(self._w_host[pos].min())
            new = int(graph.w[pos].min())
            if new == old:
                continue
            patch.append((u, v, new))
            if new > old:
                inc.append((u, v, old))
        return inc, patch

    def _run_close(self, op: str, primary, fallback):
        if self._dispatch is not None:
            return self._dispatch(op, primary, fallback)
        try:
            return primary(), False
        except Exception:
            return fallback(), True

    def _close_cold(self, graph: CompiledGraph, audit: bool = True) -> None:
        t0 = time.perf_counter()
        self._compile(graph)
        nb, bsz = self._nb, self._bsz

        def primary():
            # named fault seam: the supervisor's APSP fault-domain tests
            # inject compile/runtime/device-loss faults here, exactly
            # where a real XLA dispatch would raise (docs/Robustness.md)
            fault_point("solver.apsp.close", self)
            import jax.numpy as jnp

            w_np = build_weight_matrix(graph)
            allow_np = build_allow_matrix(graph.overloaded)
            w_dev = jnp.asarray(w_np)
            allow_dev = jnp.asarray(allow_np)
            self.h2d_bytes += w_np.nbytes + allow_np.nbytes
            d, probe = _fw_solver((nb, bsz))(w_dev, allow_dev)
            int(probe)  # 4-byte scalar: force completion for the timing
            return d, w_dev, allow_dev

        def fallback():
            self.fallback_closes += 1
            d_np = np_floyd_warshall(
                build_weight_matrix(graph), graph.overloaded
            )
            return d_np, None, None

        (d, w_dev, allow_dev), degraded = self._run_close(
            "apsp.close", primary, fallback
        )
        if degraded or w_dev is None:
            self._d_dev = None
            self._d_host = np.asarray(d)
            self._w_dev = None
            self._allow_dev = None
            self.backend = "numpy"
        else:
            self._d_dev = d
            self._d_host = None
            self._w_dev = w_dev
            self._allow_dev = allow_dev
            self.backend = "device"
        self._mem_register_resident()
        self._snapshot(graph)
        self.closes += 1
        self.cold_closes += 1
        self.reclose_rounds_last = None
        self.close_ms_last = (time.perf_counter() - t0) * 1e3
        self.stale_reason = None
        if audit:
            self._maybe_audit(graph)

    def _close_warm(self, graph: CompiledGraph, inc, patch) -> None:
        t0 = time.perf_counter()
        nb, bsz = self._nb, self._bsz

        def primary():
            fault_point("solver.apsp.close", self)
            import jax.numpy as jnp

            us = np.array([u for u, _, _ in patch], dtype=np.int32)
            vs = np.array([v for _, v, _ in patch], dtype=np.int32)
            vals = np.array([w for _, _, w in patch], dtype=np.int32)
            w_dev = self._w_dev.at[us, vs].set(jnp.asarray(vals))
            self.h2d_bytes += us.nbytes + vs.nbytes + vals.nbytes
            p = _next_bucket(max(len(inc), 1), minimum=8)
            iu = np.full(p, 1 << 30, dtype=np.int32)
            iv = np.zeros(p, dtype=np.int32)
            iw = np.zeros(p, dtype=np.int32)
            for i, (u, v, old) in enumerate(inc):
                iu[i], iv[i], iw[i] = u, v, old
            self.h2d_bytes += iu.nbytes + iv.nbytes + iw.nbytes
            d0, dirty, num_dirty = _fw_seed_solver((nb, bsz, p))(
                self._d_dev,
                w_dev,
                jnp.asarray(iu),
                jnp.asarray(iv),
                jnp.asarray(iw),
            )
            rounds = 0
            nd = int(num_dirty)  # 4-byte scalar read per round
            d = d0
            while nd:
                if rounds > nb + _RECLOSE_ROUND_MARGIN:
                    raise RuntimeError(
                        f"APSP re-close did not converge in {rounds} "
                        f"rounds ({nd} dirty blocks)"
                    )
                kb = min(_next_bucket(nd, minimum=1), nb)
                d, dirty, num_dirty, changed = _fw_reclose_solver(
                    (nb, bsz, kb)
                )(d, self._allow_dev, dirty)
                rounds += 1
                if int(changed) == 0:
                    break
                nd = int(num_dirty)
            return d, w_dev, rounds

        def fallback():
            self.fallback_closes += 1
            d_np = np_floyd_warshall(
                build_weight_matrix(graph), graph.overloaded
            )
            return d_np, None, None

        (d, w_dev, rounds), degraded = self._run_close(
            "apsp.close", primary, fallback
        )
        if degraded or w_dev is None:
            self._d_dev = None
            self._d_host = np.asarray(d)
            self._w_dev = None
            self.backend = "numpy"
            self.cold_closes += 1
            self.reclose_rounds_last = None
        else:
            self._d_dev = d
            self._d_host = None
            self._w_dev = w_dev
            self.backend = "device"
            self.warm_closes += 1
            self.reclose_rounds_last = rounds
        self._mem_register_resident()
        self._snapshot(graph)
        self.closes += 1
        self.close_ms_last = (time.perf_counter() - t0) * 1e3
        self.stale_reason = None
        self._maybe_audit(graph)

    # ------------------------------------------------------------------

    def _compile(self, graph: CompiledGraph) -> None:
        """(Re)derive the per-structure layout: block shape and the
        (u, v) -> edge-position index the pair-minimum patches need."""
        self._n_pad = graph.n_pad
        self._nb, self._bsz = fw_block_shape(graph.n_pad)
        if self._src_ref is not graph.src:
            pair_pos: Dict[Tuple[int, int], list] = {}
            for p in range(graph.e):
                pair_pos.setdefault(
                    (int(graph.src[p]), int(graph.dst[p])), []
                ).append(p)
            self._pair_pos = {
                k: np.asarray(v, dtype=np.int64)
                for k, v in pair_pos.items()
            }

    def _snapshot(self, graph: CompiledGraph) -> None:
        self._src_ref = graph.src
        self._version = graph.version
        self._w_host = graph.w.copy()
        self._ov_host = graph.overloaded.copy()

    # ------------------------------------------------------------------

    @property
    def d(self) -> np.ndarray:
        """Host mirror of the resident [n_pad, n_pad] matrix, fetched on
        first access after each close. An OWNED copy (np.array, not
        asarray): a CPU-backend zero-copy view would alias device memory
        the next close overwrites."""
        if self._d_host is None:
            self._d_host = np.array(self._d_dev)
            self.d2h_bytes += self._d_host.nbytes
        return self._d_host

    def row(self, i: int) -> np.ndarray:
        """One source row of the resident matrix (through the mirror: APSP
        consumers read many rows per event, so the full fetch amortizes)."""
        return self.d[i]

    # ------------------------------------------------------------------

    def _maybe_audit(self, graph: CompiledGraph) -> None:
        """Every `audit_interval`-th close, compare the resident matrix
        against the numpy FW oracle recomputed from host-side graph truth
        (the warm-state audit's APSP mirror). A mismatch invalidates and
        cold re-closes in place — the corrected matrix serves the same
        event."""
        if self.audit_interval <= 0:
            return
        self._closes_since_audit += 1
        if self._closes_since_audit < self.audit_interval:
            return
        self._closes_since_audit = 0
        self.audit_runs += 1
        ref = np_floyd_warshall(build_weight_matrix(graph), graph.overloaded)
        if np.array_equal(self.d, ref):
            return
        self.audit_mismatches += 1
        self.invalidate("audit_mismatch")
        self._close_cold(graph, audit=False)

    def health(self) -> Dict:
        """Introspection record (tests, getSolverHealth wiring)."""
        return {
            "resident": self.resident(),
            "backend": self.backend,
            "closes": self.closes,
            "warm_closes": self.warm_closes,
            "cold_closes": self.cold_closes,
            "fallback_closes": self.fallback_closes,
            "invalidations": self.invalidations,
            "reclose_rounds_last": self.reclose_rounds_last,
            "audit_runs": self.audit_runs,
            "audit_mismatches": self.audit_mismatches,
            "stale_reason": self.stale_reason,
        }
