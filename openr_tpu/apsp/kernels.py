"""Blocked (min,+) Floyd–Warshall kernels for dense all-pairs shortest paths.

The tensorized-FW formulation (PAPERS.md, arXiv:2310.03983) expresses APSP
as blocked tropical "matmuls" that ride the accelerator's matrix tiles
instead of gather/scatter: the [N, N] distance matrix is carved into
MXU-tile-sized B x B blocks (B = 128, the systolic-array edge) and one
classic three-phase sweep closes it exactly —

  for each diagonal stage k:
    1. close block (k, k) under (min,+) self-multiplication,
    2. panel updates: row panel D[k, j] <- min(D[k, j], C_kk (x) D[k, j])
       and column panel D[i, k] <- min(D[i, k], D[i, k] (x) C_kk),
    3. outer-product sweep D[i, j] <- min(D[i, j], D[i, k] (x) D[k, j]).

All arithmetic is int32 with the ops/graph.py INF = 1 << 29 sentinel:
INF + INF = 1 << 30 stays in range, and every (min,+) product clamps back
to INF, so unreachable never wraps (the same convention the batched solver
kernels in ops/spf.py follow).

Transit pruning (overloaded nodes relay nothing unless they are the source
itself, LinkState.cpp:829-836) composes with blocked FW through a LEFT
mask: every product masks its left operand's intermediate columns with the
per-source `allow` matrix (allow[i, k] = not overloaded[k] or k == i).
Shortest paths are simple under metrics >= 1, so a sub-path computed under
its own source's mask never traverses anything a composing source's mask
would forbid — the masked sweep is exact, the same argument the batched
per-source kernels rely on.

The warm **re-close** path serves weight-change events without the full
O(N^3/B^3) sweep:

  - `_fw_seed_solver` marks the rows whose old shortest-path witness may
    traverse an increased edge (the Ramalingam–Reps triangle test
    D[i, u] + w_old + D[v, j] == D[i, j], over-marking is safe), resets
    them to their direct edges, folds the new weight matrix in as an
    entrywise min, and reports which block rows are dirty.
  - `_fw_reclose_solver` runs one re-close round over ONLY the dirty
    block rows/columns: dirty block rows rebuild through every
    intermediate block, and every row relaxes through the dirty blocks as
    intermediates — a round costs O(kb · nb · B^3 · nb) against the full
    sweep's O(nb^3 · B^3), so local events pay ~ (dirty blocks / nb) of a
    cold close. Iterated to a fixpoint this is exact: at the fixpoint
    every (i, j, k) triangle is covered either by a dirty row rule, a
    dirty intermediate rule, or the old matrix's closure (which never
    moved for clean rows).

The numpy mirror `np_floyd_warshall` is the CPU fallback the supervisor's
fault domain degrades to, and the oracle the shadow audit and differential
tests compare against. It is never traced.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from openr_tpu.ops.graph import INF, CompiledGraph
from openr_tpu.utils.shape_contract import shape_contract

# MXU tile edge: blocks are B x B with B = min(128, n_pad); n_pad is a
# power of two (ops/graph.py bucket padding), so B always divides it
_FW_BLOCK = 128

# fixed warm-patch width: events increasing more (u, v) pair minima than
# this fall back to a cold close (the ApspState staleness guard)
_APSP_PATCH_SLOTS = 64


def _profile_span(name: str):
    """Named `jax.profiler.TraceAnnotation` around an APSP dispatch seam
    (same convention as ops/spf.py:profile_span): on-demand profiling
    windows label the blocked-FW dispatches; no-op-cheap otherwise."""
    from jax.profiler import TraceAnnotation

    return TraceAnnotation(name)


def fw_block_shape(n_pad: int) -> Tuple[int, int]:
    """(nb, bsz): block count and block edge for a padded node count."""
    bsz = min(_FW_BLOCK, n_pad)
    assert n_pad % bsz == 0, (n_pad, bsz)  # bucket padding: power of two
    return n_pad // bsz, bsz


def _to_blocks(x, nb: int, bsz: int):
    """[N, N] -> block-major [nb, nb, B, B]."""
    return x.reshape(nb, bsz, nb, bsz).transpose(0, 2, 1, 3)


def _from_blocks(x4, nb: int, bsz: int):
    """Block-major [nb, nb, B, B] -> [N, N]."""
    return x4.transpose(0, 2, 1, 3).reshape(nb * bsz, nb * bsz)


@shape_contract(
    "a:[B,B]:int32:inf", "b:[B,B]:int32:inf", returns="[B,B]:int32:inf"
)
def _mp(a, b):
    """(min,+) product of a [B, B] tile pair, INF-clamped.

    The tropical analog of one MXU tile matmul: out[i, j] =
    min_m (a[i, m] + b[m, j]); both operands are <= INF so the int32 sum
    never wraps and the clamp keeps unreachable at the sentinel."""
    return jnp.min(jnp.minimum(a[:, :, None] + b[None, :, :], INF), axis=1)


@functools.lru_cache(maxsize=16)
def _fw_solver(key: Tuple):
    """Cold blocked Floyd–Warshall close: key = (nb, bsz).

    (w [N, N] int32 direct-edge matrix with 0 diagonal, allow [N, N] bool
    per-source transit mask) -> (d [N, N], probe scalar). The probe scalar
    is read host-side to force completion so close timing covers device
    execution, matching the batched solver's rounds-output convention."""
    nb, bsz = key
    # log2(B) masked self-multiplications close a B x B block: each
    # squaring doubles the stitched segment count, and within-block paths
    # stitch at most B - 1 segments
    sq = max(bsz.bit_length() - 1, 1)

    def close(w, allow):
        d4 = _to_blocks(w, nb, bsz)
        a4 = _to_blocks(allow, nb, bsz)

        def stage(k, d4):
            diag = d4[k, k]
            adiag = a4[k, k]

            def sq_step(_, c):
                return jnp.minimum(c, _mp(jnp.where(adiag, c, INF), c))

            diag = jax.lax.fori_loop(0, sq, sq_step, diag)
            dmask = jnp.where(adiag, diag, INF)
            rowk = d4[k]  # [nb, B, B]
            colk = d4[:, k]
            ak = a4[:, k]
            row = jax.vmap(lambda bj: jnp.minimum(bj, _mp(dmask, bj)))(rowk)
            col = jax.vmap(
                lambda bi, ai: jnp.minimum(
                    bi, _mp(jnp.where(ai, bi, INF), diag)
                )
            )(colk, ak)
            row = row.at[k].set(diag)
            col = col.at[k].set(diag)
            colm = jnp.where(ak, col, INF)

            # outer-product sweep, one block row of the matrix per step so
            # the [nb, B, B, B] (min,+) intermediates stay bounded
            def outer_i(i, acc):
                upd = jax.vmap(lambda rj: _mp(colm[i], rj))(row)
                return acc.at[i].set(jnp.minimum(acc[i], upd))

            d4 = jax.lax.fori_loop(0, nb, outer_i, d4)
            d4 = d4.at[k, :].set(row)
            d4 = d4.at[:, k].set(col)
            return d4

        d4 = jax.lax.fori_loop(0, nb, stage, d4)
        d = _from_blocks(d4, nb, bsz)
        return d, jnp.min(d)

    fit = jax.jit(close)

    def dispatch(w, allow):
        # named profiling seam: on-demand jax.profiler windows
        # (monitor/profiling.py) show the cold close under this label
        with _profile_span(f"apsp.fw_close.{nb}x{bsz}"):
            return fit(w, allow)

    return dispatch


@functools.lru_cache(maxsize=16)
def _fw_seed_solver(key: Tuple):
    """Warm re-close seed: key = (nb, bsz, p) with p the padded
    increased-pair slot count.

    (d_prev [N, N], w_new [N, N], inc_u [p], inc_v [p], inc_w [p]) ->
    (d0 [N, N], dirty [nb] bool, num_dirty). Rows whose old shortest-path
    witness may traverse an increased (u, v) pair (old pair weight inc_w)
    reset to INF; the new weight matrix folds in as an entrywise min so
    direct edges and every decrease apply; the diagonal stays pinned at 0
    by w_new's zero diagonal. Padding slots carry u = 1 << 30 and drop via
    the in-range test. dirty marks the block rows that differ from d_prev
    (or were reset) — the re-close loop's initial work set."""
    nb, bsz, p = key

    def seed(d_prev, w_new, inc_u, inc_v, inc_w):
        n = d_prev.shape[0]

        def body(i, aff):
            u = inc_u[i]
            v = inc_v[i]
            w_old = inc_w[i]
            ok = u < n
            us = jnp.clip(u, 0, n - 1)
            vs = jnp.clip(v, 0, n - 1)
            du = jax.lax.dynamic_index_in_dim(
                d_prev, us, axis=1, keepdims=False
            )
            dv = jax.lax.dynamic_index_in_dim(
                d_prev, vs, axis=0, keepdims=False
            )
            cand = jnp.minimum(
                jnp.minimum(du[:, None] + w_old, INF) + dv[None, :], INF
            )
            hit = (cand == d_prev) & (d_prev < INF)
            return aff | (ok & jnp.any(hit, axis=1))

        aff = jax.lax.fori_loop(0, p, body, jnp.zeros((n,), jnp.bool_))
        d0 = jnp.where(aff[:, None], INF, d_prev)
        d0 = jnp.minimum(d0, w_new)
        dirty_rows = aff | jnp.any(d0 != d_prev, axis=1)
        dirty = jnp.any(dirty_rows.reshape(nb, bsz), axis=1)
        return d0, dirty, jnp.sum(dirty.astype(jnp.int32))

    fit = jax.jit(seed)

    def dispatch(d_prev, w_new, inc_u, inc_v, inc_w):
        with _profile_span(f"apsp.fw_seed.{nb}x{bsz}"):
            return fit(d_prev, w_new, inc_u, inc_v, inc_w)

    return dispatch


@functools.lru_cache(maxsize=32)
def _fw_reclose_solver(key: Tuple):
    """One warm re-close round: key = (nb, bsz, kb) with kb the padded
    dirty-block capacity (power-of-two bucket, so a handful of executables
    serve every event size).

    (d [N, N], allow [N, N] bool, dirty [nb] bool) ->
    (d_new, dirty_new [nb] bool, num_dirty, changed_blocks). The dirty
    block indices are compacted ON DEVICE (nonzero with a static size);
    rule (a) rebuilds each dirty block row through every intermediate
    block, rule (b) relaxes every row through the dirty blocks as
    intermediates. Dirty only grows (monotone), and a round that changes
    nothing certifies the fixpoint — at that point every (i, j, k)
    triangle is covered by (a) when i is dirty, by (b) when k is dirty,
    and by the previous close's untouched rows otherwise."""
    nb, bsz, kb = key

    def reclose(d, allow, dirty):
        d4 = _to_blocks(d, nb, bsz)
        a4 = _to_blocks(allow, nb, bsz)
        (blk,) = jnp.nonzero(dirty, size=kb, fill_value=nb)
        ok = blk < nb
        safe = jnp.clip(blk, 0, nb - 1)

        # (a) dirty block rows rebuilt through ALL intermediate blocks
        a_rows = d4[safe]  # [kb, nb, B, B]
        a_allow = a4[safe]

        def rebuild(ac, aac):
            def over_k(k, acc):
                left = jnp.where(aac[k], ac[k], INF)
                upd = jax.vmap(lambda bj: _mp(left, bj))(d4[k])
                return jnp.minimum(acc, upd)

            return jax.lax.fori_loop(0, nb, over_k, ac)

        rows_new = jax.vmap(rebuild)(a_rows, a_allow)
        rows_new = jnp.where(ok[:, None, None, None], rows_new, INF)
        d4 = d4.at[safe].min(rows_new)

        # (b) every row relaxes through the dirty blocks as intermediates
        def over_c(c, d4c):
            k = safe[c]
            row_k = jax.lax.dynamic_index_in_dim(
                d4c, k, axis=0, keepdims=False
            )
            col_k = jax.lax.dynamic_index_in_dim(
                d4c, k, axis=1, keepdims=False
            )
            a_k = jax.lax.dynamic_index_in_dim(a4, k, axis=1, keepdims=False)
            colm = jnp.where(a_k, col_k, INF)

            def outer_i(i, acc):
                upd = jax.vmap(lambda rj: _mp(colm[i], rj))(row_k)
                return acc.at[i].set(jnp.minimum(acc[i], upd))

            upd4 = jax.lax.fori_loop(0, nb, outer_i, d4c)
            return jax.lax.cond(ok[c], lambda: upd4, lambda: d4c)

        d4 = jax.lax.fori_loop(0, kb, over_c, d4)
        d_new = _from_blocks(d4, nb, bsz)
        changed_rows = jnp.any(d_new != d, axis=1)
        changed_blocks = jnp.any(changed_rows.reshape(nb, bsz), axis=1)
        dirty_new = dirty | changed_blocks
        return (
            d_new,
            dirty_new,
            jnp.sum(dirty_new.astype(jnp.int32)),
            jnp.sum(changed_blocks.astype(jnp.int32)),
        )

    fit = jax.jit(reclose)

    def dispatch(d, allow, dirty):
        with _profile_span(f"apsp.fw_reclose.{nb}x{bsz}"):
            return fit(d, allow, dirty)

    return dispatch


def build_weight_matrix(graph: CompiledGraph) -> np.ndarray:
    """Dense [n_pad, n_pad] int32 direct-edge matrix from the compiled
    arrays: parallel edges collapse to their pair minimum, down links stay
    at INF (they carry INF in graph.w), the diagonal is 0, and padding
    nodes are isolated (INF rows/columns) so they never perturb real
    distances."""
    n = graph.n_pad
    w = np.full((n, n), INF, dtype=np.int32)
    e = graph.e
    if e:
        np.minimum.at(w, (graph.src[:e], graph.dst[:e]), graph.w[:e])
    np.fill_diagonal(w, 0)
    return w


def build_allow_matrix(overloaded: np.ndarray) -> np.ndarray:
    """[N, N] bool per-source transit mask: allow[i, k] — source i may
    relay through k — unless k is overloaded and k is not i itself (the
    _bf_allow semantics on the all-sources batch)."""
    n = overloaded.shape[0]
    return (~overloaded)[None, :] | np.eye(n, dtype=bool)


def np_floyd_warshall(w: np.ndarray, overloaded: np.ndarray) -> np.ndarray:
    """Numpy masked Floyd–Warshall: the CPU fallback the APSP fault domain
    degrades to, and the shadow-audit / differential-test oracle. One
    vectorized rank-1 relaxation per intermediate k, int64 internally so
    the INF sums cannot wrap, clamped back to the int32 sentinel. Never
    traced (pinned out of the traced set by tests/test_analysis.py)."""
    n = w.shape[0]
    d = w.astype(np.int64).copy()
    np.fill_diagonal(d, 0)
    allow = build_allow_matrix(overloaded)
    big = np.int64(INF)
    for k in range(n):
        dk = np.where(allow[:, k], d[:, k], big)
        d = np.minimum(d, np.minimum(dk[:, None] + d[k][None, :], big))
    return d.astype(np.int32)


def apsp_compile_cache_stats() -> dict:
    """Executable-cache totals for the FW kernel factories, folded into
    `decision.spf.compile_cache_{hits,misses}` next to the batched-solver
    factories (ops/spf.py:compile_cache_stats)."""
    hits = misses = entries = 0
    for fn in (_fw_solver, _fw_seed_solver, _fw_reclose_solver):
        info = fn.cache_info()
        hits += info.hits
        misses += info.misses
        entries += info.currsize
    return {"hits": hits, "misses": misses, "entries": entries}
