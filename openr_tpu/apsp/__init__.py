"""Blocked min-plus Floyd–Warshall APSP subsystem (docs/Apsp.md).

Dense all-pairs shortest paths for small/medium areas as MXU-tile-sized
(min,+) block updates (arXiv:2310.03983), with a warm re-close path that
re-runs only the block rows/columns reachable from changed edges, a
device-resident `ApspState` following the `_AreaSolve` host-mirror/
d2h-accounting discipline, and a numpy Floyd–Warshall fallback inside the
solver fault domain.
"""

from openr_tpu.apsp.kernels import (
    apsp_compile_cache_stats,
    build_allow_matrix,
    build_weight_matrix,
    fw_block_shape,
    np_floyd_warshall,
)
from openr_tpu.apsp.state import ApspState

__all__ = [
    "ApspState",
    "apsp_compile_cache_stats",
    "build_allow_matrix",
    "build_weight_matrix",
    "fw_block_shape",
    "np_floyd_warshall",
]
