"""FibService interface + in-memory mock handler.

Reference: openr/if/Platform.thrift service FibService:116-202 (unicast +
MPLS route add/delete/sync per clientId, aliveSince from fb303 BaseService)
and openr/tests/mocks/MockNetlinkFibHandler.{h,cpp} (the fake FIB agent the
module tests program against, with per-API call counters and sync events).

All methods are coroutines: the real handler performs socket/netlink I/O and
the Fib module treats any raised exception as a failed programming attempt
(like a thrift call failure in the reference).
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List

from openr_tpu.types import IpPrefix, MplsRoute, UnicastRoute

# openr/if/Platform.thrift FibClient::OPENR
FIB_CLIENT_OPENR = 786


class PlatformError(RuntimeError):
    """openr/if/Platform.thrift PlatformError."""


class FibService:
    """Abstract route programming service (FibService thrift equivalent)."""

    async def alive_since(self) -> int:
        """Epoch seconds this agent started (fb303 BaseService aliveSince)."""
        raise NotImplementedError

    async def add_unicast_routes(
        self, client_id: int, routes: List[UnicastRoute]
    ) -> None:
        raise NotImplementedError

    async def delete_unicast_routes(
        self, client_id: int, prefixes: List[IpPrefix]
    ) -> None:
        raise NotImplementedError

    async def sync_fib(
        self, client_id: int, routes: List[UnicastRoute]
    ) -> None:
        raise NotImplementedError

    async def add_mpls_routes(
        self, client_id: int, routes: List[MplsRoute]
    ) -> None:
        raise NotImplementedError

    async def delete_mpls_routes(
        self, client_id: int, labels: List[int]
    ) -> None:
        raise NotImplementedError

    async def sync_mpls_fib(
        self, client_id: int, routes: List[MplsRoute]
    ) -> None:
        raise NotImplementedError

    async def get_route_table_by_client(
        self, client_id: int
    ) -> List[UnicastRoute]:
        raise NotImplementedError

    async def get_mpls_route_table_by_client(
        self, client_id: int
    ) -> List[MplsRoute]:
        raise NotImplementedError


class MockFibHandler(FibService):
    """In-memory FIB agent with fault injection + sync signaling.

    Mirrors MockNetlinkFibHandler: per-API counters, an event to await the
    next syncFib, and knobs to simulate agent failure/restart.
    """

    def __init__(self) -> None:
        self.unicast_routes: Dict[int, Dict[IpPrefix, UnicastRoute]] = {}
        self.mpls_routes: Dict[int, Dict[int, MplsRoute]] = {}
        self.counters: Dict[str, int] = {}
        self._alive_since = int(time.time())
        self._fail_next = 0  # raise on the next N programming calls
        self._unhealthy = False  # raise on every call until healed
        self._sync_event = asyncio.Event()
        self._mpls_sync_event = asyncio.Event()

    # -- fault injection -------------------------------------------------

    def fail_next(self, n: int = 1) -> None:
        self._fail_next += n

    def set_unhealthy(self, unhealthy: bool = True) -> None:
        self._unhealthy = unhealthy

    def restart(self) -> None:
        """Simulate agent restart: state wiped, aliveSince bumped."""
        self.unicast_routes.clear()
        self.mpls_routes.clear()
        self._alive_since += 1

    def _maybe_fail(self) -> None:
        if self._unhealthy:
            raise PlatformError("fib agent unhealthy")
        if self._fail_next > 0:
            self._fail_next -= 1
            raise PlatformError("injected fib agent failure")

    def _bump(self, name: str) -> None:
        self.counters[name] = self.counters.get(name, 0) + 1

    # -- sync signaling (MockNetlinkFibHandler::waitForSyncFib) ----------

    async def wait_for_sync_fib(self, timeout: float = 5.0) -> None:
        await asyncio.wait_for(self._sync_event.wait(), timeout)
        self._sync_event.clear()

    async def wait_for_sync_mpls_fib(self, timeout: float = 5.0) -> None:
        await asyncio.wait_for(self._mpls_sync_event.wait(), timeout)
        self._mpls_sync_event.clear()

    # -- FibService ------------------------------------------------------

    async def alive_since(self) -> int:
        self._maybe_fail()
        return self._alive_since

    async def add_unicast_routes(
        self, client_id: int, routes: List[UnicastRoute]
    ) -> None:
        self._maybe_fail()
        self._bump("add_unicast_routes")
        table = self.unicast_routes.setdefault(client_id, {})
        for route in routes:
            table[route.dest] = route

    async def delete_unicast_routes(
        self, client_id: int, prefixes: List[IpPrefix]
    ) -> None:
        self._maybe_fail()
        self._bump("delete_unicast_routes")
        table = self.unicast_routes.setdefault(client_id, {})
        for prefix in prefixes:
            table.pop(prefix, None)

    async def sync_fib(
        self, client_id: int, routes: List[UnicastRoute]
    ) -> None:
        self._maybe_fail()
        self._bump("sync_fib")
        self.unicast_routes[client_id] = {r.dest: r for r in routes}
        self._sync_event.set()

    async def add_mpls_routes(
        self, client_id: int, routes: List[MplsRoute]
    ) -> None:
        self._maybe_fail()
        self._bump("add_mpls_routes")
        table = self.mpls_routes.setdefault(client_id, {})
        for route in routes:
            table[route.top_label] = route

    async def delete_mpls_routes(
        self, client_id: int, labels: List[int]
    ) -> None:
        self._maybe_fail()
        self._bump("delete_mpls_routes")
        table = self.mpls_routes.setdefault(client_id, {})
        for label in labels:
            table.pop(label, None)

    async def sync_mpls_fib(
        self, client_id: int, routes: List[MplsRoute]
    ) -> None:
        self._maybe_fail()
        self._bump("sync_mpls_fib")
        self.mpls_routes[client_id] = {r.top_label: r for r in routes}
        self._mpls_sync_event.set()

    async def get_route_table_by_client(
        self, client_id: int
    ) -> List[UnicastRoute]:
        return list(self.unicast_routes.get(client_id, {}).values())

    async def get_mpls_route_table_by_client(
        self, client_id: int
    ) -> List[MplsRoute]:
        return list(self.mpls_routes.get(client_id, {}).values())
