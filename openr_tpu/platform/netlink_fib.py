"""Linux FIB agent: FibService implemented over the native netlink library.

Equivalent of openr/platform/NetlinkFibHandler.{h,cpp}: programs unicast +
MPLS routes into the kernel FIB tagged with openr's protocol id; syncFib
diffs the kernel's current openr-owned routes against the desired set and
applies adds/deletes (NetlinkFibHandler::syncFib semantics). Blocking
netlink transactions run on the default executor so the asyncio control
plane never stalls.

Also hosts NetlinkPublisher — the PlatformPublisher equivalent
(openr/platform/PlatformPublisher.h:33): subscribes to kernel link/addr
multicast groups and feeds link events straight into LinkMonitor.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Callable, Dict, List, Optional

from openr_tpu.nl import (
    Neighbor,
    NetlinkError,
    NetlinkSocket,
    NlNextHop,
    NlRoute,
)
from openr_tpu.nl.netlink import (
    MPLS_NONE,
    MPLS_PHP,
    MPLS_PUSH,
    MPLS_SWAP,
    RT_PROT_OPENR,
    RT_TABLE_MAIN,
)
from openr_tpu.platform.fib_service import FibService, PlatformError
from openr_tpu.types import (
    IpPrefix,
    MplsActionCode,
    MplsRoute,
    NextHop,
    UnicastRoute,
)

log = logging.getLogger(__name__)

_ACTION_TO_NL = {
    MplsActionCode.PUSH: MPLS_PUSH,
    MplsActionCode.SWAP: MPLS_SWAP,
    MplsActionCode.PHP: MPLS_PHP,
    MplsActionCode.POP_AND_LOOKUP: MPLS_PHP,
}


class NetlinkFibHandler(FibService):
    """FibService programming the Linux kernel FIB via openr_tpu.nl."""

    def __init__(
        self,
        proto: int = RT_PROT_OPENR,
        table: int = RT_TABLE_MAIN,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.proto = proto
        self.table = table
        self._loop = loop
        self._sock = NetlinkSocket()
        self._alive_since = int(time.time())
        # name -> ifindex cache for nexthop iface resolution
        self._if_index: Dict[str, int] = {}
        self._refresh_links()

    def close(self) -> None:
        self._sock.close()

    def _refresh_links(self) -> None:
        self._if_index = {
            link.name: link.ifindex for link in self._sock.get_links()
        }

    def _resolve_ifindex(self, iface: Optional[str]) -> int:
        if iface is None:
            return 0
        idx = self._if_index.get(iface)
        if idx is None:
            self._refresh_links()
            idx = self._if_index.get(iface)
        if idx is None:
            raise PlatformError(f"unknown interface {iface}")
        return idx

    def _to_nl_nexthop(self, nh: NextHop) -> NlNextHop:
        action, labels = MPLS_NONE, ()
        if nh.mpls_action is not None:
            action = _ACTION_TO_NL[nh.mpls_action.action]
            if nh.mpls_action.action == MplsActionCode.SWAP:
                labels = (nh.mpls_action.swap_label,)
            elif nh.mpls_action.action == MplsActionCode.PUSH:
                labels = tuple(nh.mpls_action.push_labels)
        # link-local or unspecified gateways program as direct routes
        via = nh.address
        if via in ("", "0.0.0.0", "::"):
            via = ""
        return NlNextHop(
            via=via,
            ifindex=self._resolve_ifindex(nh.iface),
            weight=max(1, nh.weight),
            mpls_action=action,
            labels=labels,
        )

    async def _run(self, fn: Callable, *args):
        loop = self._loop or asyncio.get_event_loop()
        try:
            return await loop.run_in_executor(None, fn, *args)
        except NetlinkError as exc:
            raise PlatformError(str(exc)) from exc

    # -- FibService ------------------------------------------------------

    async def alive_since(self) -> int:
        return self._alive_since

    async def add_unicast_routes(
        self, client_id: int, routes: List[UnicastRoute]
    ) -> None:
        def work() -> None:
            for route in routes:
                self._sock.add_unicast_route(
                    str(route.dest),
                    [self._to_nl_nexthop(nh) for nh in route.nexthops],
                    proto=self.proto,
                    table=self.table,
                )

        await self._run(work)

    async def delete_unicast_routes(
        self, client_id: int, prefixes: List[IpPrefix]
    ) -> None:
        def work() -> None:
            for prefix in prefixes:
                try:
                    self._sock.del_unicast_route(
                        str(prefix), proto=self.proto, table=self.table
                    )
                except NetlinkError as exc:
                    if "No such process" not in str(exc):  # ESRCH = gone
                        raise

        await self._run(work)

    async def sync_fib(
        self, client_id: int, routes: List[UnicastRoute]
    ) -> None:
        """Diff-based full sync (NetlinkFibHandler::syncFib)."""

        def work() -> None:
            desired = {str(r.dest): r for r in routes}
            current = {
                r.dest: r
                for r in self._sock.get_routes(
                    family=0, proto=self.proto, table=self.table
                )
            }
            for dest in current:
                if dest not in desired:
                    self._sock.del_unicast_route(
                        dest, proto=self.proto, table=self.table
                    )
            for dest, route in desired.items():
                self._sock.add_unicast_route(
                    dest,
                    [self._to_nl_nexthop(nh) for nh in route.nexthops],
                    proto=self.proto,
                    table=self.table,
                )

        await self._run(work)

    async def add_mpls_routes(
        self, client_id: int, routes: List[MplsRoute]
    ) -> None:
        def work() -> None:
            for route in routes:
                self._sock.add_mpls_route(
                    route.top_label,
                    [self._to_nl_nexthop(nh) for nh in route.nexthops],
                )

        await self._run(work)

    async def delete_mpls_routes(
        self, client_id: int, labels: List[int]
    ) -> None:
        def work() -> None:
            for label in labels:
                try:
                    self._sock.del_mpls_route(label)
                except NetlinkError as exc:
                    if "No such process" not in str(exc):
                        raise

        await self._run(work)

    async def sync_mpls_fib(
        self, client_id: int, routes: List[MplsRoute]
    ) -> None:
        def work() -> None:
            desired = {r.top_label: r for r in routes}
            current = self._sock.get_routes(
                family=28, proto=0, table=0  # AF_MPLS
            )
            for r in current:
                if not r.dest.startswith("mpls:"):
                    continue
                label = int(r.dest[5:])
                if label not in desired:
                    self._sock.del_mpls_route(label)
            for label, route in desired.items():
                self._sock.add_mpls_route(
                    label, [self._to_nl_nexthop(nh) for nh in route.nexthops]
                )

        await self._run(work)

    async def get_route_table_by_client(
        self, client_id: int
    ) -> List[UnicastRoute]:
        def work() -> List[NlRoute]:
            return self._sock.get_routes(
                family=0, proto=self.proto, table=self.table
            )

        nl_routes = await self._run(work)
        index_to_name = {v: k for k, v in self._if_index.items()}
        out: List[UnicastRoute] = []
        for r in nl_routes:
            nexthops = tuple(
                NextHop(
                    address=nh.via,
                    iface=index_to_name.get(nh.ifindex),
                    weight=nh.weight,
                )
                for nh in r.nexthops
            )
            out.append(UnicastRoute(IpPrefix(r.dest), nexthops))
        return out

    async def get_neighbors(self, family: int = 0) -> List[Neighbor]:
        """Kernel neighbor (ARP/NDP) table, the SystemService-side dump the
        reference exposes as getAllNeighbors."""
        return await self._run(self._sock.get_neighbors, family)

    async def get_mpls_route_table_by_client(
        self, client_id: int
    ) -> List[MplsRoute]:
        def work() -> List[NlRoute]:
            return self._sock.get_routes(family=28, proto=0, table=0)

        nl_routes = await self._run(work)
        out: List[MplsRoute] = []
        for r in nl_routes:
            if not r.dest.startswith("mpls:"):
                continue
            nexthops = tuple(
                NextHop(address=nh.via, weight=nh.weight)
                for nh in r.nexthops
            )
            out.append(MplsRoute(int(r.dest[5:]), nexthops))
        return out


class NetlinkPublisher:
    """Kernel link/addr event pump (PlatformPublisher equivalent).

    Subscribes the native socket to rtnetlink multicast groups and invokes
    `on_link(ifname, is_up)` / `on_addr(ifindex, addr, prefixlen, added)` /
    `on_neighbor(ifindex, dest, lladdr, is_reachable)` callbacks from the
    asyncio loop — LinkMonitor plugs its update_interface here (the
    reference routes these through a ZMQ PUB socket; in-process callbacks
    replace that hop; the neighbor feed mirrors
    NetlinkProtocolSocket::setNeighborEventCB).
    """

    def __init__(
        self,
        on_link: Callable[[str, bool], None],
        on_addr: Optional[Callable[[int, str, int, bool], None]] = None,
        on_neighbor: Optional[Callable[[int, str, str, bool], None]] = None,
        loop: Optional[asyncio.AbstractEventLoop] = None,
    ) -> None:
        self.on_link = on_link
        self.on_addr = on_addr
        self.on_neighbor = on_neighbor
        self._loop = loop
        self._sock = NetlinkSocket()
        self._fd: Optional[int] = None

    def start(self) -> None:
        self._fd = self._sock.subscribe()
        loop = self._loop or asyncio.get_event_loop()
        loop.add_reader(self._fd, self._drain)

    def stop(self) -> None:
        if self._fd is not None:
            loop = self._loop or asyncio.get_event_loop()
            loop.remove_reader(self._fd)
            self._fd = None
        self._sock.close()

    def _drain(self) -> None:
        while True:
            try:
                ev = self._sock.next_event()
            except NetlinkError:
                log.exception("netlink event read failed")
                return
            if ev is None:
                return
            kind, ifindex, up, name, addr, prefixlen, _state, lladdr = ev
            if kind == 1 and name:
                self.on_link(name, up)
            elif kind == 2 and self.on_addr is not None:
                self.on_addr(ifindex, addr, prefixlen, up)
            elif kind == 4 and self.on_neighbor is not None:
                self.on_neighbor(ifindex, addr, lladdr, up)
