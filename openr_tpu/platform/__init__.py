"""Platform layer: route-programming service interface + implementations.

Equivalent of openr/platform/ + the FibService thrift interface
(openr/if/Platform.thrift:116-202). The real Linux backend programs routes
through the native netlink library (openr_tpu/nl); tests use MockFibHandler
(equivalent of openr/tests/mocks/MockNetlinkFibHandler.{h,cpp}).
"""

from openr_tpu.platform.fib_service import (
    FIB_CLIENT_OPENR,
    FibService,
    MockFibHandler,
    PlatformError,
)
from openr_tpu.platform.netlink_fib import NetlinkFibHandler, NetlinkPublisher
from openr_tpu.platform.remote import RemoteFibService, spawn_agent

__all__ = [
    "FIB_CLIENT_OPENR",
    "FibService",
    "MockFibHandler",
    "NetlinkFibHandler",
    "NetlinkPublisher",
    "PlatformError",
    "RemoteFibService",
    "spawn_agent",
]
